"""TPU fleet scheduler: gang admission, fair-share queueing, idle
preemption (ISSUE 5).

Three layers, least pure on top:

- :mod:`kubeflow_tpu.scheduler.fleet` — node-pool inventory + chip
  ledger (pure; invariant: admitted never exceeds capacity, gangs are
  all-or-nothing);
- :mod:`kubeflow_tpu.scheduler.policy` — deterministic arbitration
  (priority classes, DRF fair share on chips, aging, preemption);
- :mod:`kubeflow_tpu.scheduler.runtime` — the async admission point the
  notebook controller's capacity stage consults, with tracing, metrics,
  Events and ``/debug/scheduler``.

Kill switch: ``KFTPU_SCHEDULER=off`` (see :func:`scheduler_enabled`)
restores the pre-scheduler behavior — the capacity stage goes straight
to queued provisioning. With the scheduler on but no fleet configured,
admission is a transparent pass-through (also today's behavior), so the
subsystem only bites once an operator declares or auto-infers a fleet.
"""

from __future__ import annotations

import os

from kubeflow_tpu.scheduler.fleet import (  # noqa: F401
    Allocation,
    ChipLedger,
    Fleet,
    FleetConfigError,
    LedgerError,
    NodePool,
)
from kubeflow_tpu.scheduler.policy import (  # noqa: F401
    GangRequest,
    PolicyConfig,
    PolicyQueue,
    ScheduleResult,
)
from kubeflow_tpu.scheduler.elastic import (  # noqa: F401
    DefragMove,
    ElasticConfig,
    IntentBook,
    ScaleUpIntent,
    defrag_enabled,
    elastic_enabled,
)
from kubeflow_tpu.scheduler.runtime import (  # noqa: F401
    Admission,
    SchedulerOptions,
    TpuFleetScheduler,
    parse_priority,
)


SCHEDULER_ENV = "KFTPU_SCHEDULER"


def scheduler_enabled() -> bool:
    """The ``KFTPU_SCHEDULER`` kill switch: anything but off/false/0/no
    leaves the scheduler on (it is inert until a fleet is configured)."""
    return os.environ.get(SCHEDULER_ENV, "on").strip().lower() not in (
        "off", "false", "0", "no", "disabled",
    )
