"""SubjectAccessReview evaluation for the fake apiserver.

envtest delegates SARs to a real kube-apiserver; FakeKube needs its own
evaluator so the web apps' SarAuthorizer works against the RoleBindings
the profile controller materializes (reference authz flow:
crud_backend/authz.py SAR → RBAC). Registration is an admission mutator:
a created SubjectAccessReview gets ``status.allowed`` filled in before it
is stored, exactly like the apiserver's synchronous SAR semantics.

Verb model (the subset the web apps use): ``kubeflow-view`` grants
get/list/watch; ``kubeflow-edit`` and ``kubeflow-admin`` grant everything.
"""

from __future__ import annotations

from kubeflow_tpu.runtime.objects import deep_get

READ_VERBS = {"get", "list", "watch"}
EDIT_ROLES = {"kubeflow-edit", "kubeflow-admin"}
VIEW_ROLES = {"kubeflow-view"}


def register_sar_evaluator(kube, *, cluster_admins: set[str] | None = None) -> None:
    admins = cluster_admins or set()

    async def evaluate(sar: dict, info: dict) -> None:
        if info.get("operation") != "CREATE":
            return
        spec = sar.get("spec") or {}
        user = spec.get("user") or ""
        attrs = spec.get("resourceAttributes") or {}
        verb = attrs.get("verb") or "get"
        ns = attrs.get("namespace")
        sar["status"] = {
            "allowed": await _allowed(kube, admins, user, verb, ns)
        }

    kube.add_mutator("SubjectAccessReview", evaluate)


async def _allowed(kube, admins: set[str], user: str, verb: str,
                   ns: str | None) -> bool:
    if user in admins:
        return True
    if not ns:
        return False
    # Profile owner of the namespace: full access (the profile controller
    # also materializes the admin RoleBinding, but owner-allow keeps the
    # window before reconcile finishes from 403ing the owner's first load).
    profile = await kube.get_or_none("Profile", ns)
    if profile is not None:
        owner = deep_get(profile, "spec", "owner", default={}) or {}
        if owner.get("name") == user:
            return True
    # RoleBindings in the namespace (KFAM contributor bindings + the
    # profile controller's owner binding).
    for rb in await kube.list("RoleBinding", ns):
        if not any(
            s.get("kind", "User") == "User" and s.get("name") == user
            for s in rb.get("subjects") or []
        ):
            continue
        role = deep_get(rb, "roleRef", "name", default="")
        if role in EDIT_ROLES:
            return True
        if role in VIEW_ROLES and verb in READ_VERBS:
            return True
    return False
