"""Recursive-descent parser for the frontend JS subset → tuple AST.

Anything outside the subset fails loudly at parse time — a frontend change
that starts using an unsupported construct breaks CI instead of silently
skipping execution.
"""

from __future__ import annotations

from kubeflow_tpu.testing.jsrt.lexer import tokenize

ASSIGN_OPS = {"=", "+=", "-=", "*=", "/=", "%=", "&=", "|=", "^=", "**="}

# Binary precedence (higher binds tighter). ?? sits at the ||/&& tier
# (the spec forbids unparenthesized mixing; we accept it, || first).
BINARY = {
    "??": 1, "||": 1, "&&": 2,
    "|": 3, "^": 4, "&": 5,
    "==": 6, "!=": 6, "===": 6, "!==": 6,
    "<": 7, ">": 7, "<=": 7, ">=": 7, "instanceof": 7, "in": 7,
    "<<": 8, ">>": 8,
    "+": 9, "-": 9,
    "*": 10, "/": 10, "%": 10,
    "**": 11,  # right-associative (handled in binary())
}


class ParseError(SyntaxError):
    pass


class Parser:
    def __init__(self, tokens: list[tuple], filename: str = "<js>"):
        self.toks = tokens
        self.i = 0
        self.filename = filename

    # ---- token plumbing --------------------------------------------------------

    def peek(self, offset: int = 0) -> tuple:
        return self.toks[min(self.i + offset, len(self.toks) - 1)]

    def next(self) -> tuple:
        tok = self.toks[self.i]
        self.i += 1
        return tok

    def at(self, typ: str, val=None, offset: int = 0) -> bool:
        t, v, _ = self.peek(offset)
        return t == typ and (val is None or v == val)

    def eat(self, typ: str, val=None) -> bool:
        if self.at(typ, val):
            self.i += 1
            return True
        return False

    def expect(self, typ: str, val=None) -> tuple:
        if not self.at(typ, val):
            t, v, line = self.peek()
            raise ParseError(
                f"{self.filename}:{line}: expected {val or typ}, got {v!r}")
        return self.next()

    def error(self, msg: str) -> ParseError:
        _, v, line = self.peek()
        return ParseError(f"{self.filename}:{line}: {msg} (at {v!r})")

    # ---- program ---------------------------------------------------------------

    def parse_program(self) -> list:
        stmts = []
        while not self.at("eof"):
            stmts.append(self.statement())
        return stmts

    # ---- statements ------------------------------------------------------------

    def statement(self):
        if self.eat("punct", ";"):
            return ("empty",)
        if self.at("punct", "{"):
            return self.block()
        if self.at("keyword", "var") or self.at("keyword", "let") or \
                self.at("keyword", "const"):
            stmt = self.var_decl()
            self.semi()
            return stmt
        if self.at("keyword", "async") and self.at("keyword", "function", 1):
            self.next()
            return self.func_decl(is_async=True)
        if self.at("keyword", "function"):
            return self.func_decl(is_async=False)
        if self.eat("keyword", "return"):
            if self.at("punct", ";") or self.at("punct", "}") or self.at("eof"):
                expr = None
            else:
                expr = self.expression()
            self.semi()
            return ("return", expr)
        if self.eat("keyword", "if"):
            self.expect("punct", "(")
            cond = self.expression()
            self.expect("punct", ")")
            then = self.statement()
            other = self.statement() if self.eat("keyword", "else") else None
            return ("if", cond, then, other)
        if self.at("keyword", "for"):
            return self.for_stmt()
        if self.eat("keyword", "while"):
            self.expect("punct", "(")
            cond = self.expression()
            self.expect("punct", ")")
            return ("while", cond, self.statement())
        if self.eat("keyword", "do"):
            body = self.statement()
            self.expect("keyword", "while")
            self.expect("punct", "(")
            cond = self.expression()
            self.expect("punct", ")")
            self.semi()
            return ("dowhile", body, cond)
        if self.eat("keyword", "try"):
            block = self.block()
            param = catch_block = final = None
            if self.eat("keyword", "catch"):
                if self.eat("punct", "("):
                    param = self.pattern()
                    self.expect("punct", ")")
                catch_block = self.block()
            if self.eat("keyword", "finally"):
                final = self.block()
            return ("try", block, param, catch_block, final)
        if self.eat("keyword", "throw"):
            expr = self.expression()
            self.semi()
            return ("throw", expr)
        if self.eat("keyword", "break"):
            self.semi()
            return ("break",)
        if self.eat("keyword", "continue"):
            self.semi()
            return ("continue",)
        if self.eat("keyword", "switch"):
            self.expect("punct", "(")
            disc = self.expression()
            self.expect("punct", ")")
            self.expect("punct", "{")
            cases = []
            while not self.eat("punct", "}"):
                if self.eat("keyword", "case"):
                    test = self.expression()
                else:
                    self.expect("keyword", "default")
                    test = None
                self.expect("punct", ":")
                body = []
                while not (self.at("keyword", "case") or
                           self.at("keyword", "default") or
                           self.at("punct", "}")):
                    body.append(self.statement())
                cases.append((test, body))
            return ("switch", disc, cases)
        expr = self.expression()
        self.semi()
        return ("expr_stmt", expr)

    def semi(self) -> None:
        """Semicolons required except before '}' / EOF (the shipped JS is
        prettier-formatted; full ASI is out of subset)."""
        if self.eat("punct", ";"):
            return
        if self.at("punct", "}") or self.at("eof"):
            return
        raise self.error("missing semicolon")

    def block(self):
        self.expect("punct", "{")
        stmts = []
        while not self.eat("punct", "}"):
            stmts.append(self.statement())
        return ("block", stmts)

    def var_decl(self):
        kind = self.next()[1]
        decls = []
        while True:
            pat = self.pattern()
            init = self.assignment() if self.eat("punct", "=") else None
            decls.append((pat, init))
            if not self.eat("punct", ","):
                break
        return ("var", kind, decls)

    def func_decl(self, is_async: bool):
        self.expect("keyword", "function")
        name = self.ident_name()
        params, rest = self.param_list()
        body = self.block()
        return ("func_decl", name, params, rest, body, is_async)

    def for_stmt(self):
        self.expect("keyword", "for")
        self.expect("punct", "(")
        # for (const x of y) / for (const [k, v] of y) / for (x in y)
        if self.at("keyword", "var") or self.at("keyword", "let") or \
                self.at("keyword", "const"):
            kind = self.next()[1]
            pat = self.pattern()
            if self.eat("keyword", "of"):
                it = self.assignment()
                self.expect("punct", ")")
                return ("forof", kind, pat, it, self.statement())
            if self.eat("keyword", "in"):
                obj = self.assignment()
                self.expect("punct", ")")
                return ("forin", kind, pat, obj, self.statement())
            init = self.assignment() if self.eat("punct", "=") else None
            decls = [(pat, init)]
            while self.eat("punct", ","):
                p2 = self.pattern()
                i2 = self.assignment() if self.eat("punct", "=") else None
                decls.append((p2, i2))
            init_node = ("var", kind, decls)
        elif self.at("punct", ";"):
            init_node = None
        else:
            init_node = ("expr_stmt", self.expression())
        self.expect("punct", ";")
        cond = None if self.at("punct", ";") else self.expression()
        self.expect("punct", ";")
        update = None if self.at("punct", ")") else self.expression()
        self.expect("punct", ")")
        return ("for", init_node, cond, update, self.statement())

    # ---- patterns (destructuring) ----------------------------------------------

    def ident_name(self) -> str:
        t, v, line = self.peek()
        # Contextual keywords usable as identifiers/property names.
        if t == "ident" or (t == "keyword" and v in (
                "get", "set", "of", "async", "undefined")):
            self.next()
            return v
        raise self.error("expected identifier")

    def pattern(self):
        if self.at("punct", "["):
            return self.array_pattern()
        if self.at("punct", "{"):
            return self.object_pattern()
        return ("pid", self.ident_name())

    def array_pattern(self):
        self.expect("punct", "[")
        elems: list = []
        rest = None
        while not self.at("punct", "]"):
            if self.eat("punct", ","):
                elems.append(None)  # hole: [, v]
                continue
            if self.eat("punct", "..."):
                rest = self.pattern()
                break
            pat = self.pattern()
            default = self.assignment() if self.eat("punct", "=") else None
            elems.append((pat, default))
            if not self.at("punct", "]"):
                self.expect("punct", ",")
        self.expect("punct", "]")
        return ("parr", elems, rest)

    def object_pattern(self):
        self.expect("punct", "{")
        props: list = []
        rest = None
        while not self.at("punct", "}"):
            if self.eat("punct", "..."):
                rest = self.pattern()
                break
            key = self.prop_name()
            if self.eat("punct", ":"):
                target = self.pattern()
            else:
                target = ("pid", key)
            default = self.assignment() if self.eat("punct", "=") else None
            props.append((key, target, default))
            if not self.at("punct", "}"):
                self.expect("punct", ",")
        self.expect("punct", "}")
        return ("pobj", props, rest)

    def prop_name(self) -> str:
        t, v, _ = self.peek()
        if t == "str":
            self.next()
            return v
        if t == "num":
            self.next()
            return _num_key(v)
        if t in ("ident", "keyword"):
            self.next()
            return v
        raise self.error("expected property name")

    # ---- params ----------------------------------------------------------------

    def param_list(self):
        self.expect("punct", "(")
        params: list = []
        rest = None
        while not self.at("punct", ")"):
            if self.eat("punct", "..."):
                rest = self.ident_name()
                break
            pat = self.pattern()
            default = self.assignment() if self.eat("punct", "=") else None
            params.append((pat, default))
            if not self.at("punct", ")"):
                self.expect("punct", ",")
        self.expect("punct", ")")
        return params, rest

    # ---- expressions -----------------------------------------------------------

    def expression(self):
        expr = self.assignment()
        if self.at("punct", ","):
            exprs = [expr]
            while self.eat("punct", ","):
                exprs.append(self.assignment())
            return ("seq", exprs)
        return expr

    def assignment(self):
        arrow = self.try_arrow()
        if arrow is not None:
            return arrow
        left = self.conditional()
        t, v, _ = self.peek()
        if t == "punct" and v in ASSIGN_OPS:
            self.next()
            right = self.assignment()
            return ("assign", v, left, right)
        return left

    def try_arrow(self):
        """Backtracking arrow detection: [async] ident => …, or
        [async] ( params ) => …"""
        start = self.i
        is_async = False
        if self.at("keyword", "async") and not self.at("punct", "(", 1) and \
                (self.at("ident", None, 1)):
            # async x => …
            self.next()
            is_async = True
        elif self.at("keyword", "async") and self.at("punct", "(", 1):
            save = self.i
            self.next()
            if self._scan_parens_then_arrow():
                is_async = True
            else:
                self.i = start
                return None
            self.i = save + 1  # position at "("
            params, rest = self.param_list()
            self.expect("punct", "=>")
            return self.arrow_tail(params, rest, is_async)
        if self.at("ident") and self.at("punct", "=>", 1):
            name = self.ident_name()
            self.expect("punct", "=>")
            return self.arrow_tail([(("pid", name), None)], None, is_async)
        if is_async:  # async ident but no arrow — back out
            self.i = start
            return None
        if self.at("punct", "("):
            if not self._scan_parens_then_arrow():
                return None
            params, rest = self.param_list()
            self.expect("punct", "=>")
            return self.arrow_tail(params, rest, False)
        return None

    def _scan_parens_then_arrow(self) -> bool:
        """From a '(' token, check whether the matching ')' is followed by
        '=>' (pure lookahead, no state change)."""
        j = self.i
        depth = 0
        while j < len(self.toks):
            t, v, _ = self.toks[j]
            if t == "punct" and v in ("(", "[", "{"):
                depth += 1
            elif t == "punct" and v in (")", "]", "}"):
                depth -= 1
                if depth == 0:
                    nt, nv, _ = self.toks[j + 1] if j + 1 < len(self.toks) \
                        else ("eof", None, 0)
                    return nt == "punct" and nv == "=>"
            elif t == "eof":
                return False
            j += 1
        return False

    def arrow_tail(self, params, rest, is_async: bool):
        if self.at("punct", "{"):
            return ("arrow", params, rest, self.block(), False, is_async)
        return ("arrow", params, rest, self.assignment(), True, is_async)

    def conditional(self):
        cond = self.binary(1)
        if self.eat("punct", "?"):
            a = self.assignment()
            self.expect("punct", ":")
            b = self.assignment()
            return ("cond", cond, a, b)
        return cond

    def binary(self, min_prec: int):
        left = self.unary()
        while True:
            t, v, _ = self.peek()
            op = v if (t == "punct" or (t == "keyword" and v in ("instanceof", "in"))) else None
            prec = BINARY.get(op)
            if prec is None or prec < min_prec:
                return left
            self.next()
            right = self.binary(prec if op == "**" else prec + 1)
            left = ("logic" if op in ("&&", "||", "??") else "binop",
                    op, left, right)

    def unary(self):
        t, v, _ = self.peek()
        if t == "punct" and v in ("!", "-", "+", "~"):
            self.next()
            return ("unary", v, self.unary())
        if t == "punct" and v in ("++", "--"):
            self.next()
            return ("update", v, True, self.unary())
        if t == "keyword" and v in ("typeof", "delete", "void"):
            self.next()
            return ("unary", v, self.unary())
        if t == "keyword" and v == "await":
            self.next()
            return ("await", self.unary())
        return self.postfix()

    def postfix(self):
        expr = self.call_member(self.primary())
        t, v, _ = self.peek()
        if t == "punct" and v in ("++", "--"):
            self.next()
            return ("update", v, False, expr)
        return expr

    def call_member(self, expr):
        # Optional links (?.) mark the whole chain: one nullish base
        # short-circuits the REST of the chain (spec OptionalExpression),
        # which the interpreter implements by unwinding to the optchain
        # wrapper emitted here.
        has_opt = False
        while True:
            if self.eat("punct", "?."):
                has_opt = True
                if self.at("punct", "("):
                    expr = ("optcall", expr, self.arguments())
                elif self.eat("punct", "["):
                    idx = self.expression()
                    self.expect("punct", "]")
                    expr = ("optindex", expr, idx)
                else:
                    expr = ("optmember", expr, self.prop_name())
            elif self.eat("punct", "."):
                expr = ("member", expr, self.prop_name())
            elif self.at("punct", "["):
                self.next()
                idx = self.expression()
                self.expect("punct", "]")
                expr = ("index", expr, idx)
            elif self.at("punct", "("):
                expr = ("call", expr, self.arguments())
            else:
                return ("optchain", expr) if has_opt else expr

    def arguments(self):
        self.expect("punct", "(")
        args = []
        while not self.at("punct", ")"):
            if self.eat("punct", "..."):
                args.append(("spread", self.assignment()))
            else:
                args.append(self.assignment())
            if not self.at("punct", ")"):
                self.expect("punct", ",")
        self.expect("punct", ")")
        return args

    def primary(self):
        t, v, line = self.peek()
        if t == "num":
            self.next()
            return ("num", v)
        if t == "str":
            self.next()
            return ("str", v)
        if t == "template":
            self.next()
            parts = []
            for kind, payload in v:
                if kind == "str":
                    parts.append(("str", payload))
                else:
                    parts.append(("expr", Parser(payload, self.filename).expression()))
            return ("template", parts)
        if t == "regex":
            self.next()
            return ("regex", v[0], v[1])
        if t == "ident":
            self.next()
            return ("ident", v)
        if t == "keyword":
            if v == "this":
                self.next()
                return ("this",)
            if v == "null":
                self.next()
                return ("null",)
            if v == "undefined":
                self.next()
                return ("undef",)
            if v in ("true", "false"):
                self.next()
                return ("bool", v == "true")
            if v == "new":
                self.next()
                callee = self.call_member_no_call(self.primary())
                args = self.arguments() if self.at("punct", "(") else []
                return self.call_member(("new", callee, args))
            if v == "function":
                return self.func_expr(is_async=False)
            if v == "async" and self.at("keyword", "function", 1):
                self.next()
                return self.func_expr(is_async=True)
            if v in ("get", "set", "of", "async", "undefined"):
                # contextual keyword as plain identifier
                self.next()
                return ("ident", v)
        if t == "punct" and v == "(":
            self.next()
            expr = self.expression()
            self.expect("punct", ")")
            return expr
        if t == "punct" and v == "[":
            return self.array_literal()
        if t == "punct" and v == "{":
            return self.object_literal()
        raise self.error("unexpected token")

    def call_member_no_call(self, expr):
        """Member chain without calls — `new a.b.C(...)` binds the
        arguments to the constructor, not to `a.b`."""
        while True:
            if self.eat("punct", "."):
                expr = ("member", expr, self.prop_name())
            elif self.at("punct", "["):
                self.next()
                idx = self.expression()
                self.expect("punct", "]")
                expr = ("index", expr, idx)
            else:
                return expr

    def func_expr(self, is_async: bool):
        self.expect("keyword", "function")
        name = None
        if self.at("ident"):
            name = self.ident_name()
        params, rest = self.param_list()
        body = self.block()
        return ("func", name, params, rest, body, is_async)

    def array_literal(self):
        self.expect("punct", "[")
        elems = []
        while not self.at("punct", "]"):
            if self.at("punct", ","):
                self.next()
                elems.append(("hole",))
                continue
            if self.eat("punct", "..."):
                elems.append(("spread", self.assignment()))
            else:
                elems.append(self.assignment())
            if not self.at("punct", "]"):
                self.expect("punct", ",")
        self.expect("punct", "]")
        return ("array", elems)

    def object_literal(self):
        self.expect("punct", "{")
        props = []
        while not self.at("punct", "}"):
            if self.eat("punct", "..."):
                props.append(("spread", self.assignment()))
            else:
                props.append(self.object_prop())
            if not self.at("punct", "}"):
                self.expect("punct", ",")
        self.expect("punct", "}")
        return ("object", props)

    def object_prop(self):
        t, v, _ = self.peek()
        # get name() {} / set name(v) {}
        if t == "keyword" and v in ("get", "set") and not (
                self.at("punct", ":", 1) or self.at("punct", ",", 1) or
                self.at("punct", "(", 1) or self.at("punct", "}", 1)):
            self.next()
            key = self.prop_name()
            params, rest = self.param_list()
            body = self.block()
            if v == "get":
                return ("getter", key, body)
            return ("setter", key, params[0][0], body)
        is_async = False
        if t == "keyword" and v == "async" and not (
                self.at("punct", ":", 1) or self.at("punct", ",", 1) or
                self.at("punct", "(", 1) or self.at("punct", "}", 1)):
            self.next()
            is_async = True
        key = self.prop_name()
        if self.at("punct", "("):  # method shorthand
            params, rest = self.param_list()
            body = self.block()
            return ("method", key, params, rest, body, is_async)
        if self.eat("punct", ":"):
            return ("prop", key, self.assignment())
        return ("shorthand", key)


def _num_key(v: float) -> str:
    return str(int(v)) if float(v).is_integer() else repr(v)


def parse(src: str, filename: str = "<js>"):
    return Parser(tokenize(src, filename), filename).parse_program()
