"""Tree-walking evaluator + standard library for the frontend JS subset.

Value mapping: JS undefined/null are singletons; numbers are Python
floats; strings/bools map natively; objects/arrays/functions are the
classes below. Host integration happens through ``JSObject`` subclasses
overriding ``js_get_prop``/``js_set_prop`` (the DOM does this) and through
``HostFunction`` wrapping Python callables.

``await`` semantics: spec-faithful suspension. Each in-flight async
function body runs on a cooperative carrier thread (``_AsyncBody``) with
a strict one-at-a-time handoff: at ``await`` the body parks, schedules
its continuation as a real promise-reaction microtask, and control
returns to the caller — so ``await`` always defers at least one
microtask turn and interleaves exactly like a browser's event loop (the
round-4 differential battery pinned the old run-to-completion model as
divergent: ``async-await-sequencing``/``settimeout-zero-after-
microtasks`` in ci/jsrt_differential/corpus.json). Only one thread ever
executes JS at a time, enforced by event handoff — there is no
concurrency, just continuations carried by parked threads.

Top-level ``await`` (outside any async function) keeps the old
synchronous drain: run microtasks (and the host's I/O pump) until the
promise settles, raising JSDeadlock for promises only a future host
event can settle.
"""

from __future__ import annotations

import json as _json
import math
import re as _re
import threading as _threading
import time as _time
from collections import deque

from kubeflow_tpu.testing.jsrt.jsparser import parse


class Undefined:
    _inst = None

    def __new__(cls):
        if cls._inst is None:
            cls._inst = super().__new__(cls)
        return cls._inst

    def __repr__(self):
        return "undefined"

    def __bool__(self):
        return False


class Null:
    _inst = None

    def __new__(cls):
        if cls._inst is None:
            cls._inst = super().__new__(cls)
        return cls._inst

    def __repr__(self):
        return "null"

    def __bool__(self):
        return False


undefined = Undefined()
null = Null()


class JSException(Exception):
    """A thrown JS value."""

    def __init__(self, value):
        self.value = value
        super().__init__(to_js_string_safe(value))


class JSDeadlock(RuntimeError):
    pass


class ReturnSignal(Exception):
    def __init__(self, value):
        self.value = value


class BreakSignal(Exception):
    pass


class ContinueSignal(Exception):
    pass


class _OptShortCircuit(Exception):
    """Raised by an optional link (?.) on a nullish base; caught by the
    enclosing optchain wrapper, which yields undefined (spec: one nullish
    base short-circuits the whole chain, not just that link)."""


class JSObject:
    class_name = "Object"

    def __init__(self, props: dict | None = None):
        self.props: dict = props or {}
        self.getters: dict = {}
        self.setters: dict = {}
        self.proto = None  # [[Prototype]] — set by Object.create

    # Host-overridable hooks. Return NOT_PRESENT to fall through.
    def js_get_prop(self, name: str, interp):
        # Walk the prototype chain with the ORIGINAL receiver as `this`
        # for accessor properties (spec OrdinaryGet): shadowing = first
        # hit wins, own keys stay own (own_keys doesn't walk).
        obj = self
        while obj is not None:
            if name in obj.getters:
                return interp.call_function(obj.getters[name], self, [])
            if name in obj.props:
                value = obj.props[name]
                return undefined if value is ACCESSOR_SLOT else value
            obj = getattr(obj, "proto", None)
        return NOT_PRESENT

    def js_set_prop(self, name: str, value, interp) -> bool:
        if name in self.setters:
            interp.call_function(self.setters[name], self, [value])
            return True
        self.props[name] = value
        return True

    def js_delete_prop(self, name: str) -> None:
        self.props.pop(name, None)
        self.getters.pop(name, None)
        self.setters.pop(name, None)

    def own_keys(self) -> list:
        # Accessor properties are enumerable own properties too (spec:
        # Object.keys lists them; for-in walks them). Object literals
        # reserve an ACCESSOR_SLOT in props at definition time so the
        # insertion order interleaves exactly as written; accessors
        # installed by other means (host code) land after.
        keys = list(self.props.keys())
        keys += [k for k in self.getters if k not in self.props]
        # OrdinaryOwnPropertyKeys: canonical array indexes first in
        # ascending NUMERIC order, then string keys in insertion order —
        # Object.keys({b:1, 2:2, 1:3}) is ["1","2","b"] in every real
        # engine ("01" is not canonical and keeps insertion order).
        def is_index(k):
            return k.isdigit() and (k == "0" or not k.startswith("0"))
        ints = sorted((k for k in keys if is_index(k)), key=int)
        return ints + [k for k in keys if not is_index(k)]


NOT_PRESENT = object()
ACCESSOR_SLOT = object()  # placeholder in props holding a getter's slot
                          # in enumeration order (js_get_prop routes the
                          # actual read through the getter)


class JSArray(JSObject):
    class_name = "Array"

    def __init__(self, items: list | None = None):
        super().__init__()
        self.items: list = items if items is not None else []

    def own_keys(self) -> list:
        return [str(i) for i in range(len(self.items))]


class JSFunction(JSObject):
    class_name = "Function"

    def __init__(self, name, params, rest, body, env, *, is_async=False,
                 is_arrow=False, is_expr_body=False, this_val=NOT_PRESENT):
        super().__init__()
        self.name = name or ""
        self.params = params
        self.rest = rest
        self.body = body
        self.env = env
        self.is_async = is_async
        self.is_arrow = is_arrow
        self.is_expr_body = is_expr_body
        self.this_val = this_val  # captured lexically for arrows


class HostFunction(JSObject):
    class_name = "Function"

    def __init__(self, fn, name=""):
        super().__init__()
        self.fn = fn
        self.name = name or getattr(fn, "__name__", "")


class HostClass(JSObject):
    """Constructible host type: ``new X(...)`` and ``instanceof`` support."""

    class_name = "Function"

    def __init__(self, name, construct, instancecheck=None):
        super().__init__()
        self.name = name
        self.construct = construct
        self.instancecheck = instancecheck or (lambda v: False)


class RegExpObject(JSObject):
    class_name = "RegExp"

    def __init__(self, source: str, flags: str = ""):
        super().__init__()
        self.source = source
        self.flags = flags
        pyflags = 0
        if "i" in flags:
            pyflags |= _re.IGNORECASE
        if "m" in flags:
            pyflags |= _re.MULTILINE
        if "s" in flags:
            pyflags |= _re.DOTALL
        self.regex = _re.compile(_js_regex_to_py(source), pyflags)
        self.is_global = "g" in flags


def _js_regex_to_py(source: str) -> str:
    """The used subset of JS regex syntax is Python-compatible except
    ``\\d`` style classes (same), ``(?:)`` (same) — only ``\\/`` needs
    unescaping."""
    return source.replace("\\/", "/")


class Environment:
    __slots__ = ("vars", "parent", "consts")

    def __init__(self, parent=None):
        self.vars: dict = {}
        self.consts: set = set()
        self.parent = parent

    def declare(self, name: str, value, *, const=False) -> None:
        self.vars[name] = value
        if const:
            self.consts.add(name)

    def lookup(self, name: str):
        env = self
        while env is not None:
            if name in env.vars:
                return env.vars[name]
            env = env.parent
        return NOT_PRESENT

    def assign(self, name: str, value) -> bool:
        env = self
        while env is not None:
            if name in env.vars:
                if name in env.consts:
                    raise JSException(make_error("TypeError",
                                                 f"Assignment to constant {name}"))
                env.vars[name] = value
                return True
            env = env.parent
        return False


class _AsyncBody:
    """One in-flight async function call, carried by a parked thread.

    Cooperative, never concurrent: exactly one thread executes JS at any
    moment. The controller (whoever called the async function, or later
    the microtask resuming it) blocks until the body YIELDS — either by
    parking at an ``await`` or by finishing. ``await`` registers the
    continuation as a promise reaction, so resumption order is exactly
    the microtask order a browser would use."""

    def __init__(self, interp, fn, env, this):
        self.interp = interp
        self.fn, self.env, self.this = fn, env, this
        self.promise = Promise(interp)
        self._resume = _threading.Event()   # body waits; controller sets
        self._yielded = _threading.Event()  # controller waits; body sets
        self._box = None                    # ("value" | "error", payload)
        self._thread = _threading.Thread(
            target=self._run, daemon=True, name="jsrt-async-body")

    # ---- controller side ----

    def start(self) -> "Promise":
        self._thread.start()
        self._wait_for_yield()
        return self.promise

    def _wait_for_yield(self) -> None:
        self._yielded.wait()
        self._yielded.clear()

    def _deliver(self, kind, payload) -> None:
        """Runs as a promise-reaction microtask: hand the settled value
        (or rejection) into the parked body and run it to its next
        yield point."""
        self.interp.parked_async.remove(self)
        self._box = (kind, payload)
        self._resume.set()
        self._wait_for_yield()

    # ---- body side (carrier thread) ----

    def _run(self) -> None:
        tls = self.interp._async_tls
        tls.body = self
        try:
            result = self.interp._run_body(self.fn, self.env, self.this)
            self.promise.resolve(result)
        except JSException as e:
            self.promise.reject(e.value)
        except BaseException as e:  # host bug — surface, don't hang
            self.promise.reject(make_error("InternalError", repr(e)))
        finally:
            tls.body = None
            self._yielded.set()  # final yield: body is done

    def await_on(self, value):
        wrapped = Promise(self.interp)
        wrapped.resolve(value)  # non-promises settle immediately; chains
        self.interp.parked_async.append(self)
        wrapped.then_callbacks(
            lambda v: self._deliver("value", v),
            lambda e: self._deliver("error", e),
        )
        # Park: control goes back to the controller …
        self._yielded.set()
        self._resume.wait()
        self._resume.clear()
        # … and a microtask brought us back with the settled value.
        kind, payload = self._box
        self._box = None
        if kind == "error":
            raise JSException(payload)
        return payload


class Promise(JSObject):
    class_name = "Promise"
    PENDING, FULFILLED, REJECTED = 0, 1, 2

    def __init__(self, interp):
        super().__init__()
        self.interp = interp
        self.state = Promise.PENDING
        self.value = undefined
        self.callbacks: list = []  # (on_ful, on_rej, next_promise)
        self.handled = False

    def resolve(self, value) -> None:
        if self.state != Promise.PENDING:
            return
        if isinstance(value, Promise):  # chain
            value.then_callbacks(self.resolve, self.reject)
            return
        self.state = Promise.FULFILLED
        self.value = value
        self._schedule()

    def reject(self, value) -> None:
        if self.state != Promise.PENDING:
            return
        self.state = Promise.REJECTED
        self.value = value
        self._schedule()

    def then_callbacks(self, on_ful, on_rej) -> None:
        """Host-level then (Python callables)."""
        self.handled = True
        self.callbacks.append((on_ful, on_rej, None))
        if self.state != Promise.PENDING:
            self._schedule()

    def _schedule(self) -> None:
        cbs, self.callbacks = self.callbacks, []
        for on_ful, on_rej, _next in cbs:
            cb = on_ful if self.state == Promise.FULFILLED else on_rej
            value = self.value
            if cb is not None:
                self.interp.microtasks.append(lambda cb=cb, v=value: cb(v))
            elif self.state == Promise.REJECTED and _next is not None:
                self.interp.microtasks.append(
                    lambda n=_next, v=value: n.reject(v))
            elif _next is not None:
                self.interp.microtasks.append(
                    lambda n=_next, v=value: n.resolve(v))


def make_error(kind: str, message: str) -> JSObject:
    err = JSObject({"name": kind, "message": message, "stack": ""})
    err.class_name = "Error"
    return err


# ---- coercions ------------------------------------------------------------------


def is_truthy(v) -> bool:
    if v is undefined or v is null or v is False:
        return False
    if isinstance(v, bool):
        return v
    if isinstance(v, float):
        return not (v == 0 or math.isnan(v))
    if isinstance(v, str):
        return len(v) > 0
    return True


def to_number(v) -> float:
    if isinstance(v, bool):
        return 1.0 if v else 0.0
    if isinstance(v, float):
        return v
    if isinstance(v, str):
        s = v.strip()
        if not s:
            return 0.0
        try:
            return float(int(s, 16)) if s.lower().startswith("0x") else float(s)
        except ValueError:
            return math.nan
    if v is null:
        return 0.0
    if v is undefined:
        return math.nan
    if isinstance(v, JSArray):
        if not v.items:
            return 0.0
        if len(v.items) == 1:
            return to_number(v.items[0])
    return math.nan


def format_number(n: float) -> str:
    if math.isnan(n):
        return "NaN"
    if n == math.inf:
        return "Infinity"
    if n == -math.inf:
        return "-Infinity"
    if n == int(n) and abs(n) < 1e21:
        return str(int(n))
    r = repr(n)
    if "e" in r:
        # JS (Number::toString) prints positionally down to 1e-6 and
        # writes exponents without zero padding: 0.000001 not 1e-06,
        # and 1e-7 not 1e-07 below that (Python repr does both).
        if 0 < abs(n) < 1e-6 or abs(n) >= 1e21:
            mant, _, exp = r.partition("e")
            e = int(exp)
            return f"{mant}e{'+' if e >= 0 else '-'}{abs(e)}"
        from decimal import Decimal
        return format(Decimal(r), "f")
    return r


def to_js_string(v, interp=None) -> str:
    if isinstance(v, str):
        return v
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, float):
        return format_number(v)
    if v is undefined:
        return "undefined"
    if v is null:
        return "null"
    if isinstance(v, JSArray):
        return ",".join(
            "" if (x is undefined or x is null) else to_js_string(x, interp)
            for x in v.items)
    if isinstance(v, (JSFunction, HostFunction, HostClass)):
        return f"function {getattr(v, 'name', '')}() {{ [code] }}"
    if isinstance(v, RegExpObject):
        return f"/{v.source}/{v.flags}"
    if isinstance(v, JSObject):
        if v.class_name == "Error":
            name = v.props.get("name", "Error")
            msg = v.props.get("message", "")
            return f"{name}: {msg}" if msg else str(name)
        # toString method?
        ts = v.props.get("toString")
        if interp is not None and isinstance(ts, (JSFunction, HostFunction)):
            return to_js_string(interp.call_function(ts, v, []), interp)
        return "[object Object]"
    return str(v)


def to_js_string_safe(v) -> str:
    try:
        return to_js_string(v)
    except Exception:  # pragma: no cover
        return repr(v)


def js_to_python(v):
    """JS value → plain Python (for JSON + host bridges)."""
    if v is undefined or v is null:
        return None
    if isinstance(v, float) and v.is_integer() and abs(v) < 2**53:
        return int(v)
    if isinstance(v, (bool, float, str, int)):
        return v
    if isinstance(v, JSArray):
        return [js_to_python(x) for x in v.items]
    if isinstance(v, JSObject):
        # own_keys order (integer indexes first) so JSON.stringify and
        # host bridges see the same enumeration a real engine produces.
        out = {}
        for k in v.own_keys():
            val = v.props.get(k, NOT_PRESENT)
            if (val is NOT_PRESENT or val is undefined
                    or val is ACCESSOR_SLOT
                    or isinstance(val, (JSFunction, HostFunction))):
                continue
            out[k] = js_to_python(val)
        return out
    return None


def python_to_js(v):
    if v is None:
        return null
    if isinstance(v, bool):
        return v
    if isinstance(v, (int, float)):
        return float(v)
    if isinstance(v, str):
        return v
    if isinstance(v, (list, tuple)):
        return JSArray([python_to_js(x) for x in v])
    if isinstance(v, dict):
        return JSObject({str(k): python_to_js(x) for k, x in v.items()})
    if isinstance(v, JSObject):
        return v
    return undefined


# ---- interpreter ----------------------------------------------------------------


class Interpreter:
    def __init__(self):
        self.global_env = Environment()
        self.microtasks: deque = deque()
        self.io_pump = None          # host hook: () -> bool (made progress?)
        self.console: list = []
        self.unhandled_rejections: list = []
        self._now = _time.time       # virtual clock hook (browser overrides)
        self._async_tls = _threading.local()  # carrier-thread → _AsyncBody
        self.parked_async: list = []  # bodies parked at an await right now
        install_stdlib(self)

    # -- program entry ----------------------------------------------------------

    def run(self, src: str, filename: str = "<js>") -> None:
        ast = parse(src, filename)
        self.exec_block(ast, self.global_env, this=undefined)
        self.run_microtasks()

    def run_microtasks(self) -> None:
        guard = 0
        while self.microtasks:
            task = self.microtasks.popleft()
            task()
            guard += 1
            if guard > 100_000:
                raise JSDeadlock("microtask loop did not quiesce")

    # -- promise await ----------------------------------------------------------

    def await_value(self, v):
        body = getattr(self._async_tls, "body", None)
        if body is not None:
            # Inside an async function: park the carrier and resume via a
            # promise-reaction microtask — ALWAYS defers at least one
            # turn, even for non-promises/settled promises (spec).
            return body.await_on(v)
        # Top-level await: synchronous drain (see module docstring).
        if not isinstance(v, Promise):
            return v
        for _ in range(10_000):
            if v.state != Promise.PENDING:
                break
            if self.microtasks:
                self.run_microtasks()
                continue
            if self.io_pump is not None and self.io_pump():
                continue
            raise JSDeadlock(
                "await on a promise that only a future host event can "
                "settle — use .then() for user-gesture promises")
        if v.state == Promise.FULFILLED:
            v.handled = True
            return v.value
        v.handled = True
        raise JSException(v.value)

    # -- function calls ---------------------------------------------------------

    def call_function(self, fn, this, args: list):
        if isinstance(fn, HostFunction):
            return fn.fn(this, args)
        if isinstance(fn, HostClass):
            return fn.construct(args)
        if not isinstance(fn, JSFunction):
            raise JSException(make_error(
                "TypeError", f"{to_js_string_safe(fn)} is not a function"))
        env = Environment(fn.env)
        self.bind_params(fn, env, args)
        use_this = fn.this_val if fn.is_arrow else this
        if fn.is_async:
            return _AsyncBody(self, fn, env, use_this).start()
        return self._run_body(fn, env, use_this)

    def _run_body(self, fn: JSFunction, env: Environment, this):
        if fn.is_expr_body:
            return self.eval(fn.body, env, this)
        try:
            self.exec_stmt(fn.body, env, this)
        except ReturnSignal as r:
            return r.value
        return undefined

    def bind_params(self, fn: JSFunction, env: Environment, args: list) -> None:
        for idx, (pat, default) in enumerate(fn.params):
            val = args[idx] if idx < len(args) else undefined
            if val is undefined and default is not None:
                val = self.eval(default, env, undefined)
            self.bind_pattern(pat, val, env, "let")
        if fn.rest is not None:
            env.declare(fn.rest, JSArray(list(args[len(fn.params):])))

    def bind_pattern(self, pat, value, env: Environment, kind: str) -> None:
        const = kind == "const"
        if pat[0] == "pid":
            env.declare(pat[1], value, const=const)
            return
        if pat[0] == "parr":
            items = list(self.iterate(value))
            for idx, elem in enumerate(pat[1]):
                if elem is None:
                    continue
                sub, default = elem
                v = items[idx] if idx < len(items) else undefined
                if v is undefined and default is not None:
                    v = self.eval(default, env, undefined)
                self.bind_pattern(sub, v, env, kind)
            if pat[2] is not None:
                self.bind_pattern(
                    pat[2], JSArray(items[len(pat[1]):]), env, kind)
            return
        if pat[0] == "pobj":
            taken = set()
            for key, sub, default in pat[1]:
                v = self.get_prop(value, key)
                taken.add(key)
                if v is undefined and default is not None:
                    v = self.eval(default, env, undefined)
                self.bind_pattern(sub, v, env, kind)
            if pat[2] is not None:
                rest_obj = JSObject()
                if isinstance(value, JSObject):
                    for k in value.own_keys():
                        if k not in taken:
                            rest_obj.props[k] = self.get_prop(value, k)
                self.bind_pattern(pat[2], rest_obj, env, kind)
            return
        raise JSException(make_error("SyntaxError", f"bad pattern {pat[0]}"))

    # -- property access --------------------------------------------------------

    def get_prop(self, obj, name: str):
        from kubeflow_tpu.testing.jsrt import stdlib

        if obj is undefined or obj is null:
            raise JSException(make_error(
                "TypeError",
                f"Cannot read properties of {to_js_string_safe(obj)} "
                f"(reading '{name}')"))
        if isinstance(obj, str):
            return stdlib.string_prop(self, obj, name)
        if isinstance(obj, float):
            return stdlib.number_prop(self, obj, name)
        if isinstance(obj, bool):
            return undefined
        if isinstance(obj, JSArray):
            hit = stdlib.array_prop(self, obj, name)
            if hit is not NOT_PRESENT:
                return hit
            v = obj.js_get_prop(name, self)
            return undefined if v is NOT_PRESENT else v
        if isinstance(obj, Promise):
            hit = stdlib.promise_prop(self, obj, name)
            if hit is not NOT_PRESENT:
                return hit
        if isinstance(obj, RegExpObject):
            hit = stdlib.regex_prop(self, obj, name)
            if hit is not NOT_PRESENT:
                return hit
        if isinstance(obj, JSObject):
            v = obj.js_get_prop(name, self)
            if v is not NOT_PRESENT:
                return v
            if name == "constructor":
                return undefined
            return undefined
        return undefined

    def set_prop(self, obj, name: str, value) -> None:
        if obj is undefined or obj is null:
            raise JSException(make_error(
                "TypeError", f"Cannot set properties of {to_js_string_safe(obj)}"))
        if isinstance(obj, JSArray) and name == "length":
            n = int(to_number(value))
            del obj.items[n:]
            return
        if isinstance(obj, JSObject):
            obj.js_set_prop(name, value, self)
            return
        # Setting props on primitives: silently ignored (matches sloppy mode).

    def get_index(self, obj, key):
        if isinstance(obj, JSArray) and isinstance(key, float):
            i = int(key)
            if 0 <= i < len(obj.items):
                return obj.items[i]
            return undefined
        if isinstance(obj, str) and isinstance(key, float):
            i = int(key)
            return obj[i] if 0 <= i < len(obj) else undefined
        return self.get_prop(obj, to_js_string(key, self))

    def set_index(self, obj, key, value) -> None:
        if isinstance(obj, JSArray) and isinstance(key, float):
            i = int(key)
            while len(obj.items) <= i:
                obj.items.append(undefined)
            obj.items[i] = value
            return
        self.set_prop(obj, to_js_string(key, self), value)

    # -- iteration --------------------------------------------------------------

    def iterate(self, v):
        if isinstance(v, JSArray):
            return list(v.items)
        if isinstance(v, str):
            return list(v)
        if isinstance(v, JSObject):
            it = getattr(v, "js_iter", None)
            if it is not None:
                return list(it())
        raise JSException(make_error(
            "TypeError", f"{to_js_string_safe(v)} is not iterable"))

    # -- statements -------------------------------------------------------------

    def exec_block(self, stmts: list, env: Environment, this) -> None:
        # Function-declaration hoisting within the block.
        for stmt in stmts:
            if stmt[0] == "func_decl":
                _, name, params, rest, body, is_async = stmt
                env.declare(name, JSFunction(
                    name, params, rest, body, env, is_async=is_async))
        for stmt in stmts:
            if stmt[0] != "func_decl":
                self.exec_stmt(stmt, env, this)

    def exec_stmt(self, node, env: Environment, this) -> None:
        op = node[0]
        if op == "expr_stmt":
            self.eval(node[1], env, this)
        elif op == "var":
            _, kind, decls = node
            for pat, init in decls:
                value = undefined if init is None else self.eval(init, env, this)
                self.bind_pattern(pat, value, env, kind)
        elif op == "block":
            self.exec_block(node[1], Environment(env), this)
        elif op == "if":
            _, cond, then, other = node
            if is_truthy(self.eval(cond, env, this)):
                self.exec_stmt(then, env, this)
            elif other is not None:
                self.exec_stmt(other, env, this)
        elif op == "return":
            raise ReturnSignal(
                undefined if node[1] is None else self.eval(node[1], env, this))
        elif op == "while":
            _, cond, body = node
            while is_truthy(self.eval(cond, env, this)):
                try:
                    self.exec_stmt(body, Environment(env), this)
                except BreakSignal:
                    break
                except ContinueSignal:
                    continue
        elif op == "dowhile":
            _, body, cond = node
            while True:
                try:
                    self.exec_stmt(body, Environment(env), this)
                except BreakSignal:
                    break
                except ContinueSignal:
                    pass
                if not is_truthy(self.eval(cond, env, this)):
                    break
        elif op == "for":
            _, init, cond, update, body = node
            loop_env = Environment(env)
            if init is not None:
                self.exec_stmt(init, loop_env, this)
            # let/const loop heads get a FRESH binding per iteration
            # (CreatePerIterationEnvironment): closures made in the body
            # capture that iteration's value — `for (let i …) push(() => i)`
            # yields 0,1,2, not the final value like `var`.
            per_iter = (init is not None and init[0] == "var"
                        and init[1] in ("let", "const"))
            while True:
                if cond is not None and not is_truthy(
                        self.eval(cond, loop_env, this)):
                    break
                try:
                    self.exec_stmt(body, Environment(loop_env), this)
                except BreakSignal:
                    break
                except ContinueSignal:
                    pass
                if per_iter:
                    # Copy AFTER the body, BEFORE the update: closures
                    # made this iteration keep this iteration's values;
                    # the update mutates only the next iteration's env.
                    fresh = Environment(env)
                    fresh.vars.update(loop_env.vars)
                    fresh.consts |= loop_env.consts
                    loop_env = fresh
                if update is not None:
                    self.eval(update, loop_env, this)
        elif op == "forof":
            _, kind, pat, iterable, body = node
            for item in self.iterate(self.eval(iterable, env, this)):
                iter_env = Environment(env)
                self.bind_pattern(pat, item, iter_env, kind)
                try:
                    self.exec_stmt(body, iter_env, this)
                except BreakSignal:
                    break
                except ContinueSignal:
                    continue
        elif op == "forin":
            _, kind, pat, obj_expr, body = node
            obj = self.eval(obj_expr, env, this)
            keys = obj.own_keys() if isinstance(obj, JSObject) else []
            for key in keys:
                iter_env = Environment(env)
                self.bind_pattern(pat, key, iter_env, kind)
                try:
                    self.exec_stmt(body, iter_env, this)
                except BreakSignal:
                    break
                except ContinueSignal:
                    continue
        elif op == "try":
            _, block, param, catch_block, final = node
            # Python's finally gives exact JS ordering: the finalizer runs
            # on normal exit, on a caught/propagating throw, AND on
            # return/break/continue control-flow signals escaping the try.
            try:
                try:
                    self.exec_stmt(block, env, this)
                except JSException as e:
                    if catch_block is None:
                        raise
                    catch_env = Environment(env)
                    if param is not None:
                        self.bind_pattern(param, e.value, catch_env, "let")
                    self.exec_stmt(catch_block, catch_env, this)
            finally:
                if final is not None:
                    self.exec_stmt(final, env, this)
        elif op == "throw":
            raise JSException(self.eval(node[1], env, this))
        elif op == "break":
            raise BreakSignal()
        elif op == "continue":
            raise ContinueSignal()
        elif op == "switch":
            _, disc_expr, cases = node
            disc = self.eval(disc_expr, env, this)
            sw_env = Environment(env)
            matched = False
            try:
                for test, body in cases:
                    if not matched and test is not None and \
                            strict_equals(disc, self.eval(test, sw_env, this)):
                        matched = True
                    if matched:
                        for stmt in body:
                            self.exec_stmt(stmt, sw_env, this)
                if not matched:
                    hit_default = False
                    for test, body in cases:
                        if test is None:
                            hit_default = True
                        if hit_default:
                            for stmt in body:
                                self.exec_stmt(stmt, sw_env, this)
            except BreakSignal:
                pass
        elif op == "func_decl":
            _, name, params, rest, body, is_async = node
            env.declare(name, JSFunction(
                name, params, rest, body, env, is_async=is_async))
        elif op == "empty":
            pass
        else:
            raise JSException(make_error("SyntaxError", f"bad statement {op}"))

    # -- expressions ------------------------------------------------------------

    def eval(self, node, env: Environment, this):
        op = node[0]
        if op == "num":
            return node[1]
        if op == "str":
            return node[1]
        if op == "bool":
            return node[1]
        if op == "null":
            return null
        if op == "undef":
            return undefined
        if op == "this":
            return this
        if op == "ident":
            v = env.lookup(node[1])
            if v is NOT_PRESENT:
                raise JSException(make_error(
                    "ReferenceError", f"{node[1]} is not defined"))
            return v
        if op == "template":
            out = []
            for kind, payload in node[1]:
                if kind == "str":
                    out.append(payload)
                else:
                    out.append(to_js_string(self.eval(payload, env, this), self))
            return "".join(out)
        if op == "regex":
            return RegExpObject(node[1], node[2])
        if op == "array":
            items = []
            for elem in node[1]:
                if elem == ("hole",):
                    items.append(undefined)
                elif elem[0] == "spread":
                    items.extend(self.iterate(self.eval(elem[1], env, this)))
                else:
                    items.append(self.eval(elem, env, this))
            return JSArray(items)
        if op == "object":
            obj = JSObject()
            for prop in node[1]:
                kind = prop[0]
                if kind == "prop":
                    obj.props[prop[1]] = self.eval(prop[2], env, this)
                elif kind == "shorthand":
                    obj.props[prop[1]] = self.eval(("ident", prop[1]), env, this)
                elif kind == "method":
                    _, key, params, rest, body, is_async = prop
                    obj.props[key] = JSFunction(
                        key, params, rest, body, env, is_async=is_async)
                elif kind == "getter":
                    obj.getters[prop[1]] = JSFunction(
                        prop[1], [], None, prop[2], env)
                    # Accessor keys enumerate interleaved with data keys
                    # in DEFINITION order (Object.keys/for-in): reserve
                    # the slot now, tombstoned so reads still hit the
                    # getter via js_get_prop's precedence.
                    obj.props.setdefault(prop[1], ACCESSOR_SLOT)
                elif kind == "setter":
                    obj.setters[prop[1]] = JSFunction(
                        prop[1], [(prop[2], None)], None, prop[3], env)
                elif kind == "spread":
                    src = self.eval(prop[1], env, this)
                    if isinstance(src, JSObject):
                        for k in src.own_keys():
                            obj.props[k] = self.get_prop(src, k)
            return obj
        if op == "func":
            _, name, params, rest, body, is_async = node
            return JSFunction(name, params, rest, body, env, is_async=is_async)
        if op == "arrow":
            _, params, rest, body, is_expr, is_async = node
            return JSFunction("", params, rest, body, env, is_async=is_async,
                              is_arrow=True, is_expr_body=is_expr,
                              this_val=this)
        if op == "assign":
            return self.eval_assign(node, env, this)
        if op == "cond":
            _, c, a, b = node
            return self.eval(a if is_truthy(self.eval(c, env, this)) else b,
                             env, this)
        if op == "logic":
            _, sym, l, r = node
            lv = self.eval(l, env, this)
            if sym == "&&":
                return self.eval(r, env, this) if is_truthy(lv) else lv
            if sym == "??":
                return (self.eval(r, env, this)
                        if lv is null or lv is undefined else lv)
            return lv if is_truthy(lv) else self.eval(r, env, this)
        if op == "binop":
            _, sym, l, r = node
            return self.binop(sym, self.eval(l, env, this),
                              self.eval(r, env, this))
        if op == "unary":
            _, sym, operand = node
            if sym == "typeof":
                if operand[0] == "ident":
                    v = env.lookup(operand[1])
                    if v is NOT_PRESENT:
                        return "undefined"
                else:
                    v = self.eval(operand, env, this)
                return js_typeof(v)
            if sym == "delete":
                if operand[0] == "member":
                    obj = self.eval(operand[1], env, this)
                    if isinstance(obj, JSObject):
                        obj.js_delete_prop(operand[2])
                    return True
                if operand[0] == "index":
                    obj = self.eval(operand[1], env, this)
                    key = self.eval(operand[2], env, this)
                    if isinstance(obj, JSObject):
                        obj.js_delete_prop(to_js_string(key, self))
                    return True
                return True
            v = self.eval(operand, env, this)
            if sym == "!":
                return not is_truthy(v)
            if sym == "-":
                return -to_number(v)
            if sym == "+":
                return to_number(v)
            if sym == "~":
                return float(~_to_int32(v))
            if sym == "void":
                return undefined
        if op == "update":
            _, sym, prefix, target = node
            old = to_number(self.eval(target, env, this))
            new = old + (1 if sym == "++" else -1)
            self.assign_to(target, new, env, this)
            return new if prefix else old
        if op == "member":
            obj = self.eval(node[1], env, this)
            return self.get_prop(obj, node[2])
        if op == "index":
            obj = self.eval(node[1], env, this)
            key = self.eval(node[2], env, this)
            return self.get_index(obj, key)
        if op == "call":
            return self.eval_call(node, env, this)
        if op == "optchain":
            try:
                return self.eval(node[1], env, this)
            except _OptShortCircuit:
                return undefined
        if op == "optmember":
            obj = self.eval(node[1], env, this)
            if obj is null or obj is undefined:
                raise _OptShortCircuit()
            return self.get_prop(obj, node[2])
        if op == "optindex":
            obj = self.eval(node[1], env, this)
            if obj is null or obj is undefined:
                raise _OptShortCircuit()
            return self.get_index(obj, self.eval(node[2], env, this))
        if op == "optcall":
            return self.eval_call(node, env, this, optional=True)
        if op == "new":
            _, callee_node, arg_nodes = node
            callee = self.eval(callee_node, env, this)
            args = self.eval_args(arg_nodes, env, this)
            if isinstance(callee, HostClass):
                return callee.construct(args)
            if isinstance(callee, JSFunction):
                obj = JSObject()
                result = self.call_function(callee, obj, args)
                return result if isinstance(result, JSObject) else obj
            raise JSException(make_error(
                "TypeError", f"{to_js_string_safe(callee)} is not a constructor"))
        if op == "await":
            v = self.eval(node[1], env, this)
            return self.await_value(v)
        if op == "seq":
            result = undefined
            for e in node[1]:
                result = self.eval(e, env, this)
            return result
        if op == "spread":
            raise JSException(make_error("SyntaxError", "unexpected spread"))
        raise JSException(make_error("SyntaxError", f"bad expression {op}"))

    def eval_args(self, arg_nodes, env, this) -> list:
        args = []
        for a in arg_nodes:
            if a[0] == "spread":
                args.extend(self.iterate(self.eval(a[1], env, this)))
            else:
                args.append(self.eval(a, env, this))
        return args

    def eval_call(self, node, env, this, optional=False):
        _, callee_node, arg_nodes = node
        if callee_node[0] in ("member", "optmember"):
            obj = self.eval(callee_node[1], env, this)
            if callee_node[0] == "optmember" and (obj is null or obj is undefined):
                raise _OptShortCircuit()
            fn = self.get_prop(obj, callee_node[2])
            bind_this = obj
        elif callee_node[0] in ("index", "optindex"):
            obj = self.eval(callee_node[1], env, this)
            if callee_node[0] == "optindex" and (obj is null or obj is undefined):
                raise _OptShortCircuit()
            key = self.eval(callee_node[2], env, this)
            fn = self.get_index(obj, key)
            bind_this = obj
        else:
            fn = self.eval(callee_node, env, this)
            bind_this = undefined
        if optional and (fn is null or fn is undefined):
            raise _OptShortCircuit()
        args = self.eval_args(arg_nodes, env, this)
        return self.call_function(fn, bind_this, args)

    def eval_assign(self, node, env, this):
        _, sym, target, value_node = node
        if sym == "=":
            value = self.eval(value_node, env, this)
            self.assign_to(target, value, env, this)
            return value
        # compound: a op= b
        current = self.eval(target, env, this)
        rhs = self.eval(value_node, env, this)
        value = self.binop(sym[:-1], current, rhs)
        self.assign_to(target, value, env, this)
        return value

    def assign_to(self, target, value, env, this) -> None:
        if target[0] == "ident":
            if not env.assign(target[1], value):
                self.global_env.declare(target[1], value)  # implicit global
            return
        if target[0] == "member":
            obj = self.eval(target[1], env, this)
            self.set_prop(obj, target[2], value)
            return
        if target[0] == "index":
            obj = self.eval(target[1], env, this)
            key = self.eval(target[2], env, this)
            self.set_index(obj, key, value)
            return
        if target[0] == "array":
            # [a, b] = expr — assignment destructuring over existing names.
            items = list(self.iterate(value))
            for idx, elem in enumerate(target[1]):
                if elem == ("hole",):
                    continue
                self.assign_to(elem, items[idx] if idx < len(items)
                               else undefined, env, this)
            return
        raise JSException(make_error("SyntaxError", "invalid assignment target"))

    # -- operators --------------------------------------------------------------

    def binop(self, sym: str, l, r):
        if sym == "+":
            # ToPrimitive both sides first (spec 13.15.3): a custom
            # valueOf makes `({valueOf: () => 1}) + 1` numeric 2, while
            # objects without one still stringify ("[object Object]",
            # array join) exactly as before.
            lp = to_primitive(l, self)
            rp = to_primitive(r, self)
            if isinstance(lp, str) or isinstance(rp, str):
                return to_js_string(lp, self) + to_js_string(rp, self)
            return to_number(lp) + to_number(rp)
        if sym == "-":
            return to_number(l) - to_number(r)
        if sym == "*":
            return to_number(l) * to_number(r)
        if sym == "/":
            rn = to_number(r)
            ln = to_number(l)
            if rn == 0:
                if math.isnan(ln) or ln == 0:
                    return math.nan
                # Sign of ±Infinity follows the signs of BOTH operands
                # (x / -0 is -Infinity for positive x).
                positive = (ln > 0) == (math.copysign(1.0, rn) > 0)
                return math.inf if positive else -math.inf
            return ln / rn
        if sym == "%":
            rn = to_number(r)
            ln = to_number(l)
            if rn == 0 or math.isnan(ln) or math.isnan(rn):
                return math.nan
            return math.fmod(ln, rn)
        if sym == "===":
            return strict_equals(l, r)
        if sym == "!==":
            return not strict_equals(l, r)
        if sym == "==":
            return loose_equals(l, r, self)
        if sym == "!=":
            return not loose_equals(l, r, self)
        if sym in ("<", ">", "<=", ">="):
            if isinstance(l, str) and isinstance(r, str):
                if sym == "<":
                    return l < r
                if sym == ">":
                    return l > r
                if sym == "<=":
                    return l <= r
                return l >= r
            ln, rn = to_number(l), to_number(r)
            if math.isnan(ln) or math.isnan(rn):
                return False
            if sym == "<":
                return ln < rn
            if sym == ">":
                return ln > rn
            if sym == "<=":
                return ln <= rn
            return ln >= rn
        if sym == "&":
            return float(_to_int32(l) & _to_int32(r))
        if sym == "|":
            return float(_to_int32(l) | _to_int32(r))
        if sym == "^":
            return float(_to_int32(l) ^ _to_int32(r))
        if sym == "<<":
            return float(_to_int32(l) << (_to_int32(r) & 31))
        if sym == ">>":
            return float(_to_int32(l) >> (_to_int32(r) & 31))
        if sym == "instanceof":
            if isinstance(r, HostClass):
                return bool(r.instancecheck(l))
            raise JSException(make_error(
                "TypeError", "Right-hand side of instanceof is not callable"))
        if sym == "in":
            key = to_js_string(l, self)
            if isinstance(r, JSArray):
                return key.isdigit() and int(key) < len(r.items)
            if isinstance(r, JSObject):
                return r.js_get_prop(key, self) is not NOT_PRESENT
            return False
        if sym == "**":
            return to_number(l) ** to_number(r)
        raise JSException(make_error("SyntaxError", f"bad operator {sym}"))


def _to_int32(v) -> int:
    n = to_number(v)
    if math.isnan(n) or math.isinf(n):
        return 0
    n = int(n) & 0xFFFFFFFF
    return n - 0x100000000 if n >= 0x80000000 else n


def js_typeof(v) -> str:
    if v is undefined:
        return "undefined"
    if isinstance(v, bool):
        return "boolean"
    if isinstance(v, float):
        return "number"
    if isinstance(v, str):
        return "string"
    if isinstance(v, (JSFunction, HostFunction, HostClass)):
        return "function"
    return "object"  # null, objects, arrays


def strict_equals(l, r) -> bool:
    if l is undefined and r is undefined:
        return True
    if l is null and r is null:
        return True
    if isinstance(l, bool) or isinstance(r, bool):
        return isinstance(l, bool) and isinstance(r, bool) and l == r
    if isinstance(l, float) and isinstance(r, float):
        return l == r  # NaN != NaN falls out naturally
    if isinstance(l, str) and isinstance(r, str):
        return l == r
    return l is r


def to_primitive(v, interp=None, hint="default"):
    """ToPrimitive (ES2023 §7.1.1): ``valueOf`` first for the
    default/number hints, ``toString`` first for the string hint —
    JS-defined methods run through ``interp`` so a custom
    ``{valueOf: () => 1}`` coerces the way a real engine does. Falls back
    to the engine's default stringification when neither method yields a
    primitive (plain objects → "[object Object]", arrays → join)."""
    if not isinstance(v, JSObject):
        return v
    if interp is not None:
        order = (("toString", "valueOf") if hint == "string"
                 else ("valueOf", "toString"))
        for name in order:
            m = interp.get_prop(v, name)
            if isinstance(m, (JSFunction, HostFunction)):
                res = interp.call_function(m, v, [])
                if not isinstance(res, JSObject):
                    return res
    return to_js_string(v, interp)


def loose_equals(l, r, interp=None) -> bool:
    nullish_l = l is undefined or l is null
    nullish_r = r is undefined or r is null
    if nullish_l or nullish_r:
        return nullish_l and nullish_r
    if type(l) is type(r) or (isinstance(l, JSObject) and isinstance(r, JSObject)):
        return strict_equals(l, r)
    # Booleans coerce to numbers FIRST (spec steps 9-10) — so the
    # object-vs-primitive retry below sees a number, making
    # `[] == false` / `[1] == true` come out true as in real engines.
    if isinstance(l, bool):
        return loose_equals(to_number(l), r, interp)
    if isinstance(r, bool):
        return loose_equals(l, to_number(r), interp)
    if isinstance(l, float) and isinstance(r, str):
        return l == to_number(r)
    if isinstance(l, str) and isinstance(r, float):
        return to_number(l) == r
    # object vs primitive: ToPrimitive the object, then retry —
    # `[] == ""`, `[1] == 1`, and `({valueOf: () => 2}) == 2` are true in
    # every real engine (custom valueOf/toString run via ``interp``).
    if isinstance(l, JSObject) and isinstance(r, (str, float)):
        return loose_equals(to_primitive(l, interp), r, interp)
    if isinstance(r, JSObject) and isinstance(l, (str, float)):
        return loose_equals(l, to_primitive(r, interp), interp)
    return False


def install_stdlib(interp: Interpreter) -> None:
    from kubeflow_tpu.testing.jsrt import stdlib

    stdlib.install(interp)


JSON = _json  # re-export for stdlib convenience
