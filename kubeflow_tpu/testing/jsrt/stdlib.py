"""Standard library for the vendored JS runtime: global objects (Object,
Array, JSON, Math, Date, Promise, console, …) and the per-type method
tables (string/array/number/promise/regex)."""

from __future__ import annotations

import datetime as _dt
import json as _json
import math
import urllib.parse as _url

from kubeflow_tpu.testing.jsrt.interp import (
    NOT_PRESENT,
    HostClass,
    HostFunction,
    Interpreter,
    JSArray,
    JSException,
    JSFunction,
    JSObject,
    Promise,
    RegExpObject,
    format_number,
    is_truthy,
    js_to_python,
    js_typeof,
    make_error,
    null,
    python_to_js,
    strict_equals,
    to_js_string,
    to_number,
    undefined,
)


def host(name=""):
    def wrap(fn):
        return HostFunction(fn, name or fn.__name__)
    return wrap


def _call(interp, fn, this, args):
    return interp.call_function(fn, this, list(args))


# ---- string methods --------------------------------------------------------------


def string_prop(interp: Interpreter, s: str, name: str):
    if name == "length":
        return float(len(s))

    def method(fn):
        return HostFunction(lambda this, args, f=fn: f(args), name)

    if name == "slice":
        return method(lambda a: _slice_str(s, a))
    if name == "substring":
        return method(lambda a: _substring(s, a))
    if name == "split":
        return method(lambda a: _split(s, a))
    if name == "toUpperCase":
        return method(lambda a: s.upper())
    if name == "toLowerCase":
        return method(lambda a: s.lower())
    if name == "trim":
        return method(lambda a: s.strip())
    if name == "startsWith":
        return method(lambda a: s.startswith(to_js_string(a[0], interp)))
    if name == "endsWith":
        return method(lambda a: s.endswith(to_js_string(a[0], interp)))
    if name == "includes":
        return method(lambda a: to_js_string(a[0], interp) in s)
    if name == "indexOf":
        return method(lambda a: float(s.find(to_js_string(a[0], interp))))
    if name == "lastIndexOf":
        return method(lambda a: float(s.rfind(to_js_string(a[0], interp))))
    if name == "charAt":
        return method(lambda a: s[int(to_number(a[0]))] if a and
                      0 <= int(to_number(a[0])) < len(s) else "")
    if name == "at":
        def str_at(a):
            i = int(to_number(a[0])) if a and a[0] is not undefined else 0
            if i < 0:
                i += len(s)
            return s[i] if 0 <= i < len(s) else undefined
        return method(str_at)
    if name == "charCodeAt":
        def char_code_at(a):
            i = _to_index(a[0], len(s)) if a and a[0] is not undefined else 0
            return float(ord(s[i])) if 0 <= i < len(s) else math.nan
        return method(char_code_at)
    if name == "repeat":
        return method(lambda a: s * int(to_number(a[0])))
    if name == "padStart":
        return method(lambda a: s.rjust(
            int(to_number(a[0])),
            to_js_string(a[1], interp) if len(a) > 1 else " "))
    if name == "padEnd":
        return method(lambda a: s.ljust(
            int(to_number(a[0])),
            to_js_string(a[1], interp) if len(a) > 1 else " "))
    if name == "localeCompare":
        return method(lambda a: float(
            (s > to_js_string(a[0], interp)) - (s < to_js_string(a[0], interp))))
    if name == "match":
        return method(lambda a: _match(s, a[0]))
    if name == "replace":
        return method(lambda a: _replace(interp, s, a))
    if name == "replaceAll":
        return method(lambda a: s.replace(
            to_js_string(a[0], interp), to_js_string(a[1], interp)))
    if name == "concat":
        return method(lambda a: s + "".join(to_js_string(x, interp) for x in a))
    if name == "toString":
        return method(lambda a: s)
    return undefined


def _to_index(v, length: int) -> int:
    """JS ToInteger for index args: NaN→0, ±Infinity clamps, else trunc."""
    n = to_number(v)
    if math.isnan(n):
        return 0
    if n == math.inf:
        return length
    if n == -math.inf:
        return -length
    return int(n)


def _slice_str(s: str, args):
    def idx(i, default):
        if i >= len(args) or args[i] is undefined:
            return default
        return _to_index(args[i], len(s))
    start, end = idx(0, 0), idx(1, len(s))
    return s[slice(*_norm_range(len(s), start, end))]


def _substring(s: str, args):
    def idx(i, default):
        if i >= len(args) or args[i] is undefined:
            return default
        return max(0, _to_index(args[i], len(s)))
    a = idx(0, 0)
    b = idx(1, len(s))
    a, b = min(a, len(s)), min(b, len(s))
    if a > b:
        a, b = b, a
    return s[a:b]


def _norm_range(n: int, start: int, end: int):
    if start < 0:
        start = max(0, n + start)
    if end < 0:
        end = max(0, n + end)
    return start, end


def _split(s: str, args):
    if not args or args[0] is undefined:
        return JSArray([s])
    sep = args[0]
    if isinstance(sep, RegExpObject):
        parts = sep.regex.split(s)
    else:
        sep = to_js_string(sep)
        parts = list(s) if sep == "" else s.split(sep)
    # Spec: the limit TRUNCATES the result (it is not Python's maxsplit —
    # 'a,b,c'.split(',', 2) is ['a','b'], never ['a','b,c']).
    if len(args) > 1 and args[1] is not undefined:
        parts = parts[:int(to_number(args[1]))]
    return JSArray(parts)


def _match(s: str, pattern):
    if isinstance(pattern, str):
        pattern = RegExpObject(pattern)
    if not isinstance(pattern, RegExpObject):
        return null
    if pattern.is_global:
        # finditer + group(0): findall would hand back capture groups, not
        # full matches, whenever the pattern has groups.
        found = [m.group(0) for m in pattern.regex.finditer(s)]
        return JSArray(found) if found else null
    m = pattern.regex.search(s)
    if not m:
        return null
    groups = JSArray([m.group(0)] + [
        g if g is not None else undefined for g in m.groups()])
    groups.props["index"] = float(m.start())
    groups.props["input"] = s
    return groups


def _replace(interp, s: str, args):
    pattern, repl = args[0], args[1]
    def do_repl(m):
        if isinstance(repl, (JSFunction, HostFunction)):
            call_args = [m.group(0)] + [
                g if g is not None else undefined for g in m.groups()]
            return to_js_string(
                interp.call_function(repl, undefined, call_args), interp)
        out = to_js_string(repl, interp)
        result = []
        i = 0
        while i < len(out):
            if out[i] == "$" and i + 1 < len(out):
                nxt = out[i + 1]
                if nxt.isdigit():
                    result.append(m.group(int(nxt)) or "")
                    i += 2
                    continue
                if nxt == "&":
                    result.append(m.group(0))
                    i += 2
                    continue
            result.append(out[i])
            i += 1
        return "".join(result)

    if isinstance(pattern, RegExpObject):
        return pattern.regex.sub(do_repl, s,
                                 count=0 if pattern.is_global else 1)
    target = to_js_string(pattern, interp)
    if isinstance(repl, (JSFunction, HostFunction)):
        idx = s.find(target)
        if idx < 0:
            return s
        replaced = to_js_string(
            interp.call_function(repl, undefined, [target]), interp)
        return s[:idx] + replaced + s[idx + len(target):]
    return s.replace(target, to_js_string(repl, interp), 1)


# ---- number methods --------------------------------------------------------------


def _num_to_radix(n: float, radix: int) -> str:
    """Number::toString(radix) — integer part exact, fraction to 20
    digits (the SPAs only format integers; hex ids, base-36 slugs)."""
    if math.isnan(n):
        return "NaN"
    if math.isinf(n):
        return "Infinity" if n > 0 else "-Infinity"
    digits = "0123456789abcdefghijklmnopqrstuvwxyz"
    neg, n = n < 0, abs(n)
    i, out = int(n), ""
    while True:
        out = digits[i % radix] + out
        i //= radix
        if i == 0:
            break
    frac = n - int(n)
    if frac:
        out += "."
        for _ in range(20):
            frac *= radix
            d = int(frac)
            out += digits[d]
            frac -= d
            if not frac:
                break
    return ("-" if neg else "") + out


def number_prop(interp: Interpreter, n: float, name: str):
    if name == "toFixed":
        return HostFunction(
            lambda this, args: f"{n:.{int(to_number(args[0])) if args else 0}f}",
            "toFixed")
    if name == "toString":
        def num_to_string(this, args):
            if args and args[0] is not undefined:
                radix = int(to_number(args[0]))
                if radix != 10:
                    return _num_to_radix(n, radix)
            return format_number(n)
        return HostFunction(num_to_string, "toString")
    return undefined


# ---- array methods ---------------------------------------------------------------


def array_prop(interp: Interpreter, arr: JSArray, name: str):
    items = arr.items

    def method(fn):
        return HostFunction(lambda this, args, f=fn: f(args), name)

    if name == "length":
        return float(len(items))
    if name == "push":
        return method(lambda a: (items.extend(a), float(len(items)))[1])
    if name == "pop":
        return method(lambda a: items.pop() if items else undefined)
    if name == "at":
        def arr_at(a):
            i = int(to_number(a[0])) if a and a[0] is not undefined else 0
            if i < 0:
                i += len(items)
            return items[i] if 0 <= i < len(items) else undefined
        return method(arr_at)
    if name == "shift":
        return method(lambda a: items.pop(0) if items else undefined)
    if name == "unshift":
        return method(lambda a: (items.__setitem__(slice(0, 0), list(a)),
                                 float(len(items)))[1])
    if name == "slice":
        return method(lambda a: JSArray(items[slice(*_norm_range(
            len(items),
            int(to_number(a[0])) if a else 0,
            int(to_number(a[1])) if len(a) > 1 and a[1] is not undefined
            else len(items)))]))
    if name == "splice":
        def splice(a):
            start = int(to_number(a[0])) if a else 0
            if start < 0:
                start = max(0, len(items) + start)
            count = int(to_number(a[1])) if len(a) > 1 else len(items) - start
            removed = items[start:start + count]
            items[start:start + count] = list(a[2:])
            return JSArray(removed)
        return method(splice)
    if name == "concat":
        def concat(a):
            out = list(items)
            for x in a:
                if isinstance(x, JSArray):
                    out.extend(x.items)
                else:
                    out.append(x)
            return JSArray(out)
        return method(concat)
    if name == "join":
        return method(lambda a: (
            to_js_string(a[0], interp) if a else ",").join(
            "" if (x is undefined or x is null) else to_js_string(x, interp)
            for x in items))
    if name == "indexOf":
        def index_of(a):
            for i, x in enumerate(items):
                if strict_equals(x, a[0]):
                    return float(i)
            return -1.0
        return method(index_of)
    if name == "includes":
        return method(lambda a: any(strict_equals(x, a[0]) for x in items))
    if name == "map":
        return method(lambda a: JSArray([
            _call(interp, a[0], undefined, [x, float(i), arr])
            for i, x in enumerate(list(items))]))
    if name == "filter":
        return method(lambda a: JSArray([
            x for i, x in enumerate(list(items))
            if is_truthy(_call(interp, a[0], undefined, [x, float(i), arr]))]))
    if name == "forEach":
        def for_each(a):
            for i, x in enumerate(list(items)):
                _call(interp, a[0], undefined, [x, float(i), arr])
            return undefined
        return method(for_each)
    if name == "find":
        def find(a):
            for i, x in enumerate(list(items)):
                if is_truthy(_call(interp, a[0], undefined, [x, float(i), arr])):
                    return x
            return undefined
        return method(find)
    if name == "findIndex":
        def find_index(a):
            for i, x in enumerate(list(items)):
                if is_truthy(_call(interp, a[0], undefined, [x, float(i), arr])):
                    return float(i)
            return -1.0
        return method(find_index)
    if name == "some":
        return method(lambda a: any(
            is_truthy(_call(interp, a[0], undefined, [x, float(i), arr]))
            for i, x in enumerate(list(items))))
    if name == "every":
        return method(lambda a: all(
            is_truthy(_call(interp, a[0], undefined, [x, float(i), arr]))
            for i, x in enumerate(list(items))))
    if name == "sort":
        def sort(a):
            import functools
            if a and a[0] is not undefined:
                cmp = a[0]
                items.sort(key=functools.cmp_to_key(
                    lambda x, y: _cmp_result(
                        _call(interp, cmp, undefined, [x, y]))))
            else:
                items.sort(key=lambda x: to_js_string(x, interp))
            return arr
        return method(sort)
    if name == "reverse":
        return method(lambda a: (items.reverse(), arr)[1])
    if name == "reduce":
        def reduce(a):
            fn = a[0]
            acc_given = len(a) > 1
            acc = a[1] if acc_given else None
            seq = list(items)
            start = 0
            if not acc_given:
                if not seq:
                    raise JSException(make_error(
                        "TypeError", "Reduce of empty array with no initial value"))
                acc = seq[0]
                start = 1
            for i in range(start, len(seq)):
                acc = _call(interp, fn, undefined, [acc, seq[i], float(i), arr])
            return acc
        return method(reduce)
    if name == "flat":
        def flat(a):
            depth = to_number(a[0]) if a else 1.0
            def go(xs, d):
                out = []
                for x in xs:
                    if isinstance(x, JSArray) and d > 0:
                        out.extend(go(x.items, d - 1))
                    else:
                        out.append(x)
                return out
            return JSArray(go(items, depth))
        return method(flat)
    if name == "flatMap":
        def flat_map(a):
            out = []
            for i, x in enumerate(list(items)):
                r = _call(interp, a[0], undefined, [x, float(i), arr])
                if isinstance(r, JSArray):
                    out.extend(r.items)
                else:
                    out.append(r)
            return JSArray(out)
        return method(flat_map)
    if name == "keys":
        return method(lambda a: JSArray([float(i) for i in range(len(items))]))
    if name == "entries":
        return method(lambda a: JSArray(
            [JSArray([float(i), x]) for i, x in enumerate(items)]))
    if name == "toString":
        return method(lambda a: to_js_string(arr, interp))
    return NOT_PRESENT


def _cmp_result(v) -> int:
    n = to_number(v)
    if math.isnan(n):
        return 0
    return (n > 0) - (n < 0)


# ---- promise methods -------------------------------------------------------------


def promise_prop(interp: Interpreter, p: Promise, name: str):
    if name == "then":
        def then(this, args):
            on_ful = args[0] if args and args[0] is not undefined else None
            on_rej = args[1] if len(args) > 1 and args[1] is not undefined \
                else None
            return _chain(interp, p, on_ful, on_rej)
        return HostFunction(then, "then")
    if name == "catch":
        def catch(this, args):
            return _chain(interp, p, None, args[0] if args else None)
        return HostFunction(catch, "catch")
    if name == "finally":
        def fin(this, args):
            cb = args[0] if args else None

            def on_ful(v):
                if cb is not None:
                    _call(interp, cb, undefined, [])
                return v

            def on_rej(v):
                if cb is not None:
                    _call(interp, cb, undefined, [])
                raise JSException(v)
            return _chain_host(interp, p, on_ful, on_rej)
        return HostFunction(fin, "finally")
    return NOT_PRESENT


def _chain(interp: Interpreter, p: Promise, on_ful, on_rej) -> Promise:
    def ful(v):
        if on_ful is None:
            return v
        return _call(interp, on_ful, undefined, [v])

    def rej(v):
        if on_rej is None:
            raise JSException(v)
        return _call(interp, on_rej, undefined, [v])
    return _chain_host(interp, p, ful, rej)


def _chain_host(interp: Interpreter, p: Promise, ful, rej) -> Promise:
    nxt = Promise(interp)

    def on_fulfilled(v):
        try:
            nxt.resolve(ful(v))
        except JSException as e:
            nxt.reject(e.value)

    def on_rejected(v):
        try:
            nxt.resolve(rej(v))
        except JSException as e:
            nxt.reject(e.value)
    p.then_callbacks(on_fulfilled, on_rejected)
    return nxt


# ---- regex methods ---------------------------------------------------------------


def regex_prop(interp: Interpreter, r: RegExpObject, name: str):
    if name == "test":
        return HostFunction(
            lambda this, args: r.regex.search(
                to_js_string(args[0], interp)) is not None, "test")
    if name == "exec":
        return HostFunction(
            lambda this, args: _match(to_js_string(args[0], interp), r), "exec")
    if name == "source":
        return r.source
    return NOT_PRESENT


# ---- globals ---------------------------------------------------------------------


def install(interp: Interpreter) -> None:
    g = interp.global_env

    # console
    console = JSObject()
    for level in ("log", "warn", "error", "info", "debug"):
        def logger(this, args, lvl=level):
            interp.console.append(
                (lvl, " ".join(to_js_string(a, interp) for a in args)))
            return undefined
        console.props[level] = HostFunction(logger, level)
    g.declare("console", console)

    # Math
    m = JSObject()
    for name, fn in (
        ("floor", lambda a: float(math.floor(to_number(a[0])))),
        ("ceil", lambda a: float(math.ceil(to_number(a[0])))),
        ("round", lambda a: float(math.floor(to_number(a[0]) + 0.5))),
        ("abs", lambda a: abs(to_number(a[0]))),
        ("sqrt", lambda a: math.sqrt(to_number(a[0]))),
        ("pow", lambda a: to_number(a[0]) ** to_number(a[1])),
        ("min", lambda a: min((to_number(x) for x in a), default=math.inf)),
        ("max", lambda a: max((to_number(x) for x in a), default=-math.inf)),
        ("random", lambda a: 0.42),  # deterministic for tests
        ("trunc", lambda a: float(math.trunc(to_number(a[0])))),
        ("sign", lambda a: math.copysign(1.0, to_number(a[0]))
         if to_number(a[0]) != 0 else 0.0),
    ):
        m.props[name] = HostFunction(lambda this, args, f=fn: f(args), name)
    m.props["PI"] = math.pi
    m.props["Infinity"] = math.inf
    g.declare("Math", m)
    g.declare("Infinity", math.inf)
    g.declare("NaN", math.nan)

    # JSON
    js_on = JSObject()

    def json_stringify(this, args):
        value = js_to_python(args[0]) if args else None
        indent = None
        if len(args) > 2 and args[2] is not undefined:
            indent = int(to_number(args[2]))
        if args and args[0] is undefined:
            return undefined
        # Node emits compact separators ('{"a":1}'); Python's defaults
        # insert spaces — a cross-engine divergence the differential
        # corpus pins (json-stringify-compact).
        seps = (",", ": ") if indent is not None else (",", ":")
        return _json.dumps(value, indent=indent, separators=seps)

    def json_parse(this, args):
        try:
            return python_to_js(_json.loads(to_js_string(args[0], interp)))
        except ValueError as e:
            raise JSException(make_error("SyntaxError", f"JSON.parse: {e}"))
    js_on.props["stringify"] = HostFunction(json_stringify, "stringify")
    js_on.props["parse"] = HostFunction(json_parse, "parse")
    g.declare("JSON", js_on)

    # Object
    obj_ns = JSObject()

    def object_assign(this, args):
        target = args[0]
        for src in args[1:]:
            if isinstance(src, JSObject) and not isinstance(src, JSArray):
                for k in src.own_keys():
                    interp.set_prop(target, k, interp.get_prop(src, k))
            elif isinstance(src, JSArray):
                for i, x in enumerate(src.items):
                    interp.set_prop(target, str(i), x)
        return target
    obj_ns.props["assign"] = HostFunction(object_assign, "assign")
    obj_ns.props["keys"] = HostFunction(
        lambda this, args: JSArray(list(args[0].own_keys()))
        if isinstance(args[0], JSObject) else JSArray([]), "keys")
    obj_ns.props["values"] = HostFunction(
        lambda this, args: JSArray([
            interp.get_prop(args[0], k) for k in args[0].own_keys()])
        if isinstance(args[0], JSObject) else JSArray([]), "values")
    obj_ns.props["entries"] = HostFunction(
        lambda this, args: JSArray([
            JSArray([k, interp.get_prop(args[0], k)])
            for k in args[0].own_keys()])
        if isinstance(args[0], JSObject) else JSArray([]), "entries")
    obj_ns.props["fromEntries"] = HostFunction(
        lambda this, args: JSObject({
            to_js_string(pair.items[0], interp): pair.items[1]
            for pair in args[0].items}), "fromEntries")
    obj_ns.props["freeze"] = HostFunction(lambda this, args: args[0], "freeze")

    def object_create(this, args):
        proto = args[0] if args else undefined
        o = JSObject()
        if isinstance(proto, JSObject):
            o.proto = proto
        return o
    obj_ns.props["create"] = HostFunction(object_create, "create")
    obj_ns.props["getPrototypeOf"] = HostFunction(
        lambda this, args: (getattr(args[0], "proto", None) or null)
        if isinstance(args[0], JSObject) else null, "getPrototypeOf")
    g.declare("Object", obj_ns)

    # Array
    arr_ns = HostClass("Array", lambda args: JSArray(list(args)),
                       lambda v: isinstance(v, JSArray))
    arr_ns.props["isArray"] = HostFunction(
        lambda this, args: isinstance(args[0], JSArray) if args else False,
        "isArray")

    def array_from(this, args):
        src = args[0] if args else undefined
        mapper = args[1] if len(args) > 1 else None
        if isinstance(src, JSObject) and not isinstance(src, JSArray) and \
                hasattr(src, "js_iter"):
            seq = list(src.js_iter())
        elif isinstance(src, JSArray):
            seq = list(src.items)
        elif isinstance(src, str):
            seq = list(src)
        elif isinstance(src, JSObject) and "length" in src.props:
            n = int(to_number(src.props["length"]))
            seq = [src.props.get(str(i), undefined) for i in range(n)]
        else:
            seq = []
        if mapper is not None and mapper is not undefined:
            seq = [_call(interp, mapper, undefined, [x, float(i)])
                   for i, x in enumerate(seq)]
        return JSArray(seq)
    arr_ns.props["from"] = HostFunction(array_from, "from")
    g.declare("Array", arr_ns)

    # Date — static now()/parse(iso) plus constructible instances with the
    # UTC accessor subset (what KF.formatDate renders with).
    def date_parse(this, args):
        s = to_js_string(args[0], interp)
        try:
            dt = _dt.datetime.fromisoformat(s.replace("Z", "+00:00"))
            if dt.tzinfo is None:
                dt = dt.replace(tzinfo=_dt.timezone.utc)
            return dt.timestamp() * 1000.0
        except ValueError:
            return math.nan

    def date_construct(args):
        if not args:
            ms = float(int(interp._now() * 1000))
        elif isinstance(args[0], str):
            ms = date_parse(undefined, args)
        else:
            try:  # non-numeric (undefined/null/objects) → Invalid Date
                ms = float(args[0])
            except (TypeError, ValueError):
                ms = math.nan
        obj = JSObject()
        obj.class_name = "Date"
        if math.isnan(ms):
            dt = None
        else:
            dt = _dt.datetime.fromtimestamp(ms / 1000.0, _dt.timezone.utc)
        def acc(name, fn):
            obj.props[name] = HostFunction(
                lambda this, a: math.nan if dt is None else float(fn(dt)),
                name)
        acc("getTime", lambda d: ms)
        acc("getUTCFullYear", lambda d: d.year)
        acc("getUTCMonth", lambda d: d.month - 1)
        acc("getUTCDate", lambda d: d.day)
        acc("getUTCHours", lambda d: d.hour)
        acc("getUTCMinutes", lambda d: d.minute)
        acc("getUTCSeconds", lambda d: d.second)
        acc("getUTCDay", lambda d: (d.weekday() + 1) % 7)
        obj.props["toISOString"] = HostFunction(
            lambda this, a: ("Invalid Date" if dt is None else
                             dt.strftime("%Y-%m-%dT%H:%M:%S.") +
                             f"{dt.microsecond // 1000:03d}Z"), "toISOString")
        return obj

    date_cls = HostClass("Date", date_construct,
                         lambda v: getattr(v, "class_name", "") == "Date")
    date_cls.props["now"] = HostFunction(
        lambda this, args: float(int(interp._now() * 1000)), "now")
    date_cls.props["parse"] = HostFunction(date_parse, "parse")
    g.declare("Date", date_cls)

    # Promise
    def promise_construct(args):
        p = Promise(interp)
        executor = args[0]
        resolve_fn = HostFunction(
            lambda this, a: (p.resolve(a[0] if a else undefined), undefined)[1],
            "resolve")
        reject_fn = HostFunction(
            lambda this, a: (p.reject(a[0] if a else undefined), undefined)[1],
            "reject")
        try:
            interp.call_function(executor, undefined, [resolve_fn, reject_fn])
        except JSException as e:
            p.reject(e.value)
        return p
    promise_ns = HostClass("Promise", promise_construct,
                           lambda v: isinstance(v, Promise))

    def promise_resolve(this, args):
        v = args[0] if args else undefined
        if isinstance(v, Promise):
            return v
        p = Promise(interp)
        p.resolve(v)
        return p
    promise_ns.props["resolve"] = HostFunction(promise_resolve, "resolve")

    def promise_reject(this, args):
        p = Promise(interp)
        p.reject(args[0] if args else undefined)
        return p
    promise_ns.props["reject"] = HostFunction(promise_reject, "reject")

    def promise_all(this, args):
        out = Promise(interp)
        entries = list(interp.iterate(args[0]))
        results = [undefined] * len(entries)
        remaining = {"n": 0}
        if not entries:
            out.resolve(JSArray([]))
            return out
        for i, entry in enumerate(entries):
            if isinstance(entry, Promise):
                remaining["n"] += 1

                def on_ok(v, i=i):
                    results[i] = v
                    remaining["n"] -= 1
                    if remaining["n"] == 0:
                        out.resolve(JSArray(results))

                def on_err(v):
                    out.reject(v)
                entry.then_callbacks(on_ok, on_err)
            else:
                results[i] = entry
        if remaining["n"] == 0:
            out.resolve(JSArray(results))
        return out
    promise_ns.props["all"] = HostFunction(promise_all, "all")

    def promise_all_settled(this, args):
        out = Promise(interp)
        entries = list(interp.iterate(args[0]))
        results = [undefined] * len(entries)
        remaining = {"n": len(entries)}
        if not entries:
            out.resolve(JSArray([]))
            return out

        def settle(i, status, key, v):
            results[i] = JSObject({"status": status, key: v})
            remaining["n"] -= 1
            if remaining["n"] == 0:
                out.resolve(JSArray(results))
        for i, entry in enumerate(entries):
            if isinstance(entry, Promise):
                entry.then_callbacks(
                    lambda v, i=i: settle(i, "fulfilled", "value", v),
                    lambda v, i=i: settle(i, "rejected", "reason", v))
            else:
                settle(i, "fulfilled", "value", entry)
        return out
    promise_ns.props["allSettled"] = HostFunction(promise_all_settled,
                                                  "allSettled")

    def promise_race(this, args):
        out = Promise(interp)
        settled = {"done": False}

        def first(settle_fn):
            def cb(v):
                if not settled["done"]:
                    settled["done"] = True
                    settle_fn(v)
            return cb
        ok, err = first(out.resolve), first(out.reject)
        for entry in list(interp.iterate(args[0])):
            if isinstance(entry, Promise):
                entry.then_callbacks(ok, err)
            else:
                ok(entry)
        return out
    promise_ns.props["race"] = HostFunction(promise_race, "race")
    g.declare("Promise", promise_ns)

    # Map / Set — SameValueZero keying: primitives by (type-tagged) value,
    # objects by identity. keys()/values()/entries() return arrays (spec:
    # iterators — for-of and spread over them behave identically here).
    def _svz_key(k):
        if isinstance(k, JSObject):
            return ("o", id(k))
        if isinstance(k, bool):
            return ("b", k)
        if isinstance(k, float):
            return ("n", "NaN" if math.isnan(k) else k)
        if isinstance(k, str):
            return ("s", k)
        return ("x", id(k))  # undefined / null singletons

    class MapObject(JSObject):
        class_name = "Map"

        def __init__(self):
            super().__init__()
            self.data = {}  # svz key -> (original key, value)

        def js_iter(self):
            return (JSArray([k, v]) for k, v in self.data.values())

        def js_get_prop(self, name, itp):
            d = self.data
            if name == "size":
                return float(len(d))
            if name == "get":
                return HostFunction(
                    lambda this, a: d.get(_svz_key(a[0]), (None, undefined))[1],
                    "get")
            if name == "set":
                def mset(this, a):
                    k = a[0] if a else undefined
                    v = a[1] if len(a) > 1 else undefined
                    d[_svz_key(k)] = (k, v)
                    return self
                return HostFunction(mset, "set")
            if name == "has":
                return HostFunction(
                    lambda this, a: _svz_key(a[0]) in d, "has")
            if name == "delete":
                return HostFunction(
                    lambda this, a: d.pop(_svz_key(a[0]), NOT_PRESENT)
                    is not NOT_PRESENT, "delete")
            if name == "clear":
                return HostFunction(
                    lambda this, a: (d.clear(), undefined)[1], "clear")
            if name == "keys":
                return HostFunction(
                    lambda this, a: JSArray([k for k, _ in d.values()]), "keys")
            if name == "values":
                return HostFunction(
                    lambda this, a: JSArray([v for _, v in d.values()]),
                    "values")
            if name == "entries":
                return HostFunction(
                    lambda this, a: JSArray(list(self.js_iter())), "entries")
            if name == "forEach":
                def meach(this, a):
                    for k, v in list(d.values()):
                        itp.call_function(a[0], undefined, [v, k, self])
                    return undefined
                return HostFunction(meach, "forEach")
            return super().js_get_prop(name, itp)

    class SetObject(JSObject):
        class_name = "Set"

        def __init__(self):
            super().__init__()
            self.data = {}  # svz key -> original value

        def js_iter(self):
            return iter(list(self.data.values()))

        def js_get_prop(self, name, itp):
            d = self.data
            if name == "size":
                return float(len(d))
            if name == "add":
                def sadd(this, a):
                    v = a[0] if a else undefined
                    d.setdefault(_svz_key(v), v)
                    return self
                return HostFunction(sadd, "add")
            if name == "has":
                return HostFunction(
                    lambda this, a: _svz_key(a[0]) in d, "has")
            if name == "delete":
                return HostFunction(
                    lambda this, a: d.pop(_svz_key(a[0]), NOT_PRESENT)
                    is not NOT_PRESENT, "delete")
            if name == "clear":
                return HostFunction(
                    lambda this, a: (d.clear(), undefined)[1], "clear")
            if name == "values":
                return HostFunction(
                    lambda this, a: JSArray(list(d.values())), "values")
            if name == "forEach":
                def seach(this, a):
                    for v in list(d.values()):
                        itp.call_function(a[0], undefined, [v, v, self])
                    return undefined
                return HostFunction(seach, "forEach")
            return super().js_get_prop(name, itp)

    def map_construct(args):
        m = MapObject()
        if args and args[0] is not undefined and args[0] is not null:
            for pair in interp.iterate(args[0]):
                k = interp.get_index(pair, 0.0)
                v = interp.get_index(pair, 1.0)
                m.data[_svz_key(k)] = (k, v)
        return m

    def set_construct(args):
        s = SetObject()
        if args and args[0] is not undefined and args[0] is not null:
            for v in interp.iterate(args[0]):
                s.data.setdefault(_svz_key(v), v)
        return s

    g.declare("Map", HostClass(
        "Map", map_construct, lambda v: isinstance(v, MapObject)))
    g.declare("Set", HostClass(
        "Set", set_construct, lambda v: isinstance(v, SetObject)))

    # Error family
    def error_class(kind):
        def construct(args):
            return make_error(
                kind, to_js_string(args[0], interp) if args else "")
        return HostClass(
            kind, construct,
            lambda v: isinstance(v, JSObject) and v.class_name == "Error")
    for kind in ("Error", "TypeError", "RangeError", "SyntaxError"):
        g.declare(kind, error_class(kind))

    # RegExp
    g.declare("RegExp", HostClass(
        "RegExp",
        lambda args: RegExpObject(
            to_js_string(args[0], interp),
            to_js_string(args[1], interp) if len(args) > 1 else ""),
        lambda v: isinstance(v, RegExpObject)))

    # Primitive conversion + URI helpers
    g.declare("Number", _number_ns(interp))
    g.declare("String", HostFunction(
        lambda this, args: to_js_string(args[0], interp) if args else "",
        "String"))
    g.declare("Boolean", HostFunction(
        lambda this, args: is_truthy(args[0]) if args else False, "Boolean"))
    g.declare("parseInt", HostFunction(_parse_int, "parseInt"))
    g.declare("parseFloat", HostFunction(_parse_float, "parseFloat"))
    g.declare("isNaN", HostFunction(
        lambda this, args: math.isnan(to_number(args[0])), "isNaN"))
    g.declare("encodeURIComponent", HostFunction(
        lambda this, args: _url.quote(to_js_string(args[0], interp), safe=""),
        "encodeURIComponent"))
    g.declare("decodeURIComponent", HostFunction(
        lambda this, args: _url.unquote(to_js_string(args[0], interp)),
        "decodeURIComponent"))
    g.declare("encodeURI", HostFunction(
        lambda this, args: _url.quote(to_js_string(args[0], interp),
                                      safe=":/?#[]@!$&'()*+,;="),
        "encodeURI"))
    g.declare("globalThis", JSObject())


def _number_ns(interp):
    ns = HostFunction(
        lambda this, args: to_number(args[0]) if args else 0.0, "Number")
    ns.props["isInteger"] = HostFunction(
        lambda this, args: isinstance(args[0], float) and
        args[0].is_integer(), "isInteger")
    ns.props["isFinite"] = HostFunction(
        lambda this, args: isinstance(args[0], float) and
        math.isfinite(args[0]), "isFinite")
    ns.props["parseFloat"] = HostFunction(_parse_float, "parseFloat")
    ns.props["MAX_SAFE_INTEGER"] = float(2**53 - 1)
    return ns


def _parse_int(this, args):
    s = to_js_string(args[0]).strip()
    radix = int(to_number(args[1])) if len(args) > 1 and \
        args[1] is not undefined else 10
    m = ""
    for i, c in enumerate(s):
        if c in "+-" and i == 0:
            m += c
        elif c.isdigit() or (radix == 16 and c.lower() in "abcdef"):
            m += c
        else:
            break
    try:
        return float(int(m, radix))
    except ValueError:
        return math.nan


def _parse_float(this, args):
    s = to_js_string(args[0]).strip()
    m = ""
    seen_dot = seen_e = False
    for i, c in enumerate(s):
        if c in "+-" and (i == 0 or s[i - 1].lower() == "e"):
            m += c
        elif c.isdigit():
            m += c
        elif c == "." and not seen_dot and not seen_e:
            m += c
            seen_dot = True
        elif c.lower() == "e" and not seen_e and m:
            m += c
            seen_e = True
        else:
            break
    try:
        return float(m)
    except ValueError:
        return math.nan
