"""Vendored JavaScript runtime for executing the shipped frontend in CI.

The reference runs its Angular frontends under Karma/Jasmine + Cypress
(`crud-web-apps/jupyter/frontend/cypress/e2e/`, `*.spec.ts`). This image
ships no node/bun/quickjs — so "execute the frontend" (VERDICT r2 #1)
means bringing our own engine: a tree-walking interpreter for the ES2017
subset the buildless SPAs use (arrow functions, async/await, template
literals, destructuring, spread, accessors — no classes/generators/
proxies, enforced by failing loudly on anything outside the subset), plus
a headless DOM, virtual timers and a fetch bridge into the real aiohttp
backends.

Semantics note: ``await`` resolves by synchronously draining the runtime's
microtask queue and I/O pump. Apps that await genuinely-future events
(a dialog button) would deadlock — ours ``.then()`` those, and the
interpreter raises a clear error rather than hanging.

Layout: lexer.py → jsparser.py (AST) → interp.py (evaluator + stdlib),
dom.py (document/elements/events), browser.py (page harness: HTML → DOM,
script loading, fetch/cookies, timers).
"""

from kubeflow_tpu.testing.jsrt.browser import Browser, BrowserError  # noqa: F401
from kubeflow_tpu.testing.jsrt.interp import Interpreter, JSException  # noqa: F401
