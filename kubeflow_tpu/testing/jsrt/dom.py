"""Headless DOM for the vendored JS runtime.

Implements the element/event surface the shipped frontends use: element
tree + attributes/classes/styles, bubbling events, form controls with
values, a CSS-selector subset (tag/#id/.class/[attr="v"]/:checked +
descendant combinator), classList, canvas-2d call recording. Everything is
a ``JSObject`` subclass so the interpreter's property protocol applies
directly.
"""

from __future__ import annotations

from html.parser import HTMLParser

from kubeflow_tpu.testing.jsrt.interp import (
    NOT_PRESENT,
    HostFunction,
    JSArray,
    JSObject,
    is_truthy,
    null,
    to_js_string,
    undefined,
)

VOID_ELEMENTS = {"br", "hr", "img", "input", "meta", "link", "area", "base",
                 "col", "embed", "source", "track", "wbr"}


def _method(name, fn):
    return HostFunction(fn, name)


class DomNode(JSObject):
    class_name = "Node"

    def __init__(self, document):
        super().__init__()
        self.document = document
        self.parent: DomNode | None = None
        self.child_nodes: list[DomNode] = []

    # -- tree ops (Python level) -------------------------------------------------

    def _append_node(self, node) -> None:
        if isinstance(node, str):
            node = TextNode(self.document, node)
        if node.parent is not None:
            node.parent.child_nodes.remove(node)
        node.parent = self
        self.child_nodes.append(node)

    def _remove_self(self) -> None:
        if self.parent is not None:
            self.parent.child_nodes.remove(self)
            self.parent = None

    def walk(self):
        for child in self.child_nodes:
            yield child
            if isinstance(child, Element):
                yield from child.walk()

    def text_content(self) -> str:
        out = []
        for node in [self] + list(self.walk()):
            if isinstance(node, TextNode):
                out.append(node.data)
        return "".join(out)

    def set_text_content(self, value: str) -> None:
        for child in list(self.child_nodes):
            child.parent = None
        self.child_nodes = []
        if value:
            self._append_node(TextNode(self.document, value))


class TextNode(DomNode):
    class_name = "Text"

    def __init__(self, document, data: str):
        super().__init__(document)
        self.data = data

    def js_get_prop(self, name, interp):
        if name == "textContent" or name == "data" or name == "nodeValue":
            return self.data
        if name == "nodeType":
            return 3.0
        return super().js_get_prop(name, interp)


def activate(doc, el) -> bool:
    """Click with the browser's pre-dispatch activation behavior: a
    checkbox toggles (a radio sets) its checked state before listeners
    see the event."""
    if isinstance(el, Element) and el.tag == "input":
        input_type = el.attrs.get("type")
        if input_type == "checkbox":
            current = el._checked if el._checked is not None \
                else ("checked" in el.attrs)
            el._checked = not current
        elif input_type == "radio":
            el._checked = True
    return doc.dispatch(el, Event("click"))


class Event(JSObject):
    class_name = "Event"

    def __init__(self, etype: str, props: dict | None = None):
        super().__init__()
        self.etype = etype
        self.target = null
        self.default_prevented = False
        self.propagation_stopped = False
        self.props.update(props or {})
        self.props["type"] = etype
        self.props["preventDefault"] = _method(
            "preventDefault",
            lambda this, args: setattr(self, "default_prevented", True) or undefined)
        self.props["stopPropagation"] = _method(
            "stopPropagation",
            lambda this, args: setattr(self, "propagation_stopped", True) or undefined)

    def js_get_prop(self, name, interp):
        if name == "target":
            return self.target
        if name == "defaultPrevented":
            return self.default_prevented
        return super().js_get_prop(name, interp)


class ClassList(JSObject):
    class_name = "DOMTokenList"

    def __init__(self, element: "Element"):
        super().__init__()
        self.element = element
        self.props["add"] = _method("add", self._add)
        self.props["remove"] = _method("remove", self._remove)
        self.props["toggle"] = _method("toggle", self._toggle)
        self.props["contains"] = _method("contains", self._contains)

    def _classes(self) -> list[str]:
        return [c for c in self.element.attrs.get("class", "").split() if c]

    def _store(self, classes: list[str]) -> None:
        self.element.attrs["class"] = " ".join(classes)

    def _add(self, this, args):
        classes = self._classes()
        for a in args:
            name = to_js_string(a)
            if name not in classes:
                classes.append(name)
        self._store(classes)
        return undefined

    def _remove(self, this, args):
        names = {to_js_string(a) for a in args}
        self._store([c for c in self._classes() if c not in names])
        return undefined

    def _toggle(self, this, args):
        name = to_js_string(args[0])
        classes = self._classes()
        if len(args) > 1:
            want = is_truthy(args[1])
        else:
            want = name not in classes
        if want and name not in classes:
            classes.append(name)
        if not want and name in classes:
            classes.remove(name)
        self._store(classes)
        return want

    def _contains(self, this, args):
        return to_js_string(args[0]) in self._classes()


class CanvasContext(JSObject):
    class_name = "CanvasRenderingContext2D"

    def __init__(self):
        super().__init__()
        self.calls: list[tuple] = []
        for name in ("clearRect", "fillText", "beginPath", "moveTo", "lineTo",
                     "stroke", "fill", "arc", "rect", "fillRect", "closePath"):
            self.props[name] = _method(
                name,
                lambda this, args, n=name: (
                    self.calls.append((n, [a for a in args])), undefined)[1])


class Element(DomNode):
    class_name = "Element"

    def __init__(self, document, tag: str):
        super().__init__(document)
        self.tag = tag.lower()
        self.attrs: dict[str, str] = {}
        self.listeners: dict[str, list] = {}
        self.style = JSObject()
        self._value: str | None = None
        self._checked: bool | None = None
        self.disabled = False
        self.scroll_top = 0.0
        self.scroll_left = 0.0
        self._sel: tuple[int, int] | None = None
        self._canvas_ctx: CanvasContext | None = None

    # -- js property protocol ----------------------------------------------------

    def js_get_prop(self, name, interp):  # noqa: PLR0911, PLR0912 — dispatch table
        if name in self.setters or name in self.getters or name in self.props:
            return super().js_get_prop(name, interp)
        if name == "tagName":
            return self.tag.upper()
        if name == "nodeType":
            return 1.0
        if name == "id":
            return self.attrs.get("id", "")
        if name == "className":
            return self.attrs.get("class", "")
        if name == "classList":
            return ClassList(self)
        if name == "style":
            return self.style
        if name == "textContent" or name == "innerText":
            return self.text_content()
        if name == "value":
            return self.get_value()
        if name == "checked":
            return self._checked if self._checked is not None \
                else ("checked" in self.attrs)
        if name == "disabled":
            return self.disabled
        if name == "name":
            return self.attrs.get("name", "")
        if name == "type":
            return self.attrs.get("type", "")
        if name == "href":
            return self.attrs.get("href", "")
        if name == "title":
            return self.attrs.get("title", "")
        if name == "children":
            return JSArray([c for c in self.child_nodes
                            if isinstance(c, Element)])
        if name == "childNodes":
            return JSArray(list(self.child_nodes))
        if name == "parentElement" or name == "parentNode":
            return self.parent if self.parent is not None else null
        if name == "firstChild":
            return self.child_nodes[0] if self.child_nodes else null
        if name in ("nextElementSibling", "previousElementSibling"):
            parent = self.parent
            if parent is None:
                return null
            sibs = [c for c in parent.child_nodes if isinstance(c, Element)]
            try:
                at = sibs.index(self)
            except ValueError:  # pragma: no cover - detached node
                return null
            at += 1 if name == "nextElementSibling" else -1
            return sibs[at] if 0 <= at < len(sibs) else null
        if name == "options":
            return JSArray([c for c in self.walk()
                            if isinstance(c, Element) and c.tag == "option"])
        if name == "scrollTop":
            return self.scroll_top
        if name == "scrollLeft":
            return self.scroll_left
        if name == "selectionStart":
            return float(self._selection()[0])
        if name == "selectionEnd":
            return float(self._selection()[1])
        if name == "setSelectionRange":
            def set_range(this, args):
                self._sel = (int(args[0]), int(args[1]))
                return undefined
            return _method(name, set_range)
        if name == "scrollHeight":
            return 1000.0
        if name == "clientWidth":
            return float(int(self.attrs.get("width", 0) or 0))
        if name == "clientHeight":
            return float(int(self.attrs.get("height", 0) or 0))
        if name == "width":
            return float(int(self.attrs.get("width", 0) or 0))
        if name == "height":
            return float(int(self.attrs.get("height", 0) or 0))
        if name == "dataset":
            data = JSObject()
            for k, v in self.attrs.items():
                if k.startswith("data-"):
                    parts = k[5:].split("-")
                    camel = parts[0] + "".join(p.title() for p in parts[1:])
                    data.props[camel] = v
            return data
        method = self._dom_method(name, interp)
        if method is not NOT_PRESENT:
            return method
        if name in self.attrs:
            return self.attrs[name]
        return undefined

    def js_set_prop(self, name, value, interp) -> bool:
        if name in self.setters:
            return super().js_set_prop(name, value, interp)
        if name == "textContent" or name == "innerText":
            self.set_text_content(to_js_string(value, interp))
            return True
        if name == "className":
            self.attrs["class"] = to_js_string(value, interp)
            return True
        if name == "value":
            self._value = to_js_string(value, interp)
            return True
        if name == "checked":
            self._checked = is_truthy(value)
            return True
        if name == "disabled":
            self.disabled = is_truthy(value)
            return True
        if name == "id":
            self.attrs["id"] = to_js_string(value, interp)
            return True
        if name == "title":
            self.attrs["title"] = to_js_string(value, interp)
            return True
        if name == "scrollTop":
            self.scroll_top = float(to_js_string(value, interp) == "" or value)
            return True
        if name == "scrollLeft":
            self.scroll_left = float(to_js_string(value, interp) == "" or value)
            return True
        if name == "selectionStart":
            self._sel = (int(value), self._selection()[1])
            return True
        if name == "selectionEnd":
            self._sel = (self._selection()[0], int(value))
            return True
        if name in ("width", "height"):
            self.attrs[name] = str(int(value)) if isinstance(value, float) \
                else to_js_string(value, interp)
            return True
        return super().js_set_prop(name, value, interp)

    # -- value semantics ---------------------------------------------------------

    def _selection(self) -> tuple[int, int]:
        """Caret range clamped to the current value (collapsed at the end
        by default, like a freshly-focused real textarea)."""
        n = len(self.get_value())
        if self._sel is None:
            return (n, n)
        return (min(self._sel[0], n), min(self._sel[1], n))

    def get_value(self) -> str:
        if self.tag == "select":
            if self._value is not None:
                options = [c for c in self.walk()
                           if isinstance(c, Element) and c.tag == "option"]
                for o in options:
                    if o.option_value() == self._value:
                        return self._value
            options = [c for c in self.walk()
                       if isinstance(c, Element) and c.tag == "option"]
            for o in options:
                if "selected" in o.attrs:
                    return o.option_value()
            return options[0].option_value() if options else ""
        if self._value is not None:
            return self._value
        return self.attrs.get("value", "")

    def option_value(self) -> str:
        return self.attrs.get("value", self.text_content())

    # -- methods -----------------------------------------------------------------

    def _dom_method(self, name, interp):
        doc = self.document

        if name == "append":
            def append(this, args):
                for a in args:
                    self._append_node(a if isinstance(a, DomNode)
                                      else to_js_string(a, interp))
                return undefined
            return _method(name, append)
        if name == "appendChild":
            def append_child(this, args):
                self._append_node(args[0])
                return args[0]
            return _method(name, append_child)
        if name == "prepend":
            def prepend(this, args):
                for a in reversed(args):
                    node = a if isinstance(a, DomNode) \
                        else TextNode(doc, to_js_string(a, interp))
                    if node.parent is not None:
                        node.parent.child_nodes.remove(node)
                    node.parent = self
                    self.child_nodes.insert(0, node)
                return undefined
            return _method(name, prepend)
        if name == "replaceChildren":
            def replace_children(this, args):
                for child in list(self.child_nodes):
                    child.parent = None
                self.child_nodes = []
                for a in args:
                    self._append_node(a if isinstance(a, DomNode)
                                      else to_js_string(a, interp))
                return undefined
            return _method(name, replace_children)
        if name == "remove":
            return _method(name, lambda this, args: (self._remove_self(),
                                                     undefined)[1])
        if name == "removeChild":
            def remove_child(this, args):
                child = args[0]
                child._remove_self()
                return child
            return _method(name, remove_child)
        if name == "addEventListener":
            def add_listener(this, args):
                etype = to_js_string(args[0], interp)
                self.listeners.setdefault(etype, []).append(args[1])
                return undefined
            return _method(name, add_listener)
        if name == "removeEventListener":
            def remove_listener(this, args):
                etype = to_js_string(args[0], interp)
                if args[1] in self.listeners.get(etype, []):
                    self.listeners[etype].remove(args[1])
                return undefined
            return _method(name, remove_listener)
        if name == "dispatchEvent":
            return _method(name, lambda this, args: doc.dispatch(self, args[0]))
        if name == "setAttribute":
            def set_attr(this, args):
                self.attrs[to_js_string(args[0], interp)] = \
                    to_js_string(args[1], interp)
                return undefined
            return _method(name, set_attr)
        if name == "getAttribute":
            def get_attr(this, args):
                key = to_js_string(args[0], interp)
                return self.attrs.get(key, null)
            return _method(name, get_attr)
        if name == "removeAttribute":
            def remove_attr(this, args):
                self.attrs.pop(to_js_string(args[0], interp), None)
                return undefined
            return _method(name, remove_attr)
        if name == "hasAttribute":
            return _method(name, lambda this, args: to_js_string(
                args[0], interp) in self.attrs)
        if name == "querySelector":
            def qs(this, args):
                hits = select(self, to_js_string(args[0], interp))
                return hits[0] if hits else null
            return _method(name, qs)
        if name == "querySelectorAll":
            def qsa(this, args):
                return NodeList(select(self, to_js_string(args[0], interp)))
            return _method(name, qsa)
        if name == "closest":
            def closest(this, args):
                selector = to_js_string(args[0], interp)
                node = self
                while node is not None and isinstance(node, Element):
                    if matches(node, selector):
                        return node
                    node = node.parent
                return null
            return _method(name, closest)
        if name == "contains":
            return _method(name, lambda this, args: args[0] is self or
                           args[0] in list(self.walk()))
        if name == "matches":
            return _method(name, lambda this, args: matches(
                self, to_js_string(args[0], interp)))
        if name == "focus":
            def do_focus(this, args):
                doc._active_element = self
                return undefined
            return _method(name, do_focus)
        if name == "blur":
            def do_blur(this, args):
                if getattr(doc, "_active_element", None) is self:
                    doc._active_element = None
                return undefined
            return _method(name, do_blur)
        if name == "click":
            def click(this, args):
                return activate(doc, self)
            return _method(name, click)
        if name == "getContext":
            def get_context(this, args):
                if self._canvas_ctx is None:
                    self._canvas_ctx = CanvasContext()
                return self._canvas_ctx
            return _method(name, get_context)
        if name == "submit" and self.tag == "form":
            def submit(this, args):
                return doc.dispatch(self, Event("submit"))
            return _method(name, submit)
        if name == "reset" and self.tag == "form":
            def reset(this, args):
                for el in self.walk():
                    if isinstance(el, Element):
                        el._value = None
                        el._checked = None
                return undefined
            return _method(name, reset)
        return NOT_PRESENT


class NodeList(JSArray):
    class_name = "NodeList"

    def js_iter(self):
        return list(self.items)


class Document(Element):
    class_name = "Document"

    def __init__(self, browser):
        super().__init__(None, "#document")
        self.document = self
        self.browser = browser
        self.body = Element(self, "body")
        self.head = Element(self, "head")
        html = Element(self, "html")
        self._append_node(html)
        html._append_node(self.head)
        html._append_node(self.body)

    # dispatch with bubbling; returns not-default-prevented like the real API.
    def dispatch(self, target, event: Event):
        event.target = target
        node = target
        while node is not None:
            listeners = list(getattr(node, "listeners", {}).get(event.etype, []))
            for listener in listeners:
                result = self.browser.interp.call_function(
                    listener, node, [event])
                # An async handler that throws yields a rejected promise no
                # one will ever .catch — record it so the harness fails
                # loudly instead of shipping the app bug green.
                self.browser.observe_rejection(result)
                if event.propagation_stopped:
                    break
            if event.propagation_stopped:
                break
            node = getattr(node, "parent", None)
        self.browser.interp.run_microtasks()
        return not event.default_prevented

    def js_get_prop(self, name, interp):
        if name == "body":
            return self.body
        if name == "head":
            return self.head
        if name == "activeElement":
            # Tracked by Element.focus()/blur(); components use it for
            # modal focus restore (confirmDialog/drawer opener capture).
            active = getattr(self, "_active_element", None)
            return active if active is not None else self.body
        if name == "cookie":
            return self.browser.cookie_string()
        if name == "createElement":
            return _method(name, lambda this, args: Element(
                self, to_js_string(args[0], interp)))
        if name == "createTextNode":
            return _method(name, lambda this, args: TextNode(
                self, to_js_string(args[0], interp)))
        if name == "getElementById":
            def by_id(this, args):
                want = to_js_string(args[0], interp)
                for node in self.walk():
                    if isinstance(node, Element) and \
                            node.attrs.get("id") == want:
                        return node
                return null
            return _method(name, by_id)
        if name == "documentElement":
            return self.child_nodes[0]
        return super().js_get_prop(name, interp)

    def js_set_prop(self, name, value, interp) -> bool:
        if name == "cookie":
            self.browser.set_cookie_string(to_js_string(value, interp))
            return True
        if name == "title":
            self.attrs["title"] = to_js_string(value, interp)
            return True
        return super().js_set_prop(name, value, interp)


# ---- selector engine -------------------------------------------------------------


def _parse_compound(compound: str):
    """tag?(#id)?(.class)*([attr="v"])*(:checked)? → matcher parts."""
    import re

    tag = None
    ident = None
    classes = []
    attrs = []
    pseudo = []
    pattern = re.compile(
        r"""
        (?P<tag>^[a-zA-Z][\w-]*)
        |\#(?P<id>[\w-]+)
        |\.(?P<cls>[\w-]+)
        |\[(?P<attr>[\w-]+)(?:=(?P<q>["']?)(?P<val>[^\]"']*)(?P=q))?\]
        |:(?P<pseudo>[\w-]+)
        """,
        re.VERBOSE,
    )
    pos = 0
    while pos < len(compound):
        m = pattern.match(compound, pos)
        if not m:
            raise ValueError(f"unsupported selector {compound!r}")
        if m.group("tag"):
            tag = m.group("tag").lower()
        elif m.group("id"):
            ident = m.group("id")
        elif m.group("cls"):
            classes.append(m.group("cls"))
        elif m.group("attr"):
            attrs.append((m.group("attr"), m.group("val")))
        elif m.group("pseudo"):
            pseudo.append(m.group("pseudo"))
        pos = m.end()
    return tag, ident, classes, attrs, pseudo


def _matches_compound(el: Element, compound: str) -> bool:
    tag, ident, classes, attrs, pseudo = _parse_compound(compound)
    if tag is not None and el.tag != tag:
        return False
    if ident is not None and el.attrs.get("id") != ident:
        return False
    el_classes = el.attrs.get("class", "").split()
    for c in classes:
        if c not in el_classes:
            return False
    for key, val in attrs:
        if val is None:
            if key not in el.attrs:
                return False
        elif el.attrs.get(key) != val:
            return False
    for p in pseudo:
        if p == "checked":
            checked = el._checked if el._checked is not None \
                else ("checked" in el.attrs)
            if not checked:
                return False
        elif p == "disabled":
            if not el.disabled:
                return False
        else:
            raise ValueError(f"unsupported pseudo-class :{p}")
    return True


def matches(el: Element, selector: str) -> bool:
    parts = selector.strip().split()
    if not parts:
        return False
    if not _matches_compound(el, parts[-1]):
        return False
    node = el.parent
    remaining = parts[:-1]
    while remaining:
        if node is None or not isinstance(node, Element):
            return False
        if _matches_compound(node, remaining[-1]):
            remaining.pop()
        node = node.parent
    return True


def select(root: DomNode, selector: str) -> list:
    out = []
    for part in selector.split(","):
        for node in root.walk():
            if isinstance(node, Element) and matches(node, part) and \
                    node not in out:
                out.append(node)
    return out


# ---- HTML parsing ----------------------------------------------------------------


class _DomBuilder(HTMLParser):
    def __init__(self, document: Document):
        super().__init__(convert_charrefs=True)
        self.document = document
        self.stack: list[Element] = []
        self.scripts: list[str] = []   # external script srcs, in order
        self._in_inline_script = False
        self.inline_scripts: list[str] = []

    def current(self) -> Element:
        return self.stack[-1] if self.stack else self.document.body

    def handle_starttag(self, tag, attrs):
        if tag == "script":
            src = dict(attrs).get("src")
            if src:
                self.scripts.append(src)
            else:
                self._in_inline_script = True
                self.inline_scripts.append("")
            return
        if tag in ("html", "head", "body", "meta", "link", "title"):
            return
        el = Element(self.document, tag)
        for key, value in attrs:
            el.attrs[key] = value if value is not None else ""
        self.current()._append_node(el)
        if tag not in VOID_ELEMENTS:
            self.stack.append(el)

    def handle_endtag(self, tag):
        if tag == "script":
            self._in_inline_script = False
            return
        if self.stack and self.stack[-1].tag == tag:
            self.stack.pop()

    def handle_data(self, data):
        if self._in_inline_script:
            self.inline_scripts[-1] += data
            return
        if data.strip():
            self.current()._append_node(TextNode(self.document, data))


def build_dom(document: Document, html: str):
    builder = _DomBuilder(document)
    builder.feed(html)
    return builder.scripts, builder.inline_scripts
