"""Browser harness: page loading, fetch bridge, cookies, virtual timers.

``Browser(http)`` takes a synchronous transport:
``http(method, path, headers, body) -> (status, reason, resp_headers, text)``
— tests adapt an aiohttp ``TestClient`` to this (testing/jsweb.py), so the
JS runs against the real backend handlers, CSRF cookies and all.

Time is virtual: ``setTimeout``/``setInterval`` park callbacks on a heap
that only ``advance(ms)`` drains — polling loops are stepped
deterministically, never slept through.
"""

from __future__ import annotations

import itertools

from kubeflow_tpu.testing.jsrt import dom
from kubeflow_tpu.testing.jsrt.interp import (
    HostClass,
    HostFunction,
    Interpreter,
    JSArray,
    JSException,
    JSObject,
    Promise,
    is_truthy,
    null,
    python_to_js,
    to_js_string,
    to_number,
    undefined,
)


class BrowserError(RuntimeError):
    pass


class Browser:
    def __init__(self, http, base_path: str = ""):
        self.http = http
        self.base_path = base_path.rstrip("/")
        self.interp = Interpreter()
        self.interp.io_pump = lambda: False
        self.clock_ms = 1_700_000_000_000.0
        self.interp._now = lambda: self.clock_ms / 1000.0
        self.cookies: dict[str, str] = {}
        self.timers: list[dict] = []
        self._timer_ids = itertools.count(1)
        self.local_storage: dict[str, str] = {}
        self.window_listeners: dict[str, list] = {}
        self.location_path = "/"
        self.location_hash = ""
        self.document = dom.Document(self)
        self.blobs: list = []
        self._install_globals()

    # ---- unhandled rejections --------------------------------------------------

    def observe_rejection(self, value) -> None:
        """Attach a last-resort rejection observer to a promise returned by
        an event/timer callback: if nothing else handles it, it lands in
        ``interp.unhandled_rejections`` and the next harness step raises."""
        if not isinstance(value, Promise):
            return

        def record(reason):
            if not value.handled:
                self.interp.unhandled_rejections.append(reason)
        value.callbacks.append((None, record, None))
        if value.state == Promise.REJECTED:
            value._schedule()

    def check_rejections(self) -> None:
        pending = self.interp.unhandled_rejections
        if pending:
            self.interp.unhandled_rejections = []
            from kubeflow_tpu.testing.jsrt.interp import to_js_string_safe

            raise BrowserError(
                "unhandled promise rejection(s) in event/timer callbacks: "
                + "; ".join(to_js_string_safe(r) for r in pending))

    # ---- cookies ---------------------------------------------------------------

    def cookie_string(self) -> str:
        return "; ".join(f"{k}={v}" for k, v in self.cookies.items())

    def set_cookie_string(self, s: str) -> None:
        parts = s.split(";")
        first = parts[0]
        if "=" not in first:
            return
        k, _, v = first.partition("=")
        k = k.strip()
        # Deletion semantics: Max-Age<=0 or an already-past expires removes
        # the cookie (the logout path) instead of storing an empty value.
        for attr in parts[1:]:
            akey, _, aval = attr.strip().partition("=")
            if akey.lower() == "max-age" and aval.strip().lstrip("-").isdigit() \
                    and int(aval) <= 0:
                self.cookies.pop(k, None)
                return
            if akey.lower() == "expires" and ("1970" in aval or "1969" in aval):
                self.cookies.pop(k, None)
                return
        self.cookies[k] = v.strip()

    def _absorb_set_cookie(self, resp_headers) -> None:
        for key, value in resp_headers:
            if key.lower() == "set-cookie":
                self.set_cookie_string(value)

    # ---- page loading ----------------------------------------------------------

    def load(self, path: str = "/") -> None:
        """GET the page, build the DOM, then fetch+run its scripts in
        order — the same sequence a real browser performs."""
        status, reason, headers, text = self._request("GET", path, {}, None)
        if status != 200:
            raise BrowserError(f"page load {path} -> {status} {reason}")
        scripts, inline = dom.build_dom(self.document, text)
        for src in scripts:
            s_status, s_reason, _, js_src = self._request("GET", src, {}, None)
            if s_status != 200:
                raise BrowserError(f"script {src} -> {s_status} {s_reason}")
            self.interp.run(js_src, filename=src)
        for js_src in inline:
            self.interp.run(js_src, filename=f"{path}#inline")
        self.interp.run_microtasks()

    def _request(self, method, path, headers, body):
        if not path.startswith("/"):
            path = "/" + path
        send_headers = dict(headers)
        if self.cookies:
            send_headers["Cookie"] = self.cookie_string()
        status, reason, resp_headers, text = self.http(
            method, self.base_path + path, send_headers, body)
        self._absorb_set_cookie(resp_headers)
        return status, reason, resp_headers, text

    # ---- timers ----------------------------------------------------------------

    def advance(self, ms: float) -> int:
        """Advance the virtual clock, firing due timers in order. Returns
        the number of callbacks fired."""
        deadline = self.clock_ms + ms
        fired = 0
        while True:
            due = [t for t in self.timers if t["due"] <= deadline]
            if not due:
                break
            t = min(due, key=lambda x: (x["due"], x["id"]))
            self.clock_ms = max(self.clock_ms, t["due"])
            if t["interval"] is None:
                self.timers.remove(t)
            else:
                t["due"] += t["interval"]
            result = self.interp.call_function(
                t["fn"], undefined, list(t["args"]))
            self.observe_rejection(result)
            self.interp.run_microtasks()
            fired += 1
            if fired > 10_000:
                raise BrowserError("timer storm: >10k callbacks in one advance")
        self.clock_ms = deadline
        self.check_rejections()
        return fired

    # ---- test-facing conveniences ----------------------------------------------

    def query(self, selector: str):
        hits = dom.select(self.document, selector)
        return hits[0] if hits else None

    def query_all(self, selector: str) -> list:
        return dom.select(self.document, selector)

    def text(self, selector: str) -> str:
        el = self.query(selector)
        if el is None:
            raise BrowserError(f"no element matches {selector!r}")
        return el.text_content()

    def click(self, target) -> bool:
        el = self.query(target) if isinstance(target, str) else target
        if el is None:
            raise BrowserError(f"no element matches {target!r}")
        result = dom.activate(self.document, el)
        self.check_rejections()
        return result

    def focus(self, target) -> None:
        el = self.query(target) if isinstance(target, str) else target
        if el is None:
            raise BrowserError(f"no element matches {target!r}")
        self.document._active_element = el

    def set_value(self, selector: str, value: str, *, fire="input") -> None:
        el = self.query(selector)
        if el is None:
            raise BrowserError(f"no element matches {selector!r}")
        el._value = value
        if fire:
            self.document.dispatch(el, dom.Event(fire))

    def change(self, selector: str, value: str | None = None) -> None:
        el = self.query(selector)
        if el is None:
            raise BrowserError(f"no element matches {selector!r}")
        if value is not None:
            el._value = value
        self.document.dispatch(el, dom.Event("change"))

    def submit(self, selector: str) -> bool:
        el = self.query(selector)
        if el is None:
            raise BrowserError(f"no element matches {selector!r}")
        result = self.document.dispatch(el, dom.Event("submit"))
        self.check_rejections()
        return result

    def keydown(self, key: str, selector=None, shift: bool = False) -> None:
        target = self.document.body
        if selector is not None:
            target = (self.query(selector) if isinstance(selector, str)
                      else selector)
            if target is None:
                raise BrowserError(f"no element matches {selector!r}")
        self.document.dispatch(
            target, dom.Event("keydown", {"key": key, "shiftKey": shift}))

    def eval(self, src: str):
        """Evaluate a JS expression/program for assertions; returns the
        value of a trailing expression statement if any."""
        from kubeflow_tpu.testing.jsrt.jsparser import parse

        ast = parse(src, "<eval>")
        result = undefined
        env = self.interp.global_env
        for stmt in ast:
            if stmt[0] == "expr_stmt":
                result = self.interp.eval(stmt[1], env, undefined)
            else:
                self.interp.exec_stmt(stmt, env, undefined)
        self.interp.run_microtasks()
        return result

    def fire_window(self, etype: str, props: dict | None = None) -> None:
        event = dom.Event(etype, props or {})
        for listener in list(self.window_listeners.get(etype, [])):
            self.observe_rejection(
                self.interp.call_function(listener, undefined, [event]))
        self.interp.run_microtasks()

    def fire_storage(self, key: str, new_value: str) -> None:
        """Cross-window localStorage change (iframe namespace sync)."""
        self.local_storage[key] = new_value
        self.fire_window("storage", {"key": key, "newValue": new_value})

    # ---- globals ---------------------------------------------------------------

    def _install_globals(self) -> None:
        interp = self.interp
        g = interp.global_env
        g.declare("document", self.document)

        # Node for `instanceof Node`.
        g.declare("Node", HostClass(
            "Node", lambda args: _raise(interp, "Node is not constructible"),
            lambda v: isinstance(v, dom.DomNode)))
        g.declare("Event", HostClass(
            "Event",
            lambda args: dom.Event(to_js_string(args[0], interp)),
            lambda v: isinstance(v, dom.Event)))

        # window — addEventListener + a handful of mirrors.
        window = JSObject()

        def window_add_listener(this, args):
            etype = to_js_string(args[0], interp)
            self.window_listeners.setdefault(etype, []).append(args[1])
            return undefined
        window.props["addEventListener"] = HostFunction(
            window_add_listener, "addEventListener")

        def window_remove_listener(this, args):
            etype = to_js_string(args[0], interp)
            listeners = self.window_listeners.get(etype, [])
            if args[1] in listeners:
                listeners.remove(args[1])
            return undefined
        window.props["removeEventListener"] = HostFunction(
            window_remove_listener, "removeEventListener")
        g.declare("window", window)

        # location + history
        browser = self

        class Location(JSObject):
            def js_get_prop(self, name, itp):
                if name == "hash":
                    return browser.location_hash
                if name == "pathname":
                    return browser.location_path
                if name == "href":
                    return browser.location_path + browser.location_hash
                return super().js_get_prop(name, itp)

            def js_set_prop(self, name, value, itp):
                if name == "hash":
                    new = to_js_string(value, itp)
                    if new and not new.startswith("#"):
                        new = "#" + new
                    changed = new != browser.location_hash
                    browser.location_hash = new
                    if changed:
                        browser.fire_window("hashchange")
                    return True
                return super().js_set_prop(name, value, itp)
        location = Location()
        g.declare("location", location)
        window.props["location"] = location

        history = JSObject()

        def replace_state(this, args):
            url = to_js_string(args[2], interp) if len(args) > 2 else ""
            if url.startswith("#"):
                self.location_hash = url
            elif url:
                self.location_path = url.split("#")[0]
                self.location_hash = ("#" + url.split("#", 1)[1]) \
                    if "#" in url else ""
            return undefined
        history.props["replaceState"] = HostFunction(replace_state,
                                                     "replaceState")
        history.props["pushState"] = HostFunction(replace_state, "pushState")
        g.declare("history", history)

        # localStorage
        storage = JSObject()
        storage.props["getItem"] = HostFunction(
            lambda this, args: self.local_storage.get(
                to_js_string(args[0], interp), null), "getItem")
        storage.props["setItem"] = HostFunction(
            lambda this, args: (self.local_storage.__setitem__(
                to_js_string(args[0], interp), to_js_string(args[1], interp)),
                undefined)[1], "setItem")
        storage.props["removeItem"] = HostFunction(
            lambda this, args: (self.local_storage.pop(
                to_js_string(args[0], interp), None), undefined)[1],
            "removeItem")
        g.declare("localStorage", storage)

        # timers
        def set_timer(interval: bool):
            def impl(this, args):
                fn = args[0]
                delay = to_number(args[1]) if len(args) > 1 else 0.0
                tid = float(next(self._timer_ids))
                self.timers.append({
                    "id": tid, "fn": fn, "due": self.clock_ms + delay,
                    "interval": delay if interval else None,
                    "args": list(args[2:]),
                })
                return tid
            return impl
        g.declare("setTimeout", HostFunction(set_timer(False), "setTimeout"))
        g.declare("setInterval", HostFunction(set_timer(True), "setInterval"))

        def clear_timer(this, args):
            if args and isinstance(args[0], float):
                self.timers = [t for t in self.timers if t["id"] != args[0]]
            return undefined
        g.declare("clearTimeout", HostFunction(clear_timer, "clearTimeout"))
        g.declare("clearInterval", HostFunction(clear_timer, "clearInterval"))

        # fetch
        def fetch(this, args):
            path = to_js_string(args[0], interp)
            options = args[1] if len(args) > 1 and \
                isinstance(args[1], JSObject) else JSObject()
            method = to_js_string(
                options.props.get("method", "GET"), interp).upper()
            headers = {}
            h = options.props.get("headers")
            if isinstance(h, JSObject):
                for k in h.own_keys():
                    # get_prop, not a raw props read: a getter-defined
                    # header must invoke the getter (and accessor slots
                    # never leak their placeholder).
                    headers[k] = to_js_string(interp.get_prop(h, k), interp)
            body = options.props.get("body")
            body_bytes = to_js_string(body, interp).encode() \
                if body is not None and body is not undefined else None
            promise = Promise(interp)
            try:
                status, reason, resp_headers, text = self._request(
                    method, path, headers, body_bytes)
            except Exception as e:  # network-level failure → rejected promise
                from kubeflow_tpu.testing.jsrt.interp import make_error

                promise.reject(make_error("TypeError", f"fetch failed: {e}"))
                return promise
            promise.resolve(_response_object(interp, status, reason, text))
            return promise
        g.declare("fetch", HostFunction(fetch, "fetch"))

        # FormData
        def formdata_construct(args):
            form = args[0] if args else None
            data: list[tuple[str, str]] = []
            if isinstance(form, dom.Element):
                for el in form.walk():
                    if not isinstance(el, dom.Element):
                        continue
                    name = el.attrs.get("name")
                    if not name or el.disabled:
                        continue
                    if el.tag == "input":
                        itype = el.attrs.get("type", "text")
                        if itype in ("checkbox", "radio"):
                            checked = el._checked if el._checked is not None \
                                else ("checked" in el.attrs)
                            if checked:
                                data.append((name, el.get_value() or "on"))
                        else:
                            data.append((name, el.get_value()))
                    elif el.tag in ("select", "textarea"):
                        data.append((name, el.get_value()))
            fd = JSObject()
            fd.class_name = "FormData"

            def get(this, a):
                want = to_js_string(a[0], interp)
                for k, v in data:
                    if k == want:
                        return v
                return null

            def get_all(this, a):
                want = to_js_string(a[0], interp)
                return JSArray([v for k, v in data if k == want])
            fd.props["get"] = HostFunction(get, "get")
            fd.props["getAll"] = HostFunction(get_all, "getAll")
            fd.props["has"] = HostFunction(
                lambda this, a: any(
                    k == to_js_string(a[0], interp) for k, _ in data), "has")
            return fd
        g.declare("FormData", HostClass("FormData", formdata_construct))

        # Blob + URL
        def blob_construct(args):
            parts = args[0] if args else JSArray([])
            blob = JSObject()
            blob.class_name = "Blob"
            content = "".join(
                to_js_string(p, interp) for p in
                (parts.items if isinstance(parts, JSArray) else []))
            blob.props["size"] = float(len(content))
            blob.host_content = content
            self.blobs.append(blob)
            return blob
        g.declare("Blob", HostClass(
            "Blob", blob_construct,
            lambda v: isinstance(v, JSObject) and v.class_name == "Blob"))

        url_ns = JSObject()
        url_ns.props["createObjectURL"] = HostFunction(
            lambda this, args: f"blob:mock-{len(self.blobs)}",
            "createObjectURL")
        url_ns.props["revokeObjectURL"] = HostFunction(
            lambda this, args: undefined, "revokeObjectURL")
        g.declare("URL", url_ns)

        g.declare("alert", HostFunction(
            lambda this, args: undefined, "alert"))
        g.declare("requestAnimationFrame", HostFunction(
            lambda this, args: (interp.call_function(
                args[0], undefined, [self.clock_ms]), 0.0)[1],
            "requestAnimationFrame"))
        g.declare("navigator", python_to_js({"userAgent": "jsrt/1.0"}))


def _response_object(interp, status, reason, text) -> JSObject:
    resp = JSObject()
    resp.class_name = "Response"
    resp.props["ok"] = 200 <= status < 300
    resp.props["status"] = float(status)
    resp.props["statusText"] = reason or ""

    def json_method(this, args):
        import json as _json

        p = Promise(interp)
        try:
            p.resolve(python_to_js(_json.loads(text or "")))
        except ValueError as e:
            from kubeflow_tpu.testing.jsrt.interp import make_error

            p.reject(make_error("SyntaxError", f"invalid JSON: {e}"))
        return p
    resp.props["json"] = HostFunction(json_method, "json")

    def text_method(this, args):
        p = Promise(interp)
        p.resolve(text or "")
        return p
    resp.props["text"] = HostFunction(text_method, "text")
    return resp


def _raise(interp, msg):
    from kubeflow_tpu.testing.jsrt.interp import make_error

    raise JSException(make_error("TypeError", msg))
