"""JS tokenizer for the frontend subset (see package docstring).

Tokens: (type, value, line). Types: num, str, template, regex, ident,
keyword, punct, eof. Template tokens carry the decomposed parts:
``("template", [("str", s) | ("expr", token_list), ...], line)`` — the
parser re-parses each expr token list.
"""

from __future__ import annotations

KEYWORDS = {
    "var", "let", "const", "function", "return", "if", "else", "for", "while",
    "do", "break", "continue", "new", "delete", "typeof", "instanceof", "in",
    "of", "try", "catch", "finally", "throw", "null", "undefined", "true",
    "false", "this", "async", "await", "void", "get", "set", "switch", "case",
    "default",
}

# Longest first so '===' wins over '=='.
PUNCT = sorted(
    [
        "===", "!==", "**=", "...", "=>", "==", "!=", "<=", ">=", "&&", "||",
        "??", "?.", "**",
        "++", "--", "+=", "-=", "*=", "/=", "%=", "&=", "|=", "^=", "<<", ">>",
        "{", "}", "(", ")", "[", "]", ";", ",", "<", ">", "+", "-", "*", "/",
        "%", "&", "|", "^", "!", "~", "?", ":", "=", ".",
    ],
    key=len,
    reverse=True,
)

ESCAPES = {"n": "\n", "t": "\t", "r": "\r", "b": "\b", "f": "\f", "v": "\v",
           "0": "\0", "'": "'", '"': '"', "`": "`", "\\": "\\", "/": "/",
           "\n": ""}


class LexError(SyntaxError):
    pass


def _ident_start(c: str) -> bool:
    return c.isalpha() or c in "_$"


def _ident_part(c: str) -> bool:
    return c.isalnum() or c in "_$"


class Lexer:
    def __init__(self, src: str, filename: str = "<js>"):
        self.src = src
        self.filename = filename
        self.pos = 0
        self.line = 1
        self.tokens: list[tuple] = []

    def error(self, msg: str) -> LexError:
        return LexError(f"{self.filename}:{self.line}: {msg}")

    def tokenize(self) -> list[tuple]:
        while self.pos < len(self.src):
            c = self.src[self.pos]
            if c == "\n":
                self.line += 1
                self.pos += 1
            elif c.isspace():
                self.pos += 1
            elif self.src.startswith("//", self.pos):
                nl = self.src.find("\n", self.pos)
                self.pos = len(self.src) if nl < 0 else nl
            elif self.src.startswith("/*", self.pos):
                end = self.src.find("*/", self.pos + 2)
                if end < 0:
                    raise self.error("unterminated block comment")
                self.line += self.src.count("\n", self.pos, end)
                self.pos = end + 2
            elif c in "'\"":
                self.tokens.append(self._string(c))
            elif c == "`":
                self.tokens.append(self._template())
            elif c.isdigit() or (c == "." and self.pos + 1 < len(self.src)
                                 and self.src[self.pos + 1].isdigit()):
                self.tokens.append(self._number())
            elif _ident_start(c):
                self.tokens.append(self._ident())
            elif c == "/" and self._regex_allowed():
                self.tokens.append(self._regex())
            else:
                self.tokens.append(self._punct())
        self.tokens.append(("eof", None, self.line))
        return self.tokens

    # ---- helpers ---------------------------------------------------------------

    def _regex_allowed(self) -> bool:
        """A ``/`` begins a regex when it can't be division: after nothing,
        an operator, ``(``/``[``/``,``/``{``/``;``/``:``, or keywords like
        ``return``/``typeof``. After idents/literals/closing brackets it is
        division."""
        for typ, val, _ in reversed(self.tokens):
            if typ in ("num", "str", "template", "regex"):
                return False
            if typ == "ident":
                return False
            if typ == "keyword":
                return val not in ("this", "null", "undefined", "true", "false")
            if typ == "punct":
                return val not in (")", "]", "}", "++", "--")
            return True
        return True

    def _string(self, quote: str) -> tuple:
        line = self.line
        self.pos += 1
        out = []
        while True:
            if self.pos >= len(self.src):
                raise self.error("unterminated string")
            c = self.src[self.pos]
            if c == quote:
                self.pos += 1
                return ("str", "".join(out), line)
            if c == "\n":
                raise self.error("newline in string")
            if c == "\\":
                self.pos += 1
                e = self.src[self.pos]
                if e == "u":
                    if self.src[self.pos + 1] == "{":
                        end = self.src.index("}", self.pos)
                        out.append(chr(int(self.src[self.pos + 2:end], 16)))
                        self.pos = end + 1
                    else:
                        out.append(chr(int(self.src[self.pos + 1:self.pos + 5], 16)))
                        self.pos += 5
                    continue
                if e == "x":
                    out.append(chr(int(self.src[self.pos + 1:self.pos + 3], 16)))
                    self.pos += 3
                    continue
                out.append(ESCAPES.get(e, e))
                self.pos += 1
                if e == "\n":
                    self.line += 1
                continue
            out.append(c)
            self.pos += 1

    def _template(self) -> tuple:
        line = self.line
        self.pos += 1  # opening backtick
        parts: list[tuple] = []
        buf: list[str] = []
        while True:
            if self.pos >= len(self.src):
                raise self.error("unterminated template literal")
            c = self.src[self.pos]
            if c == "`":
                self.pos += 1
                if buf:
                    parts.append(("str", "".join(buf)))
                return ("template", parts, line)
            if c == "\\":
                e = self.src[self.pos + 1]
                buf.append(ESCAPES.get(e, e))
                self.pos += 2
                continue
            if c == "$" and self.src.startswith("${", self.pos):
                if buf:
                    parts.append(("str", "".join(buf)))
                    buf = []
                # Find the matching } (nesting-aware; strings inside too).
                depth = 1
                j = self.pos + 2
                start = j
                while depth:
                    if j >= len(self.src):
                        raise self.error("unterminated ${} in template")
                    cj = self.src[j]
                    if cj in "'\"`":
                        quote = cj
                        j += 1
                        while self.src[j] != quote:
                            if self.src[j] == "\\":
                                j += 1
                            j += 1
                    elif cj == "{":
                        depth += 1
                    elif cj == "}":
                        depth -= 1
                        if not depth:
                            break
                    j += 1
                inner = Lexer(self.src[start:j], self.filename).tokenize()
                parts.append(("expr", inner))
                self.pos = j + 1
                continue
            if c == "\n":
                self.line += 1
            buf.append(c)
            self.pos += 1

    def _number(self) -> tuple:
        line = self.line
        start = self.pos
        src = self.src
        if src.startswith(("0x", "0X"), self.pos):
            self.pos += 2
            while self.pos < len(src) and src[self.pos] in "0123456789abcdefABCDEF":
                self.pos += 1
            return ("num", float(int(src[start:self.pos], 16)), line)
        while self.pos < len(src) and (src[self.pos].isdigit() or src[self.pos] == "."):
            self.pos += 1
        if self.pos < len(src) and src[self.pos] in "eE":
            self.pos += 1
            if src[self.pos] in "+-":
                self.pos += 1
            while self.pos < len(src) and src[self.pos].isdigit():
                self.pos += 1
        return ("num", float(src[start:self.pos]), line)

    def _ident(self) -> tuple:
        line = self.line
        start = self.pos
        while self.pos < len(self.src) and _ident_part(self.src[self.pos]):
            self.pos += 1
        word = self.src[start:self.pos]
        return ("keyword" if word in KEYWORDS else "ident", word, line)

    def _regex(self) -> tuple:
        line = self.line
        start = self.pos
        self.pos += 1  # opening /
        in_class = False
        while True:
            if self.pos >= len(self.src):
                raise self.error("unterminated regex literal")
            c = self.src[self.pos]
            if c == "\\":
                self.pos += 2
                continue
            if c == "[":
                in_class = True
            elif c == "]":
                in_class = False
            elif c == "/" and not in_class:
                break
            elif c == "\n":
                raise self.error("newline in regex literal")
            self.pos += 1
        body = self.src[start + 1:self.pos]
        self.pos += 1
        fstart = self.pos
        while self.pos < len(self.src) and self.src[self.pos].isalpha():
            self.pos += 1
        flags = self.src[fstart:self.pos]
        return ("regex", (body, flags), line)

    def _punct(self) -> tuple:
        for p in PUNCT:
            if self.src.startswith(p, self.pos):
                self.pos += len(p)
                return ("punct", p, self.line)
        raise self.error(f"unexpected character {self.src[self.pos]!r}")


def tokenize(src: str, filename: str = "<js>") -> list[tuple]:
    return Lexer(src, filename).tokenize()
