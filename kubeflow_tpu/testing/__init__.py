"""Test infrastructure: in-memory fake kube-apiserver + simulators.

The reference tests controllers against envtest (a real etcd+apiserver with no
kubelet; ``notebook-controller/controllers/suite_test.go:50-100``). This
package is our equivalent, plus what envtest never had: an optional kubelet
simulator (``podsim``) that materialises StatefulSet/Deployment pods so
e2e-style flows (spawn → Running → probe) run entirely in-process, and a fake
TPU runtime harness for multi-host wiring tests (SURVEY.md §4 "fake TPU
runtime").
"""

from kubeflow_tpu.testing.fakekube import FakeKube, FaultPlan
from kubeflow_tpu.testing.podsim import PodSimulator

__all__ = ["FakeKube", "FaultPlan", "PodSimulator"]
