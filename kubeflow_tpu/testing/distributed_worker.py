"""One worker process of the multi-process jax.distributed harness.

SURVEY.md §4's "fake TPU runtime": the reference never needed to fake
multi-node at the network level, but the TPU build must prove that the env
the notebook controller injects into each worker
(``TpuSlice.worker_env`` — JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES /
JAX_PROCESS_ID over the headless-Service hostnames) actually bootstraps
``jax.distributed`` and carries a cross-process collective. Run as
``python -m kubeflow_tpu.testing.distributed_worker`` with that env set;
prints one ``PSUM_RESULT <value> NPROC <n>`` line on success.

The e2e analogue in the reference probes a live spawned notebook over HTTP
(odh-notebook-controller/e2e/helper_test.go:23-100); here the "probe" is
the collective itself.
"""

from __future__ import annotations

import os
from functools import partial

# Optional 2D-mesh mode (docs/operations.md "Probe / burn-in env").
WORKER_MESH_ENV = "KFTPU_WORKER_MESH"


def main() -> None:
    import jax

    # CPU backend regardless of what the host image registers (same trick
    # as tests/conftest.py) — each process contributes its one CPU device.
    jax.config.update("jax_platforms", "cpu")

    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    try:
        from jax import shard_map
    except ImportError:  # pragma: no cover
        from jax.experimental.shard_map import shard_map

    # The exact bootstrap incantation documented for in-notebook use: every
    # argument comes from the env the controller injected.
    jax.distributed.initialize(
        coordinator_address=os.environ["JAX_COORDINATOR_ADDRESS"],
        num_processes=int(os.environ["JAX_NUM_PROCESSES"]),
        process_id=int(os.environ["JAX_PROCESS_ID"]),
    )

    n_devices = jax.device_count()
    pid = jax.process_index()
    mesh = Mesh(np.asarray(jax.devices()), ("x",))
    sharding = NamedSharding(mesh, P("x"))
    # Each process contributes (process_id + 1) so the psum result encodes
    # that every process really participated: with P processes of one
    # device each the reduction is 1 + 2 + ... + P.
    x = jax.make_array_from_callback(
        (n_devices,), sharding, lambda _idx: np.array([float(pid + 1)])
    )

    @jax.jit
    @partial(shard_map, mesh=mesh, in_specs=P("x"), out_specs=P("x"))
    def allreduce(v):
        return jax.lax.psum(v, "x")

    out = allreduce(x)
    local = np.asarray(out.addressable_shards[0].data)
    print(f"PSUM_RESULT {float(local[0])} NPROC {jax.process_count()}", flush=True)

    # Optional 2D-mesh mode (KFTPU_WORKER_MESH="DxM"): the multi-axis
    # collectives a real dp x tp training step issues, across PROCESS
    # boundaries — psum on the model axis and pmean on data must both
    # cross the DCN bootstrap, not just a single 1D all-reduce.
    mesh_spec = os.environ.get(WORKER_MESH_ENV)
    if mesh_spec:
        import math

        dims = tuple(int(p) for p in mesh_spec.lower().split("x"))
        if len(dims) != 2 or math.prod(dims) != jax.device_count():
            # A stray inherited env var must not break the 1D contract run.
            print(f"MESH2D_SKIPPED {mesh_spec} (have {jax.device_count()} "
                  "devices)", flush=True)
            jax.distributed.shutdown()
            return
        grid = np.asarray(jax.devices()).reshape(dims)
        mesh2 = Mesh(grid, ("data", "model"))

        @jax.jit
        @partial(shard_map, mesh=mesh2, in_specs=P("data", "model"),
                 out_specs=P("data", "model"))
        def both_axes(v):
            return jax.lax.pmean(jax.lax.psum(v, "model"), "data")

        x2 = jax.make_array_from_callback(
            dims, NamedSharding(mesh2, P("data", "model")),
            lambda _idx: np.array([[float(pid + 1)]]),
        )
        out2 = both_axes(x2)
        local2 = np.asarray(out2.addressable_shards[0].data)
        print(f"MESH2D_RESULT {float(local2[0, 0])}", flush=True)

    jax.distributed.shutdown()


if __name__ == "__main__":
    main()
