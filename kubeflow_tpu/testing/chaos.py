"""Chaos soak harness: crash/restart + API-fault churn with invariant gates.

The control plane's correctness claims — chip ledger invariants (PR 5),
the multi-step drain/restore protocol (PR 6), DAG-parallel applies
(PR 4) — were only ever exercised on a well-behaved FakeKube. This
module drives the REAL manager/controller/scheduler/migration stack
through seeded fault storms (:class:`~kubeflow_tpu.testing.fakekube.
FaultPlan`: 5xx/429/409 injection, watch resets, stale LISTs) while
killing and restarting the Manager mid-reconcile, then asserts the
global invariants every convergence must restore:

- zero ``ChipLedger.violations`` and a self-consistent ledger;
- no gang both Admitted and Queued;
- no orphaned or duplicate slice StatefulSets (and none for Queued gangs);
- every drain terminal — Parked, restored, or hard-stopped — none wedged;
- every workqueue fully drained, no key stuck at max backoff forever
  (transient quarantines must release through the escape hatch);
- **committed-step restore** (ISSUE 16): drain acks run REAL
  ``CheckpointFabric`` saves against on-disk tiers while storage faults
  (crash-mid-upload, torn manifests, read corruption, stale staging
  pointers) blow through the storm — at every convergence each
  notebook's restore must yield a bit-exact member of its durably
  committed step set, never a partial.

``bench.py chaos_soak [--smoke]`` runs this over ≥5 seeds as the CI
gate; tests/test_chaos.py replays the same seeds in tier-1.

``shard_kill_scenario`` (ISSUE 17) extends the soak to the sharded
active-active control plane: N replicas over one apiserver, one crash-
killed mid-flight, survivors absorbing its keyspace with zero dropped
queued keys and every global invariant intact.
"""

from __future__ import annotations

import asyncio
import os
import random
import shutil
import tempfile
import time
from dataclasses import dataclass, field

import numpy as np

from kubeflow_tpu.api import keys
from kubeflow_tpu.api import notebook as nbapi
from kubeflow_tpu.checkpoint import (
    CheckpointFabric,
    CheckpointIntegrityError,
)
from kubeflow_tpu.controllers.notebook import (
    NotebookOptions,
    setup_notebook_controller,
)
from kubeflow_tpu.controllers.warmpool import (
    WarmPoolManager,
    WarmPoolOptions,
)
from kubeflow_tpu.migration import protocol as migration
from kubeflow_tpu.runtime import timeline as timeline_mod
from kubeflow_tpu.runtime.aiotasks import reap
from kubeflow_tpu.runtime.errors import ApiError
from kubeflow_tpu.runtime.manager import Manager
from kubeflow_tpu.runtime.metrics import Registry
from kubeflow_tpu.runtime.objects import (
    annotations_of,
    deep_get,
    fmt_iso,
    get_meta,
    name_of,
    namespace_of,
)
from kubeflow_tpu.scheduler import (
    Fleet,
    SchedulerOptions,
    TpuFleetScheduler,
)
from kubeflow_tpu.testing.fakekube import FakeKube, FaultPlan
from kubeflow_tpu.testing.podsim import PodSimulator
from kubeflow_tpu.webhooks import register_all


@dataclass
class SoakConfig:
    """One seeded soak run. Defaults are the tier-1/smoke shape; the full
    bench widens notebooks/rounds, not the semantics."""

    seed: int = 0
    namespaces: int = 2
    notebooks_per_namespace: int = 2
    # Manager kill/restart cycles; each round is storm → kill → restart
    # under faults → repair → converge → invariant check.
    rounds: int = 3
    storm_seconds: float = 0.8
    # Served through a fleet ConfigMap (a DYNAMIC source) so the elastic
    # scale-up grant action can actually grow it mid-soak; pool-spot is
    # reclaim-aware spot capacity; pool-small hosts the warm-eligible
    # single-host 2x2 gangs (ISSUE 14).
    fleet: str = ("pool-a=v5e:4x4:2,pool-spot=v5e:4x4:2:spot,"
                  "pool-small=v5e:2x2:4")
    # Warm pod pools under the storm (ISSUE 14): a small pool in team-0
    # plus warm-eligible 2x2 notebooks drives claims through the fault
    # storm; check_invariants asserts no pod is claimed by two Notebooks
    # and the pool converges back to spec after kills/reclaims.
    warm_pools: str = "team-0/warm-img:latest@v5e:2x2:2"
    warm_image: str = "warm-img:latest"
    fault_rate: float = 0.12
    watch_reset_rate: float = 0.04
    stale_list_rate: float = 0.15
    # Per-probe chance a "spot" churn action revokes a spot node
    # (FaultPlan.reclaim_spot — same seeded RNG stream as the API
    # faults, so a seed replays the same revocation schedule).
    spot_reclaim_rate: float = 0.5
    # One never-fits gang per soak drives the scale-up intent path;
    # churn actions then grant (grow the ConfigMap) or deny (stamp
    # Failed on the intent's ProvisioningRequest).
    big_gang_slices: int = 6
    quarantine_after: int = 25
    drain_grace_seconds: float = 2.0
    converge_timeout: float = 30.0
    # Checkpoint-fabric storage faults (ISSUE 16): each drain ack runs a
    # REAL CheckpointFabric save (snapshot-then-ack, background upload)
    # against per-notebook on-disk tiers that survive manager kills;
    # these rates arm crash-mid-upload, torn-manifest, read-corruption,
    # and stale-staging-pointer windows during the storm. The committed-
    # step invariant then checks every restore at convergence. Rates are
    # probed PER CHUNK (the fabric's saves here are ~7 chunks), so the
    # per-save crash probability is roughly 1-(1-rate)^7.
    crash_upload_rate: float = 0.08
    torn_manifest_rate: float = 0.2
    corrupt_read_rate: float = 0.15
    stale_staging_rate: float = 0.3

    @property
    def controller_namespace(self) -> str:
        return "kubeflow-tpu"


@dataclass
class SoakReport:
    seed: int = 0
    rounds: int = 0
    manager_restarts: int = 0
    actions: int = 0
    injected: dict = field(default_factory=dict)
    ledger_violations: int = 0
    quarantined_transient: int = 0
    spot_revocations: int = 0
    scale_up_grants: int = 0
    scale_up_denials: int = 0
    # Checkpoint fabric under the storm: durable commits the simulated
    # SDK landed, uploads the crash fault killed, and restores the
    # committed-step invariant verified at convergence.
    checkpoint_commits: int = 0
    checkpoint_crashes: int = 0
    restores_checked: int = 0
    problems: list = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return not self.problems and self.ledger_violations == 0

    def to_dict(self) -> dict:
        return {
            "seed": self.seed,
            "rounds": self.rounds,
            "manager_restarts": self.manager_restarts,
            "actions": self.actions,
            "injected": dict(sorted(self.injected.items())),
            "ledger_violations": self.ledger_violations,
            "quarantined_transient": self.quarantined_transient,
            "spot_revocations": self.spot_revocations,
            "scale_up_grants": self.scale_up_grants,
            "scale_up_denials": self.scale_up_denials,
            "checkpoint_commits": self.checkpoint_commits,
            "checkpoint_crashes": self.checkpoint_crashes,
            "restores_checked": self.restores_checked,
            "problems": list(self.problems),
            "ok": self.ok,
        }


# ---- invariant checks ----------------------------------------------------------


async def check_invariants(kube: FakeKube, mgr: Manager,
                           sched: TpuFleetScheduler,
                           warmpool=None) -> list[str]:
    """The global truths every convergence must restore; returns human-
    readable violations (empty = healthy). Reads the store and in-memory
    scheduler state directly — no fault plan should be active."""
    problems: list[str] = []
    ledger = sched.policy.ledger
    if ledger.violations:
        problems.append(f"ledger violations counter = {ledger.violations}")
    try:
        ledger.assert_consistent()
    except Exception as e:  # LedgerError
        problems.append(f"ledger inconsistent: {e}")

    admitted = set(ledger.allocations)
    queued = set(sched.policy.pending)
    both = admitted & queued
    if both:
        problems.append(f"gangs both Admitted and Queued: {sorted(both)}")

    # Drain terminality: nothing mid-drain at convergence — every drain
    # must have ended Parked (ack), restored, or hard-stopped (deadline).
    if sched._draining:
        problems.append(
            f"non-terminal drains: {sorted(sched._draining)}")

    notebooks = await kube.list("Notebook")
    by_uid: dict[str, dict] = {}
    expected_sts: dict[tuple, set] = {}
    for nb in notebooks:
        key = (namespace_of(nb), name_of(nb))
        by_uid[get_meta(nb).get("uid")] = nb
        try:
            ms = nbapi.multi_slice_of(nb)
        except Exception:
            ms = None
        expected_sts[key] = (
            {ms.slice_sts_name(key[1], j) for j in range(ms.num_slices)}
            if ms else {key[1]}
        )
        ann = annotations_of(nb)
        if (migration.drain_requested_at(ann) is not None
                and not nbapi.is_stopped(nb)):
            problems.append(
                f"{key[0]}/{key[1]}: drain-requested but neither parked "
                "nor finalized (wedged drain)")
        # Unbroken lifecycle timeline (ISSUE 13): the durable journal is
        # written as one whole capped list per transition, so across
        # every manager kill/rebuild the retained window must replay
        # with consecutive seqs, no duplicate transitions, and monotone
        # timestamps — and every surviving object must HAVE one (a
        # rebuilt manager re-derives and persists the current state on
        # its first clean reconcile).
        tl = timeline_mod.decode(ann)
        for p in timeline_mod.continuity_problems(tl):
            problems.append(f"{key[0]}/{key[1]}: timeline {p}")
        if not tl and not get_meta(nb).get("deletionTimestamp"):
            problems.append(
                f"{key[0]}/{key[1]}: empty lifecycle timeline after "
                "convergence")
        # No gang lost across a reclaim (ISSUE 10): every live TPU
        # notebook must still be IN the scheduler — admitted, queued, or
        # draining. A reclaim/defrag that parked a gang and then dropped
        # it (auto-requeue lost) would leave it stopped-less yet absent
        # from both books.
        try:
            has_tpu = nbapi.multi_slice_of(nb) is not None
        except Exception:
            has_tpu = False
        if (has_tpu and sched.active and not nbapi.is_stopped(nb)
                and not get_meta(nb).get("deletionTimestamp")
                and key not in sched.policy.ledger.allocations
                and key not in sched.policy.pending
                and key not in sched._draining):
            problems.append(
                f"{key[0]}/{key[1]}: live gang lost by the scheduler "
                "(neither admitted nor queued nor draining)")

    sts_seen: dict[tuple, list] = {}
    for sts in await kube.list("StatefulSet"):
        ref = next((r for r in get_meta(sts).get("ownerReferences", [])
                    if r.get("controller") and r.get("kind") == "Notebook"),
                   None)
        if ref is None:
            continue
        owner = by_uid.get(ref.get("uid"))
        if owner is None:
            problems.append(
                f"orphan StatefulSet {namespace_of(sts)}/{name_of(sts)}: "
                "owner Notebook gone")
            continue
        okey = (namespace_of(owner), name_of(owner))
        if name_of(sts) not in expected_sts.get(okey, set()):
            problems.append(
                f"duplicate/stale slice StatefulSet "
                f"{namespace_of(sts)}/{name_of(sts)} for {okey}")
        sts_seen.setdefault(okey, []).append(sts)

    for key in queued:
        # A Queued gang may keep zero-replica StatefulSet shells from an
        # earlier parked run (stop scales to 0, it does not delete) — the
        # violation is a Queued gang with SCALED-UP slices: pods on chips
        # the ledger gave to someone else.
        hot = [
            name_of(s) for s in sts_seen.get(key, ())
            if (deep_get(s, "spec", "replicas", default=1) or 0) > 0
            or (deep_get(s, "status", "readyReplicas", default=0) or 0) > 0
        ]
        if hot:
            problems.append(
                f"Queued gang {key} owns scaled-up StatefulSets {hot}")

    # Warm-pool invariants (ISSUE 14). (a) No pod claimed by two
    # Notebooks: the CAS claim protocol must hold through every fault
    # storm and manager kill — two CRs whose warm-claimed annotations
    # name the same pod would mean the protocol double-adopted.
    claimed_by: dict[tuple, list] = {}
    for nb in notebooks:
        pod_name = annotations_of(nb).get(nbapi.WARM_CLAIMED_ANNOTATION)
        if pod_name:
            claimed_by.setdefault(
                (namespace_of(nb), pod_name), []).append(name_of(nb))
    for (ns, pod_name), owners in sorted(claimed_by.items()):
        if len(owners) > 1:
            problems.append(
                f"pod {ns}/{pod_name} claimed by two Notebooks: "
                f"{sorted(owners)}")
            continue
        pod = await kube.get_or_none("Pod", pod_name, ns)
        if pod is None:
            problems.append(
                f"{ns}/{owners[0]}: warm-claimed pod {pod_name} is gone "
                "but the claim annotation survived convergence")
        elif not (annotations_of(pod).get(keys.TPU_WARM_CLAIM) or ""
                  ).startswith(f"{ns}/{owners[0]}/"):
            problems.append(
                f"{ns}/{owners[0]}: claimed pod {pod_name} carries a "
                "foreign (or no) claim annotation: "
                f"{annotations_of(pod).get(keys.TPU_WARM_CLAIM)!r}")
    # (b) Pool size converges back to spec after kills/claims/reclaims —
    # below-target is only legitimate while the shape has NO free
    # capacity (the scheduler legitimately cannibalized the reserve).
    if warmpool is not None and warmpool.active and sched.active:
        for pool in warmpool.pools:
            ready = len(await warmpool._claimable_pods(pool))
            if ready >= pool.size:
                continue
            free = sum(
                max(sched.policy.ledger.free_slices(p), 0)
                for p in sched.policy.fleet.matching(
                    pool.accelerator, pool.topology))
            if free > 0:
                problems.append(
                    f"warm pool {pool.slug} not converged: {ready} ready "
                    f"< target {pool.size} with {free} free "
                    f"{pool.accelerator}:{pool.topology} slice(s)")
    for name, queue in mgr._queues.items():
        info = queue.debug_info()
        if info["ready"] or info["in_flight"] or info["dirty"]:
            problems.append(
                f"workqueue {name} not drained: ready={info['ready']} "
                f"in_flight={info['in_flight']} dirty={info['dirty']}")
    return problems


# ---- the soak ------------------------------------------------------------------


class ChaosSoak:
    def __init__(self, config: SoakConfig):
        self.cfg = config
        self.rng = random.Random(config.seed)
        self.kube = FakeKube()
        register_all(self.kube)
        self.plan = FaultPlan(seed=config.seed)
        self.report = SoakReport(seed=config.seed)
        self.mgr: Manager | None = None
        self.sched: TpuFleetScheduler | None = None
        self.warmpool: WarmPoolManager | None = None
        self._nb_names: list[tuple] = []
        self._created = 0
        # Live fleet spec (the ConfigMap's data["fleet"]); scale-up
        # grants rewrite it.
        self._fleet_spec = config.fleet
        self._spot_nodes: list[str] = []
        # Checkpoint fabric per notebook — POD-side state rooted on disk,
        # so it survives manager kills like a real pod's tiers would.
        self._fabric_root = tempfile.mkdtemp(prefix="kftpu-chaos-ckpt-")
        self._fabrics: dict[tuple, CheckpointFabric] = {}
        self._fabric_steps: dict[tuple, int] = {}
        # In-flight async saves: key → [(handle, step, raw drain echo)];
        # the SDK loop polls these and stamps the commit mark. A list —
        # rapid drain cycles can overlap uploads (the fabric serializes
        # them, the harness must not lose one).
        self._pending_commits: dict[tuple, list] = {}
        # Last (step, raw) saved per key: an ack retry for the SAME
        # drain re-patches without re-snapshotting (guard semantics).
        self._last_save: dict[tuple, tuple] = {}
        # What the harness KNOWS committed (the invariant's ground truth).
        self._committed_steps: dict[tuple, set[int]] = {}

    # -- stack lifecycle -----------------------------------------------------

    def _build_stack(self) -> None:
        """Fresh Manager + scheduler over the SAME kube/store — what a
        controller pod restart looks like to the cluster. In-memory state
        (ledger, drains, queues, caches) starts empty and must be
        re-derived from the API (reclaim, annotation self-heal)."""
        mgr = Manager(self.kube, registry=Registry(),
                      quarantine_after=self.cfg.quarantine_after)
        sched = TpuFleetScheduler(
            self.kube,
            SchedulerOptions(
                # The safety-net requeue cadence for Queued gangs; kept
                # well above the settle sampling window — admissions
                # re-enqueue winners immediately, so this only paces the
                # steady "still waiting" refresh.
                queued_requeue_seconds=0.5,
                idle_preempt_after_seconds=0.2,
                enable_migration=True,
                drain_grace_seconds=self.cfg.drain_grace_seconds,
                # Elastic fleet under chaos: the spec comes from the
                # fleet ConfigMap (a DYNAMIC source — grants grow it,
                # and a restarted manager re-discovers it through the
                # fault storm), refreshed at soak speed.
                fleet_configmap="kftpu-fleet",
                controller_namespace=self.cfg.controller_namespace,
                fleet_refresh_seconds=0.05,
                enable_elastic=True,
                scale_up_ttl_seconds=5.0,
                defrag_interval_seconds=0.2,
                defrag_idle_seconds=0.3,
            ),
            registry=mgr.registry,
        )
        # Warm pod pools ride the storm too (ISSUE 14): claims, pool
        # kills, and scheduler cannibalization all replay per seed; a
        # REBUILT manager's fresh pool manager must adopt the running
        # slots (and their CAS state) from the API alone.
        warmpool = (WarmPoolManager(
            self.kube,
            WarmPoolOptions(
                spec=self.cfg.warm_pools,
                controller_namespace=self.cfg.controller_namespace,
                replenish_seconds=0.05),
            registry=mgr.registry)
            if self.cfg.warm_pools else None)
        setup_notebook_controller(mgr, NotebookOptions(), scheduler=sched,
                                  warmpool=warmpool)
        # Soak-speed clocks: tiny workqueue backoff and informer resync so
        # a seeded run converges in seconds, not production minutes.
        for q in mgr._queues.values():
            q.base_delay = 0.002
            q.max_delay = 0.05
        for inf in mgr.informers.values():
            inf.resync_backoff = 0.02
            inf.resync_backoff_max = 0.2
        self.mgr, self.sched, self.warmpool = mgr, sched, warmpool

    async def _start(self) -> None:
        self._build_stack()
        await self.mgr.start()

    async def _kill_manager(self) -> None:
        """Mid-reconcile kill: stop() cancels every worker wherever it is
        awaiting — half-applied child sets, un-stamped admissions and all.
        The dying scheduler's ledger-violation count is harvested FIRST:
        the rebuilt stack starts a fresh counter, and a violation from the
        first half of a round must not vanish with the old instance."""
        self.report.ledger_violations += self.sched.policy.ledger.violations
        await self.mgr.stop()
        self.report.manager_restarts += 1

    # -- storm + churn -------------------------------------------------------

    def _arm_faults(self) -> None:
        cfg = self.cfg
        self.plan.reclaim_spot(rate=cfg.spot_reclaim_rate)
        self.plan.fail("unavailable", rate=cfg.fault_rate)
        self.plan.fail("internal", rate=cfg.fault_rate / 2)
        self.plan.fail("timeout", rate=cfg.fault_rate / 3)
        self.plan.fail("throttle", rate=cfg.fault_rate / 3)
        self.plan.fail("conflict", verbs=("update", "update_status", "patch"),
                       rate=cfg.fault_rate / 2)
        self.plan.reset_watch(rate=cfg.watch_reset_rate)
        self.plan.stale_list(rate=cfg.stale_list_rate)
        # Storage faults ride the same storm (lifted by plan.clear()):
        # the fabrics hold the plan itself, so these windows open and
        # close with the API faults. What a fault LEAVES on disk (a torn
        # manifest, partial chunks, a stale staging pointer) persists
        # into the fault-free restore check — that durable damage is the
        # thing the committed-step invariant interrogates.
        self.plan.crash_upload(rate=cfg.crash_upload_rate)
        self.plan.tear_manifest("remote", rate=cfg.torn_manifest_rate)
        self.plan.corrupt_read(rate=cfg.corrupt_read_rate)
        self.plan.stale_staging(rate=cfg.stale_staging_rate)
        self.kube.use_faults(self.plan)

    def _lift_faults(self) -> None:
        self.plan.clear()
        self.report.injected = dict(self.plan.injected)

    async def _create_notebook(self, ns: str) -> None:
        name = f"soak-{self._created}"
        self._created += 1
        if self.rng.random() < 0.4:
            # Warm-eligible shape/image (single-host 2x2 on the warm
            # pool's image): in team-0 these drive claims through the
            # storm; elsewhere they prove claims stay namespace-local.
            nb = nbapi.new(name, ns, image=self.cfg.warm_image,
                           accelerator="v5e", topology="2x2")
        else:
            nb = nbapi.new(name, ns, accelerator="v5e", topology="4x4")
        prio = self.rng.choice(["low", "normal", "normal", "high"])
        nb["metadata"].setdefault("annotations", {})[
            nbapi.PRIORITY_ANNOTATION] = prio
        try:
            await self.kube.create("Notebook", nb)
            self._nb_names.append((ns, name))
        except ApiError:
            self._created -= 1  # injected failure: retry the same name later

    async def _seed_cluster(self) -> None:
        """Pre-storm cluster state: the fleet ConfigMap (the scheduler's
        dynamic source), one Node per spot-pool slice (the revocation
        signal's carrier), and — elastic — one never-fits gang whose
        shortfall keeps a scale-up intent alive for the grant/deny
        churn actions to answer."""
        await self.kube.create("ConfigMap", {
            "apiVersion": "v1", "kind": "ConfigMap",
            "metadata": {"name": "kftpu-fleet",
                         "namespace": self.cfg.controller_namespace},
            "data": {"fleet": self._fleet_spec},
        })
        for pool in Fleet.parse(self._fleet_spec).pools:
            if not pool.spot:
                continue
            for i in range(pool.num_slices):
                node_name = f"{pool.name}-node-{i}"
                self._spot_nodes.append(node_name)
                await self.kube.create("Node", {
                    "apiVersion": "v1", "kind": "Node",
                    "metadata": {"name": node_name, "labels": {
                        "cloud.google.com/gke-nodepool": pool.name,
                        "cloud.google.com/gke-spot": "true",
                    }},
                })
        big = nbapi.new("soak-big", "team-0", accelerator="v5e",
                        topology="4x4",
                        num_slices=self.cfg.big_gang_slices)
        try:
            await self.kube.create("Notebook", big)
            self._nb_names.append(("team-0", "soak-big"))
        except ApiError:
            pass

    async def _seed_notebooks(self) -> None:
        for n in range(self.cfg.namespaces):
            for _ in range(self.cfg.notebooks_per_namespace):
                await self._create_notebook(f"team-{n}")

    async def _churn_once(self) -> None:
        """One rng-driven user/operator action. Every kube call may take
        an injected fault — the driver shrugs like kubectl's user would."""
        if not self._nb_names:
            return
        key = self.rng.choice(self._nb_names)
        ns, name = key
        action = self.rng.choice(
            ["stop", "start", "suspend", "resume", "idle", "active",
             "edit", "ack", "spot", "scale_up"])
        self.report.actions += 1
        patch = None
        if action == "stop":
            patch = {nbapi.STOP_ANNOTATION: fmt_iso(time.time())}
        elif action == "start":
            patch = {nbapi.STOP_ANNOTATION: None}
        elif action == "suspend":
            patch = {nbapi.SUSPEND_ANNOTATION: "true"}
        elif action == "resume":
            patch = {nbapi.SUSPEND_ANNOTATION: None}
        elif action == "idle":
            patch = {nbapi.LAST_ACTIVITY_ANNOTATION: fmt_iso(
                time.time() - 3600)}
        elif action == "active":
            patch = {nbapi.LAST_ACTIVITY_ANNOTATION: fmt_iso(time.time())}
        elif action == "edit":
            patch = {"chaos-edit": str(self.rng.randrange(1 << 30))}
        elif action == "ack":
            await self._ack_drains(only=key)
            return
        elif action == "spot":
            await self._spot_action()
            return
        elif action == "scale_up":
            await self._scale_up_action()
            return
        try:
            await self.kube.patch(
                "Notebook", name, {"metadata": {"annotations": patch}}, ns)
        except ApiError:
            pass

    async def _kick_elastic(self) -> None:
        """Deterministic elastic exercise, once per soak: revoke one
        spot node and deny the standing scale-up intent (the never-fits
        gang keeps its demand alive, so later churn can still grant).
        The wall-clock-paced churn alone could miss both paths on a
        slow host, and the tier-1 seeds assert they ran."""
        if self._spot_nodes:
            self.report.spot_revocations += 1
            try:
                await self.kube.patch(
                    "Node", self._spot_nodes[0],
                    {"spec": {"taints": [{
                        "key": "cloud.google.com/gke-spot-termination",
                        "effect": "NoSchedule"}]}})
            except ApiError:
                pass
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            intents = (self.sched._intent_book.intents
                       if self.sched is not None
                       and self.sched._intent_book is not None else {})
            if intents:
                intent = sorted(intents.values(),
                                key=lambda i: i.name)[0]
                try:
                    await self.kube.patch(
                        "ProvisioningRequest", intent.name,
                        {"status": {"conditions": [{
                            "type": "Failed", "status": "True",
                            "reason": "ChaosDenied",
                            "message": "injected scale-up denial",
                        }]}},
                        self.cfg.controller_namespace,
                        subresource="status")
                    self.report.scale_up_denials += 1
                    return
                except ApiError:
                    pass  # CR mirror not created yet — retry
            await asyncio.sleep(0.05)

    async def _spot_action(self) -> None:
        """Revoke — or give back — spot capacity. The revocation
        schedule comes from the FaultPlan (seeded, deterministic); the
        signal itself travels as the real GKE taint on the pool's Node,
        through the normal API."""
        if not self._spot_nodes:
            return
        node = self.rng.choice(self._spot_nodes)
        pool = node.rsplit("-node-", 1)[0]
        try:
            if self.plan.should_reclaim_spot(pool):
                self.report.spot_revocations += 1
                await self.kube.patch("Node", node, {"spec": {"taints": [{
                    "key": "cloud.google.com/gke-spot-termination",
                    "effect": "NoSchedule",
                }]}})
            else:
                await self.kube.patch("Node", node,
                                      {"spec": {"taints": None}})
        except ApiError:
            pass

    async def _scale_up_action(self) -> None:
        """Answer a pending scale-up intent: grant (grow the fleet
        ConfigMap — the dynamic source the scheduler re-reads) or deny
        (stamp Failed on the intent's ProvisioningRequest)."""
        intents = (self.sched._intent_book.intents
                   if self.sched is not None
                   and self.sched._intent_book is not None else {})
        if not intents:
            return
        intent = self.rng.choice(sorted(intents.values(),
                                        key=lambda i: i.name))
        if self.rng.random() < 0.5:
            # Grant: +2 slices on pool-a (bounded so a grant-happy seed
            # cannot grow the fleet without limit).
            try:
                parts = self._fleet_spec.split(",")
                name, shape = parts[0].split("=")
                acc, topo, n, *rest = shape.split(":")
                if int(n) >= 8:
                    return
                parts[0] = f"{name}={acc}:{topo}:{int(n) + 2}" + (
                    ":" + ":".join(rest) if rest else "")
                self._fleet_spec = ",".join(parts)
                await self.kube.patch(
                    "ConfigMap", "kftpu-fleet",
                    {"data": {"fleet": self._fleet_spec}},
                    self.cfg.controller_namespace)
                self.report.scale_up_grants += 1
            except (ApiError, ValueError):
                pass
        else:
            try:
                await self.kube.patch(
                    "ProvisioningRequest", intent.name,
                    {"status": {"conditions": [{
                        "type": "Failed", "status": "True",
                        "reason": "ChaosDenied",
                        "message": "injected scale-up denial",
                    }]}},
                    self.cfg.controller_namespace, subresource="status")
                self.report.scale_up_denials += 1
            except ApiError:
                pass

    def _fabric_for(self, key: tuple) -> CheckpointFabric:
        """The notebook's pod-side fabric: on-disk remote + staging tiers
        under the soak's temp root, tiny chunks so every save is
        multi-chunk (the crash-mid-upload window needs chunks to crash
        between), and the soak's FaultPlan as the storage-fault hook."""
        fab = self._fabrics.get(key)
        if fab is None:
            ns, name = key
            base = os.path.join(self._fabric_root, ns, name)
            fab = CheckpointFabric(
                os.path.join(base, "remote"),
                staging_dir=os.path.join(base, "staging"),
                chunk_bytes=64, keep=4, full_interval=3,
                upload_retries=2, backoff_seconds=0.005,
                registry=Registry(), faults=self.plan)
            self._fabrics[key] = fab
        return fab

    def _step_tree(self, key: tuple, step: int) -> dict:
        """Deterministic per-(notebook, step) training state — restored
        content is verified against a regeneration of exactly this, so a
        partial or cross-step mix of chunks cannot pass."""
        offset = (hash(key) & 0xFFFF) / 7.0
        return {"w": np.arange(48.0) * (step + 1) + offset,
                "step": np.int64(step)}

    async def _ack_drains(self, only: tuple | None = None) -> None:
        """The simulated in-pod SDK: answer any un-acked drain request
        the way CheckpointGuard-over-the-fabric does — a REAL
        ``save_async`` (host snapshot) then an immediate ack echoing the
        raw request value; the background upload's commit is stamped by
        :meth:`_poll_commits` when (and only when) it durably lands."""
        for ns, name in list(self._nb_names):
            if only is not None and (ns, name) != only:
                continue
            key = (ns, name)
            try:
                nb = await self.kube.get_or_none("Notebook", name, ns)
            except ApiError:
                continue
            if nb is None:
                continue
            ann = annotations_of(nb)
            raw = ann.get(nbapi.DRAIN_REQUESTED_ANNOTATION)
            if not raw or migration.drain_acked(ann):
                continue
            fab = self._fabric_for(key)
            last = self._last_save.get(key)
            if last is not None and last[1] == raw:
                # Ack-patch retry for the same drain: the snapshot is
                # done, only the annotation failed — do NOT re-save.
                step = last[0]
            else:
                # A previous drain's upload may still be in flight — the
                # fabric's queue serializes saves, so snapshot-and-ack
                # again without waiting (exactly what the guard does).
                step = self._fabric_steps.get(key, 0) + 1
                self._fabric_steps[key] = step
                handle = fab.save_async(step, self._step_tree(key, step))
                self._pending_commits.setdefault(key, []).append(
                    (handle, step, raw))
                self._last_save[key] = (step, raw)
            try:
                await self.kube.patch(
                    "Notebook", name,
                    {"metadata": {"annotations": migration.ack_patch(
                        fab.directory, step,
                        time.time(), for_request=raw)}}, ns)
            except ApiError:
                pass  # the next SDK tick re-acks; the save is not redone

    async def _kick_checkpoints(self) -> None:
        """Deterministic fabric exercise, once per storm round: a burst
        of real snapshot-then-ack saves per notebook while the storage
        fault storm is blowing. Drains alone are rng-paced and a seed
        can legitimately schedule almost none — which would leave the
        committed-step invariant vacuous (zero commits, zero restores
        checked). The tier-1 seeds assert the invariant actually ran,
        so the exercise is unconditional, like :meth:`_kick_elastic`."""
        keys = sorted(self._nb_names)[:3]
        for key in keys:
            for _ in range(3):
                fab = self._fabric_for(key)
                step = self._fabric_steps.get(key, 0) + 1
                self._fabric_steps[key] = step
                handle = fab.save_async(step, self._step_tree(key, step))
                self._pending_commits.setdefault(key, []).append(
                    (handle, step, None))
        # A mid-storm restore against each fabric that already has a
        # durable commit: the read-corruption and slow-tier faults are
        # live HERE (the convergence-time check runs fault-free against
        # whatever damage the storm left), so this drives the hash-
        # verify fall-through under fire. A clean refusal is legal;
        # whatever DOES come back must regenerate bit-exact — a torn or
        # cross-step mix of chunks can never leak into the loop.
        for key in keys:
            if not self._committed_steps.get(key):
                continue
            fab = self._fabrics[key]
            try:
                tree = await asyncio.to_thread(fab.restore)
            except (CheckpointIntegrityError, FileNotFoundError):
                continue
            step = int(tree["step"])
            expect = self._step_tree(key, step)
            if not np.array_equal(tree["w"], expect["w"]):
                self.report.problems.append(
                    f"{key[0]}/{key[1]}: mid-storm restore returned a "
                    f"partial for step {step}")
            else:
                self.report.restores_checked += 1

    async def _poll_commits(self) -> None:
        """Resolve finished uploads: committed → stamp the durable-commit
        mark (retrying on injected patch failures) and record the step in
        the harness's committed set; crashed → count it and drop (that
        step must never be restored — the invariant checks exactly
        this)."""
        for key, entries in list(self._pending_commits.items()):
            for entry in list(entries):
                handle, step, raw = entry
                if not handle.done():
                    continue
                if not handle.committed:
                    self.report.checkpoint_crashes += 1
                    entries.remove(entry)
                    continue
                # The fabric's pointer advance IS the ground truth —
                # record it now; the annotation mark below is protocol
                # bookkeeping and must not gate the invariant's
                # committed set (the CR may be deleted, the patch may
                # hit injected faults).
                if step not in self._committed_steps.setdefault(key, set()):
                    self._committed_steps[key].add(step)
                    self.report.checkpoint_commits += 1
                ns, name = key
                try:
                    nb = await self.kube.get_or_none("Notebook", name, ns)
                    if nb is not None:
                        await self.kube.patch(
                            "Notebook", name,
                            {"metadata": {"annotations":
                                          migration.commit_patch(
                                              time.time(),
                                              for_request=raw)}}, ns)
                except ApiError:
                    continue  # retry the mark next tick
                entries.remove(entry)
            if not entries:
                self._pending_commits.pop(key, None)

    async def _sdk_loop(self, stop: asyncio.Event) -> None:
        while not stop.is_set():
            await self._ack_drains()
            await self._poll_commits()
            await asyncio.sleep(0.05)

    async def _check_restores(self) -> list[str]:
        """THE checkpoint-fabric invariant (ISSUE 16): after convergence,
        every notebook with at least one durably committed step restores
        to a member of its committed set with bit-exact content — a
        crash-mid-upload or torn manifest never yields a restored
        partial; integrity damage falls back to an earlier committed
        step, never raises a partial into the training loop. Runs
        fault-free (the storm is lifted), against whatever damage the
        storm left on disk."""
        problems: list[str] = []
        for key, committed in sorted(self._committed_steps.items()):
            fab = self._fabrics.get(key)
            if fab is None or not committed:
                continue
            await asyncio.to_thread(fab.wait)
            try:
                tree = await asyncio.to_thread(fab.restore)
            except CheckpointIntegrityError:
                # Every committed manifest torn: the fabric REFUSED to
                # restore rather than hand back a partial — the
                # invariant is about never restoring damage, and a
                # clean refusal honors it.
                continue
            except FileNotFoundError:
                problems.append(
                    f"{key[0]}/{key[1]}: committed steps "
                    f"{sorted(committed)} but no committed pointer "
                    f"found on restore")
                continue
            except Exception as e:  # noqa: BLE001 — anything else leaked
                problems.append(
                    f"{key[0]}/{key[1]}: restore raised into the "
                    f"training loop: {type(e).__name__}: {e}")
                continue
            self.report.restores_checked += 1
            info = fab.last_restore or {}
            step = info.get("step")
            if step not in committed:
                problems.append(
                    f"{key[0]}/{key[1]}: restored step {step} is not a "
                    f"committed step (committed: {sorted(committed)}) — "
                    f"a partial/crashed checkpoint was restored")
                continue
            want = self._step_tree(key, step)
            if not (np.array_equal(tree.get("w"), want["w"])
                    and int(tree.get("step", -1)) == step):
                problems.append(
                    f"{key[0]}/{key[1]}: restored step {step} content "
                    f"mismatch — torn or cross-step chunk mix passed "
                    f"verification")
        return problems

    # -- convergence ---------------------------------------------------------

    async def _settle_streak(self, deadline: float, *,
                             need_clear: int = 8,
                             interval: float = 0.03) -> bool:
        """Converged = ``need_clear`` consecutive samples with no ready/
        in-flight/dirty workqueue entries and no in-flight drains. The
        streak (240 ms) outlasts every soak-scale retry backoff (max
        50 ms), so the only future-delayed entries it can miss are the
        benign 0.5 s still-Queued refreshes."""
        clear = 0
        while time.monotonic() < deadline:
            busy = any(
                q.ready_count() or info["in_flight"] or info["dirty"]
                for q in self.mgr._queues.values()
                for info in (q.debug_info(),)
            ) or bool(self.sched._draining)
            clear = 0 if busy else clear + 1
            if clear >= need_clear:
                return True
            await asyncio.sleep(interval)
        return False

    def _release_transient_quarantines(self) -> None:
        """Storm-era quarantines are released through the manual escape
        hatch — the operator action POST /debug/queue/requeue models. If
        such a key re-quarantines with no faults active, it is a
        genuinely wedged key and the invariant check reports it."""
        for cname, queue in self.mgr._queues.items():
            for key in queue.quarantined_keys():
                self.report.quarantined_transient += 1
                self.mgr.requeue_quarantined(cname, key)

    async def _converge_and_check(self) -> list[str]:
        """Lift faults, force a global watch reset (every informer relists
        a clean view, the kubelet sim resyncs), settle, release storm-era
        quarantines, and run the invariant checks — retrying the
        settle+check loop until they pass or the timeout expires (a check
        can race the final benign requeues; a REAL violation is stable
        and survives to the timeout)."""
        self._lift_faults()
        # Revocations complete between storms: the dying spot nodes are
        # replaced (taints clear), so reclaimed pools re-open and the
        # drained gangs can re-admit.
        for node in self._spot_nodes:
            try:
                await self.kube.patch("Node", node,
                                      {"spec": {"taints": None}})
            except ApiError:
                pass
        self.kube.close_watches()
        deadline = time.monotonic() + self.cfg.converge_timeout
        released = False
        problems = [f"no convergence within {self.cfg.converge_timeout}s"]
        while time.monotonic() < deadline:
            if not await self._settle_streak(deadline):
                break
            if not released:
                self._release_transient_quarantines()
                released = True
                continue  # settle again after the requeues
            for cname, queue in self.mgr._queues.items():
                for key in queue.quarantined_keys():
                    problems = [
                        f"workqueue {cname}: key re-quarantined with no "
                        f"faults active (permanently wedged): {key}"]
                    return problems
            problems = await check_invariants(self.kube, self.mgr,
                                              self.sched, self.warmpool)
            if not problems:
                return []
            await asyncio.sleep(0.05)
        return problems

    # -- entry point ---------------------------------------------------------

    async def run(self) -> SoakReport:
        cfg = self.cfg
        await self._seed_cluster()   # fleet source exists before the
        await self._start()          # first admission pass runs
        sdk_stop = asyncio.Event()
        sdk_task = asyncio.create_task(self._sdk_loop(sdk_stop))
        sim = PodSimulator(self.kube)
        await sim.start()
        try:
            await self._seed_notebooks()
            for p in await self._converge_and_check():
                self.report.problems.append(f"initial: {p}")
            await self._kick_elastic()
            for round_no in range(cfg.rounds):
                self.report.rounds += 1
                self._arm_faults()
                await self._kick_checkpoints()
                t_end = time.monotonic() + cfg.storm_seconds
                kill_at = time.monotonic() + cfg.storm_seconds * \
                    self.rng.uniform(0.3, 0.7)
                killed = False
                while time.monotonic() < t_end:
                    await self._churn_once()
                    if not killed and time.monotonic() >= kill_at:
                        # Kill mid-reconcile, restart while the fault
                        # storm is still blowing: the new manager's first
                        # lists/reclaims run against a faulty apiserver.
                        await self._kill_manager()
                        self._build_stack()
                        await self.mgr.start()
                        killed = True
                    await asyncio.sleep(self.rng.uniform(0.01, 0.04))
                if not killed:
                    await self._kill_manager()
                    self._build_stack()
                    await self.mgr.start()
                for p in await self._converge_and_check():
                    self.report.problems.append(f"round {round_no}: {p}")
                for p in await self._check_restores():
                    self.report.problems.append(
                        f"round {round_no} restore: {p}")
        finally:
            sdk_stop.set()
            sdk_task.cancel()
            await reap(sdk_task)
            await sim.stop()
            # Each scheduler instance's cumulative counter is harvested
            # exactly once — at its death (_kill_manager for mid-soak
            # instances, here for the last one); summing it per round as
            # well would double-count a violation across round boundaries.
            self.report.ledger_violations += \
                self.sched.policy.ledger.violations
            await self.mgr.stop()
            self.kube.use_faults(None)
            self.kube.close_watches()
            for fab in self._fabrics.values():
                await asyncio.to_thread(fab.close)
            shutil.rmtree(self._fabric_root, ignore_errors=True)
        return self.report


async def run_soak(config: SoakConfig) -> SoakReport:
    return await ChaosSoak(config).run()


# ---- poison-pill scenario ------------------------------------------------------


async def poison_scenario(seed: int = 0, *, quarantine_after: int = 6) -> dict:
    """The deliberate poison pill (acceptance gate): a CR whose children
    can never apply must be quarantined within the retry budget, surface
    the Degraded condition + quarantined debug row, and resume — and
    converge — on the next spec edit once the fault is gone."""
    from kubeflow_tpu.web.common.status import process_status

    kube = FakeKube()
    register_all(kube)
    plan = FaultPlan(seed=seed)
    # The poison: every write to this notebook's StatefulSets fails — an
    # admission webhook black-holing the child, a broken CRD, a bad node
    # selector... the reconcile itself always errors; the CR's own status
    # surface stays writable (as it would be in each of those cases).
    rule = plan.fail("internal", verbs=("create", "update", "patch"),
                    kinds="StatefulSet", names="poison*")
    kube.use_faults(plan)
    mgr = Manager(kube, registry=Registry(), quarantine_after=quarantine_after)
    setup_notebook_controller(mgr, NotebookOptions(), scheduler=None)
    for q in mgr._queues.values():
        q.base_delay = 0.002
        q.max_delay = 0.05
    sim = PodSimulator(kube)
    await mgr.start()
    await sim.start()
    out: dict = {"seed": seed, "quarantine_after": quarantine_after}
    try:
        await kube.create("Notebook", nbapi.new(
            "poison", "ns", accelerator="v5e", topology="4x4"))
        queue = mgr._queues["notebook"]
        key = ("ns", "poison")

        deadline = time.monotonic() + 20
        while not queue.is_quarantined(key):
            if time.monotonic() > deadline:
                out["quarantined"] = False
                return out
            await asyncio.sleep(0.02)
        out["quarantined"] = True
        out["failures_at_quarantine"] = queue.poison_streak(key)
        out["within_budget"] = \
            queue.poison_streak(key) == quarantine_after

        await mgr.wait_idle(timeout=10)
        nb = await kube.get("Notebook", "poison", "ns")
        cond = next((c for c in deep_get(
            nb, "status", "conditions", default=[])
            if c.get("type") == "Degraded"), None)
        out["degraded_condition"] = bool(
            cond and cond.get("status") == "True"
            and cond.get("reason") == "ReconcileQuarantined")
        status = process_status(nb)
        out["jwa_message_ok"] = (
            status.phase == "warning"
            and "Reconciliation suspended" in status.message)
        events = await kube.list("Event", "ns")
        out["warning_event"] = any(
            e.get("reason") == "ReconcileQuarantined" for e in events)
        dbg = mgr.debug_queues()["notebook"]
        out["debug_row"] = "('ns', 'poison')" in dbg["quarantined"]

        # The cure: fault gone + spec edit → new informer delta rv →
        # automatic release → clean reconcile → Degraded flips False.
        plan.drop(rule)
        await kube.patch(
            "Notebook", "poison",
            {"metadata": {"annotations": {"fixed": "yes"}}}, "ns")
        deadline = time.monotonic() + 20
        while queue.is_quarantined(key):
            if time.monotonic() > deadline:
                out["released"] = False
                return out
            await asyncio.sleep(0.02)
        out["released"] = True
        await mgr.wait_idle(timeout=20)
        sts = await kube.get_or_none("StatefulSet", "poison", "ns")
        nb = await kube.get("Notebook", "poison", "ns")
        cond = next((c for c in deep_get(
            nb, "status", "conditions", default=[])
            if c.get("type") == "Degraded"), None)
        out["reconciled_after_release"] = sts is not None
        out["degraded_cleared"] = bool(cond) and cond.get("status") == "False"
        out["pass"] = all(out.get(k) for k in (
            "quarantined", "within_budget", "degraded_condition",
            "jwa_message_ok", "warning_event", "debug_row", "released",
            "reconciled_after_release", "degraded_cleared"))
        return out
    finally:
        await sim.stop()
        await mgr.stop()
        kube.use_faults(None)
        kube.close_watches()


# ---- shard-kill scenario -------------------------------------------------------


async def shard_kill_scenario(
    seed: int = 0,
    *,
    shards: int = 4,
    replicas: int = 3,
    notebooks_per_namespace: int = 2,
    lease_seconds: float = 0.6,
    renew_seconds: float = 0.15,
    converge_timeout: float = 30.0,
) -> dict:
    """Kill one shard of N mid-flight (ISSUE 17): N manager replicas run
    active-active over one FakeKube, each reconciling only the namespace-
    hash shards whose leases it holds. A non-arbiter replica is crash-
    killed — leases left to expire, its queued keys dying with its
    workqueues — the moment fresh work lands on its keyspace. Survivors
    must absorb the orphaned shards within ~lease expiry plus the two-
    tick orphan confirmation, converge EVERY notebook including the ones
    created just before the kill (zero dropped queued keys), and restore
    the global invariants (ledger, timeline continuity, drained queues)
    with shard ownership still disjoint.

    The arbiter replica (preferred owner of shard 0) is never the
    victim: the shared scheduler instance stands in for "per-shard
    admission queues feeding one elected arbiter", and arbiter failover
    is controller-restart semantics the main soak already exercises.

    Deterministic end to end (lease protocol + FakeKube, no fault RNG);
    ``seed`` tags the report so the CI matrix stays uniform.
    """
    from kubeflow_tpu.runtime.sharding import (
        ARBITER_SHARD,
        ShardRing,
        shard_of,
    )

    if replicas < 2 or shards < 2:
        raise ValueError("shard-kill needs >= 2 replicas and >= 2 shards")
    kube = FakeKube()
    register_all(kube)
    await kube.create("ConfigMap", {
        "apiVersion": "v1", "kind": "ConfigMap",
        "metadata": {"name": "kftpu-fleet", "namespace": "kubeflow-tpu"},
        "data": {"fleet": "pool-a=v5e:2x2:64"},
    })

    # Enough namespaces that every shard owns at least two — the victim's
    # keyspace must be non-trivial for the absorption to prove anything.
    by_shard: dict[int, list] = {s: [] for s in range(shards)}
    namespaces: list[str] = []
    i = 0
    while any(len(v) < 2 for v in by_shard.values()):
        ns = f"team-{i}"
        i += 1
        by_shard[shard_of(ns, shards)].append(ns)
        namespaces.append(ns)
        if i > 64 * shards:  # crc32 would have to be badly broken
            raise RuntimeError("could not cover every shard with namespaces")

    # ONE scheduler for the whole fleet: the in-process arbiter seam
    # (scheduler/runtime.py attach_ring) — every replica's reconcilers
    # feed it, only the arbiter-shard holder's ring activates it.
    sched = TpuFleetScheduler(
        kube,
        SchedulerOptions(
            queued_requeue_seconds=0.5,
            fleet_configmap="kftpu-fleet",
            controller_namespace="kubeflow-tpu",
            fleet_refresh_seconds=0.05,
        ),
        registry=Registry(),
    )

    rings: list[ShardRing] = []
    mgrs: list[Manager] = []
    for r in range(replicas):
        reg = Registry()
        ring = ShardRing(
            kube, shards=shards, replica=r, replicas=replicas,
            lease_seconds=lease_seconds, renew_seconds=renew_seconds,
            registry=reg)
        mgr = Manager(kube, registry=reg, shard_ring=ring)
        setup_notebook_controller(mgr, NotebookOptions(), scheduler=sched)
        for q in mgr._queues.values():
            q.base_delay = 0.002
            q.max_delay = 0.05
        for inf in mgr.informers.values():
            inf.resync_backoff = 0.02
            inf.resync_backoff_max = 0.2
        rings.append(ring)
        mgrs.append(mgr)
    arbiter_replica = ARBITER_SHARD % replicas
    victim = (replicas - 1 if replicas - 1 != arbiter_replica
              else replicas - 2)
    # setup wiring leaves sched._nb_informer pointing at the LAST
    # manager's (filtered) cache; pin it to the arbiter's so the shared
    # scheduler never reads through a dead replica's stopped informer.
    sched._nb_informer = mgrs[arbiter_replica].informer_for("Notebook")
    sched.attach_ring(rings[arbiter_replica])

    sim = PodSimulator(kube)
    out: dict = {
        "seed": seed,
        "shards": shards,
        "replicas": replicas,
        "namespaces": len(namespaces),
        "victim_replica": victim,
    }
    stopped: set[int] = set()
    try:
        for r in range(replicas):
            await rings[r].start()
            await mgrs[r].start()
        await sim.start()

        names: list[tuple] = []
        for ns in namespaces:
            for j in range(notebooks_per_namespace):
                name = f"nb-{j}"
                await kube.create("Notebook", nbapi.new(
                    name, ns, accelerator="v5e", topology="2x2"))
                names.append((ns, name))
        out["notebooks"] = len(names)

        async def wait_ready(want_keys, timeout: float) -> set:
            pending = set(want_keys)
            deadline = time.monotonic() + timeout
            while pending and time.monotonic() < deadline:
                for ns, name in sorted(pending):
                    nb = await kube.get_or_none("Notebook", name, ns)
                    if nb is None:
                        continue
                    want = deep_get(
                        nb, "status", "tpu", "hosts", default=1) or 1
                    got = deep_get(
                        nb, "status", "readyReplicas", default=0) or 0
                    if got >= want:
                        pending.discard((ns, name))
                await asyncio.sleep(0.02)
            return pending

        not_ready = await wait_ready(names, converge_timeout)
        out["pre_kill_ready"] = len(names) - len(not_ready)
        out["pre_kill_converged"] = not not_ready

        victim_shards = set(rings[victim].owned)
        victim_namespaces = [
            ns for ns in namespaces
            if shard_of(ns, shards) in victim_shards]
        out["victim_shards"] = sorted(victim_shards)
        out["victim_namespaces"] = len(victim_namespaces)

        # Fresh keys on the victim's keyspace, then an immediate crash:
        # these land in the victim's workqueues (watch delta → enqueue)
        # and die with them. Zero-dropped-keys means every one still
        # converges, re-discovered by the absorbing survivor's
        # refill-on-acquire and live-predicate filtered watch.
        post_keys: list[tuple] = []
        for ns in victim_namespaces:
            await kube.create("Notebook", nbapi.new(
                "post-kill", ns, accelerator="v5e", topology="2x2"))
            post_keys.append((ns, "post-kill"))
        out["post_kill_created"] = len(post_keys)

        t_kill = time.monotonic()
        await rings[victim].kill()  # crash: no lease release, no fencing
        await mgrs[victim].stop()   # workers die mid-flight, queues lost
        stopped.add(victim)

        survivors = [r for r in range(replicas) if r != victim]
        deadline = (time.monotonic() + lease_seconds
                    + 20 * renew_seconds + 5)
        absorbed = False
        while time.monotonic() < deadline:
            held: set[int] = set()
            for r in survivors:
                held |= rings[r].owned
            if victim_shards <= held:
                absorbed = True
                break
            await asyncio.sleep(renew_seconds / 2)
        out["absorbed"] = absorbed
        out["failover_seconds"] = round(time.monotonic() - t_kill, 3)

        still_pending = await wait_ready(
            names + post_keys, converge_timeout)
        out["dropped_keys"] = sorted(
            f"{ns}/{name}" for ns, name in still_pending)
        out["all_ready_after_kill"] = not still_pending

        for r in survivors:
            await mgrs[r].wait_idle(timeout=15)

        owned_sets = [set(rings[r].owned) for r in survivors]
        union: set[int] = set().union(*owned_sets)
        disjoint = sum(len(s) for s in owned_sets) == len(union)
        out["ownership_disjoint"] = disjoint
        out["all_shards_owned"] = union == set(range(shards))

        problems: list[str] = []
        for r in survivors:
            for p in await check_invariants(kube, mgrs[r], sched, None):
                problems.append(f"replica {r}: {p}")
        out["invariant_problems"] = problems

        out["pass"] = bool(
            out.get("pre_kill_converged")
            and victim_shards
            and post_keys
            and absorbed
            and out.get("all_ready_after_kill")
            and disjoint
            and out.get("all_shards_owned")
            and not problems)
        return out
    finally:
        await sim.stop()
        for r in range(replicas):
            if r not in stopped:
                await mgrs[r].stop()
                await rings[r].stop()
        kube.close_watches()
