"""Frontend-execution harness: the vendored JS runtime driving the real
aiohttp backends over real HTTP.

``JsWebHarness`` owns a private asyncio loop so the Browser's synchronous
``fetch`` bridge can run aiohttp coroutines to completion mid-JS — the
control plane (manager + pod simulator) lives on the same loop, so
reconciles progress while the frontend polls, exactly like the reference's
Cypress runs against a live backend (SURVEY.md §4.3), except here the
backend is real, not fixture-mocked.
"""

from __future__ import annotations

import asyncio

import aiohttp
from aiohttp.test_utils import TestClient, TestServer

from kubeflow_tpu.testing.jsrt import Browser

USER_HEADERS = {"kubeflow-userid": "alice@example.com"}


class JsWebHarness:
    """Sync facade over the async control plane + one web app + a Browser.

    Use as a context manager from *synchronous* tests::

        with JsWebHarness(create_jwa) as h:
            h.browser.load("/")
            h.settle()                      # let controllers reconcile
            h.browser.advance(5000)         # fire the poller
    """

    def __init__(self, create_app, *, user=None, extra_controllers=()):
        from kubeflow_tpu.controllers.notebook import setup_notebook_controller
        from kubeflow_tpu.runtime.manager import Manager
        from kubeflow_tpu.testing.fakekube import FakeKube
        from kubeflow_tpu.testing.podsim import PodSimulator
        from kubeflow_tpu.webhooks import register_all

        self.loop = asyncio.new_event_loop()
        self.kube = FakeKube()
        register_all(self.kube)
        self.mgr = Manager(self.kube)
        setup_notebook_controller(self.mgr)
        for setup in extra_controllers:
            setup(self.mgr)
        self.sim = PodSimulator(self.kube)
        self._create_app = create_app
        self.user = dict(user or USER_HEADERS)
        self.client: TestClient | None = None
        self.browser = Browser(self._http)

    # -- lifecycle ---------------------------------------------------------------

    def __enter__(self) -> "JsWebHarness":
        self.loop.run_until_complete(self._astart())
        return self

    def __exit__(self, *exc) -> None:
        self.loop.run_until_complete(self._astop())
        self.loop.close()

    async def _astart(self) -> None:
        await self.mgr.start()
        await self.sim.start()
        self.client = TestClient(
            TestServer(self._create_app(self.kube)),
            cookie_jar=aiohttp.DummyCookieJar(),  # the Browser owns cookies
        )
        await self.client.start_server()

    async def _astop(self) -> None:
        if self.client is not None:
            await self.client.close()
        await self.sim.stop()
        await self.mgr.stop()
        self.kube.close_watches()

    # -- the Browser's transport -------------------------------------------------

    def _http(self, method, path, headers, body):
        return self.loop.run_until_complete(
            self._arequest(method, path, headers, body))

    async def _arequest(self, method, path, headers, body):
        send = {**self.user, **headers}
        resp = await self.client.request(
            method, path, headers=send, data=body)
        text = await resp.text()
        header_pairs = []
        for key in resp.headers:
            for value in resp.headers.getall(key):
                header_pairs.append((key, value))
        await resp.release()
        return resp.status, resp.reason or "", header_pairs, text

    # -- control-plane helpers ---------------------------------------------------

    def settle(self, rounds: int = 6) -> None:
        async def go():
            for _ in range(rounds):
                await self.mgr.wait_idle(timeout=20)
                await asyncio.sleep(0.02)
        self.loop.run_until_complete(go())

    def kube_get(self, kind, name, ns=None):
        return self.loop.run_until_complete(
            self.kube.get_or_none(kind, name, ns))

    def kube_list(self, kind, ns=None):
        return self.loop.run_until_complete(self.kube.list(kind, ns))

    def kube_create(self, kind, obj):
        return self.loop.run_until_complete(self.kube.create(kind, obj))

    def kube_patch(self, kind, name, patch, ns=None):
        return self.loop.run_until_complete(
            self.kube.patch(kind, name, patch, ns))

    def poll_ui(self, ms_per_round: int = 5000, rounds: int = 2) -> None:
        """Settle the control plane and step the UI's pollers."""
        for _ in range(rounds):
            self.settle()
            self.browser.advance(ms_per_round)
