"""In-memory Kubernetes apiserver with real API semantics.

Implements the ``KubeApi`` surface the controllers use, with the semantics
that matter for correctness testing:

- resourceVersion on every write + Conflict on stale full updates
- metadata.generation bumped on spec changes, mirrored nowhere (status is a
  subresource, like the real server)
- watches with ADDED/MODIFIED/DELETED events fanned out per watcher
- finalizers + deletionTimestamp two-phase delete
- ownerReference cascade deletion (background propagation)
- admission plugin chain (mutating then validating) so webhook logic is
  exercised through the same path the real apiserver would drive it
- namespace existence is NOT enforced (matches envtest looseness) but
  namespace-scoped listing/selectors are

This is our envtest (reference: suite_test.go boots envtest with CRDs;
here CRDs are just registered kinds in the scheme).
"""

from __future__ import annotations

import asyncio
import copy
import fnmatch
import random
import time
import uuid
from collections import defaultdict, deque
from typing import Any, AsyncIterator, Awaitable, Callable

from kubeflow_tpu.runtime import tracing

from kubeflow_tpu.runtime.errors import (
    AlreadyExists,
    ApiError,
    Conflict,
    Invalid,
    NotFound,
    ServerTimeout,
    TooManyRequests,
)
from kubeflow_tpu.runtime.objects import (
    deep_get,
    deepcopy,
    get_meta,
    matches_selector,
    name_of,
    namespace_of,
    parse_label_selector,
)
from kubeflow_tpu.runtime.objects import now_iso as _now
from kubeflow_tpu.runtime.scheme import DEFAULT_SCHEME, Scheme

Mutator = Callable[[dict, dict], Awaitable[None] | None]  # (obj, request-info)
Validator = Callable[[dict, dict], Awaitable[None] | None]


class _Watch:
    def __init__(self, kind: str, namespace: str | None, selector: dict | None,
                 field_selector: Callable[[dict], bool] | None = None):
        self.kind = kind
        self.namespace = namespace
        self.selector = selector
        self.field_selector = field_selector
        self.queue: asyncio.Queue[tuple[str, dict] | None] = asyncio.Queue()

    def wants(self, obj: dict) -> bool:
        if self.namespace and namespace_of(obj) != self.namespace:
            return False
        if not matches_selector(get_meta(obj).get("labels"), self.selector):
            return False
        if self.field_selector is not None:
            # Same contract as list(): the predicate runs against the LIVE
            # store dict and must be pure. A predicate that reads mutable
            # external state (a shard ring) re-evaluates per event — that
            # is the point: filtered informers follow ownership changes.
            try:
                return bool(self.field_selector(obj))
            except Exception:
                return False
        return True


def _injected_error(error: str) -> ApiError:
    """Build the ApiError an injected fault surfaces as. The five flavors
    cover the real apiserver's transient-failure taxonomy: 500 internal,
    503 overloaded/apiserver-restarting, 504 client deadline, 409 optimistic
    concurrency, 429 priority & fairness."""
    if error == "timeout":
        return ServerTimeout("injected fault: no response within deadline")
    if error == "conflict":
        return Conflict("injected fault: the object has been modified")
    if error == "throttle":
        return TooManyRequests("injected fault: too many requests")
    err = ApiError(f"injected fault: {error}")
    err.code = {"internal": 500, "unavailable": 503}.get(error, 500)
    err.reason = {"internal": "InternalError",
                  "unavailable": "ServiceUnavailable"}.get(error, error)
    return err


class FaultRule:
    """One scheduled fault: which requests it matches and how it fails them.

    ``verbs=None`` matches every verb; ``kinds``/``names`` are fnmatch
    globs. ``rate`` is the per-matching-request injection probability
    (drawn from the plan's seeded RNG — deterministic per seed + request
    order), ``after`` skips the first N matching requests, and ``times``
    bounds total injections (None = unlimited, e.g. a permanent poison).
    """

    ERRORS = ("internal", "unavailable", "timeout", "conflict", "throttle")

    def __init__(self, error: str = "unavailable", *,
                 verbs: tuple[str, ...] | None = None,
                 kinds: str = "*", names: str = "*",
                 rate: float = 1.0, times: int | None = None,
                 after: int = 0):
        if error not in self.ERRORS:
            raise ValueError(f"unknown fault error {error!r}; "
                             f"want one of {self.ERRORS}")
        self.error = error
        self.verbs = tuple(verbs) if verbs is not None else None
        self.kinds = kinds
        self.names = names
        self.rate = rate
        self.times = times
        self.after = after
        self.injected = 0
        self._seen = 0

    def matches(self, verb: str, kind: str, name: str) -> bool:
        if self.verbs is not None and verb not in self.verbs:
            return False
        return fnmatch.fnmatch(kind, self.kinds) and \
            fnmatch.fnmatch(name or "", self.names)

    def consume(self, rng: random.Random, verb: str, kind: str,
                name: str) -> bool:
        """True if this rule injects for the matching request. The RNG is
        consulted only for probabilistic rules, so deterministic schedules
        (rate=1.0) never perturb the seed stream."""
        if not self.matches(verb, kind, name):
            return False
        if self.times is not None and self.injected >= self.times:
            return False
        self._seen += 1
        if self._seen <= self.after:
            return False
        if self.rate < 1.0 and rng.random() >= self.rate:
            return False
        self.injected += 1
        return True


class _WatchResetRule:
    def __init__(self, kinds: str, rate: float, every: int | None):
        self.kinds = kinds
        self.rate = rate
        self.every = every
        self.triggered = 0
        self._seen = 0

    def consume(self, rng: random.Random, kind: str) -> bool:
        if not fnmatch.fnmatch(kind, self.kinds):
            return False
        self._seen += 1
        if self.every is not None:
            if self._seen % self.every:
                return False
        elif rng.random() >= self.rate:
            return False
        self.triggered += 1
        return True


class _StorageRule:
    """One storage-fault schedule (checkpoint fabric): matches a tier
    glob, fires after every ``every``-th probe or with probability
    ``rate``, bounded by ``times``; ``seconds`` carries the slow-tier
    delay."""

    def __init__(self, tiers: str = "*", *, rate: float = 0.0,
                 every: int | None = None, times: int | None = None,
                 seconds: float = 0.0):
        self.tiers = tiers
        self.rate = rate
        self.every = every
        self.times = times
        self.seconds = seconds
        self.triggered = 0
        self._seen = 0

    def consume(self, rng: random.Random, tier: str = "") -> bool:
        if not fnmatch.fnmatch(tier or "", self.tiers):
            return False
        if self.times is not None and self.triggered >= self.times:
            return False
        self._seen += 1
        if self.every is not None:
            if self._seen % self.every:
                return False
        elif rng.random() >= self.rate:
            return False
        self.triggered += 1
        return True


class FaultPlan:
    """Deterministic, seeded API fault schedule for :class:`FakeKube`.

    The failure paths the reference stack never exercised (SURVEY.md §5),
    one injection point per apiserver behavior:

    - request errors (``fail``): matched in ``FakeKube._admit`` after
      flow-control admission and the RTT sleep, so faults compose with
      the latency and priority-and-fairness mirrors;
    - mid-stream watch resets (``reset_watch``): the server closes the
      stream after a delivered event — informers must relist;
    - stale LISTs (``stale_list``): the server answers from its previous
      snapshot of the kind (an old-resourceVersion read) — informer
      caches must self-correct on a later relist.

    Storage faults (checkpoint fabric, ISSUE 16) ride the same plan:
    the fabric duck-types its ``faults`` object against the
    ``should_*``/``storage_delay`` probes below, so a plan armed with
    :meth:`crash_upload` / :meth:`tear_manifest` / :meth:`corrupt_read`
    / :meth:`slow_tier` / :meth:`stale_staging` drives the
    crash-mid-upload, torn-manifest, read-corruption, slow-tier, and
    stale-staging-pointer windows deterministically.

    All randomness comes from one ``random.Random(seed)``: the same seed
    over the same request sequence replays the same fault schedule.
    """

    def __init__(self, seed: int = 0):
        self.seed = seed
        self._rng = random.Random(seed)
        self.rules: list[FaultRule] = []
        self._watch_rules: list[_WatchResetRule] = []
        self._stale_rules: list[FaultRule] = []
        self._reclaim_rules: list[_WatchResetRule] = []
        # Storage-fault buckets (one per fabric probe). The storage RNG
        # is separate so arming checkpoint faults never perturbs the API
        # fault schedule of an existing seed.
        self._storage_rng = random.Random((seed << 4) ^ 0x5EED)
        self._crash_upload_rules: list[_StorageRule] = []
        self._fail_upload_rules: list[_StorageRule] = []
        self._tear_rules: list[_StorageRule] = []
        self._corrupt_rules: list[_StorageRule] = []
        self._slow_rules: list[_StorageRule] = []
        self._stale_staging_rules: list[_StorageRule] = []
        # Per-error injection counts — the soak report and tests assert
        # faults actually fired.
        self.injected: dict[str, int] = defaultdict(int)

    def fail(self, error: str = "unavailable", *,
             verbs: tuple[str, ...] | None = None,
             kinds: str = "*", names: str = "*", rate: float = 1.0,
             times: int | None = None, after: int = 0) -> FaultRule:
        rule = FaultRule(error, verbs=verbs, kinds=kinds, names=names,
                         rate=rate, times=times, after=after)
        self.rules.append(rule)
        return rule

    def reset_watch(self, kinds: str = "*", *, rate: float = 0.0,
                    every: int | None = None) -> _WatchResetRule:
        """Close matching watch streams mid-flight: after every ``every``-th
        delivered event, or with probability ``rate`` per event."""
        rule = _WatchResetRule(kinds, rate, every)
        self._watch_rules.append(rule)
        return rule

    def stale_list(self, kinds: str = "*", *, rate: float = 1.0,
                   times: int | None = None, after: int = 0) -> FaultRule:
        """Serve matching LISTs from the kind's previous snapshot."""
        rule = FaultRule("unavailable", verbs=("list",), kinds=kinds,
                         rate=rate, times=times, after=after)
        self._stale_rules.append(rule)
        return rule

    def reclaim_spot(self, pools: str = "*", *, rate: float = 0.0,
                     every: int | None = None) -> _WatchResetRule:
        """Seeded spot-revocation schedule for harnesses (chaos soak,
        bench reclaim storm): each :meth:`should_reclaim_spot` probe
        consults it — after every ``every``-th probe of a matching pool,
        or with probability ``rate`` per probe. The harness acts on a
        True by tainting the pool's Node through the normal API, so the
        control plane sees exactly what GKE would send; determinism
        comes from the plan's one seeded RNG."""
        rule = _WatchResetRule(pools, rate, every)
        self._reclaim_rules.append(rule)
        return rule

    def should_reclaim_spot(self, pool: str) -> bool:
        for rule in self._reclaim_rules:
            if rule.consume(self._rng, pool):
                self.injected["spot_reclaim"] += 1
                return True
        return False

    # ---- storage faults (checkpoint fabric) ------------------------------------

    def crash_upload(self, *, rate: float = 0.0, every: int | None = None,
                     times: int | None = None) -> _StorageRule:
        """Kill the uploading process mid-chunk-stream: the fabric aborts
        the upload with partial chunks in the remote tier and NO commit —
        the chaos invariant is that such a step is never restored."""
        rule = _StorageRule(rate=rate, every=every, times=times)
        self._crash_upload_rules.append(rule)
        return rule

    def fail_upload(self, *, rate: float = 0.0, every: int | None = None,
                    times: int | None = None) -> _StorageRule:
        """Transient upload error — the fabric's bounded retry/backoff
        must absorb it (unlike :meth:`crash_upload`, which is fatal to
        the attempt)."""
        rule = _StorageRule(rate=rate, every=every, times=times)
        self._fail_upload_rules.append(rule)
        return rule

    def tear_manifest(self, tiers: str = "*", *, rate: float = 0.0,
                      every: int | None = None,
                      times: int | None = None) -> _StorageRule:
        """Write a truncated manifest at the final path (non-atomic
        backend / partial replication) — restore's self-checksum must
        refuse it and fall back."""
        rule = _StorageRule(tiers, rate=rate, every=every, times=times)
        self._tear_rules.append(rule)
        return rule

    def corrupt_read(self, tiers: str = "*", *, rate: float = 0.0,
                     every: int | None = None,
                     times: int | None = None) -> _StorageRule:
        """Flip bits on a chunk read — hash verification must catch it
        (staging corruption falls through to remote; remote corruption
        falls back a step)."""
        rule = _StorageRule(tiers, rate=rate, every=every, times=times)
        self._corrupt_rules.append(rule)
        return rule

    def slow_tier(self, tiers: str = "*", *, seconds: float,
                  rate: float = 1.0, every: int | None = None,
                  times: int | None = None) -> _StorageRule:
        """Add per-operation latency to a tier (a degraded disk or an
        overloaded object store)."""
        rule = _StorageRule(tiers, rate=rate, every=every, times=times,
                            seconds=seconds)
        self._slow_rules.append(rule)
        return rule

    def stale_staging(self, *, rate: float = 0.0, every: int | None = None,
                      times: int | None = None) -> _StorageRule:
        """Silently skip the staging tier's pointer advance — restore
        must trust the remote committed pointer, never the stale local
        one."""
        rule = _StorageRule(rate=rate, every=every, times=times)
        self._stale_staging_rules.append(rule)
        return rule

    # Fabric-facing probes (duck-typed; see kubeflow_tpu/checkpoint).

    def should_crash_upload(self) -> bool:
        for rule in self._crash_upload_rules:
            if rule.consume(self._storage_rng):
                self.injected["storage_crash_upload"] += 1
                return True
        return False

    def should_fail_upload(self) -> bool:
        for rule in self._fail_upload_rules:
            if rule.consume(self._storage_rng):
                self.injected["storage_fail_upload"] += 1
                return True
        return False

    def should_tear_manifest(self, tier: str) -> bool:
        for rule in self._tear_rules:
            if rule.consume(self._storage_rng, tier):
                self.injected["storage_torn_manifest"] += 1
                return True
        return False

    def should_corrupt_read(self, tier: str) -> bool:
        for rule in self._corrupt_rules:
            if rule.consume(self._storage_rng, tier):
                self.injected["storage_read_corrupt"] += 1
                return True
        return False

    def storage_delay(self, tier: str) -> float:
        total = 0.0
        for rule in self._slow_rules:
            if rule.consume(self._storage_rng, tier):
                self.injected["storage_slow_tier"] += 1
                total += rule.seconds
        return total

    def should_skip_staging_commit(self) -> bool:
        for rule in self._stale_staging_rules:
            if rule.consume(self._storage_rng):
                self.injected["storage_stale_staging"] += 1
                return True
        return False

    def clear(self) -> None:
        """Lift every fault (rules stay readable for their counters)."""
        self.rules = []
        self._watch_rules = []
        self._stale_rules = []
        self._reclaim_rules = []
        self._crash_upload_rules = []
        self._fail_upload_rules = []
        self._tear_rules = []
        self._corrupt_rules = []
        self._slow_rules = []
        self._stale_staging_rules = []

    def drop(self, rule) -> None:
        for bucket in (self.rules, self._watch_rules, self._stale_rules,
                       self._reclaim_rules, self._crash_upload_rules,
                       self._fail_upload_rules, self._tear_rules,
                       self._corrupt_rules, self._slow_rules,
                       self._stale_staging_rules):
            if rule in bucket:
                bucket.remove(rule)

    # ---- FakeKube-facing hooks ------------------------------------------------

    def error_for(self, verb: str, kind: str, name: str | None) -> ApiError | None:
        for rule in self.rules:
            if rule.consume(self._rng, verb, kind, name or ""):
                self.injected[rule.error] += 1
                return _injected_error(rule.error)
        return None

    def watch_should_reset(self, kind: str) -> bool:
        for rule in self._watch_rules:
            if rule.consume(self._rng, kind):
                self.injected["watch_reset"] += 1
                return True
        return False

    def list_is_stale(self, kind: str) -> bool:
        for rule in self._stale_rules:
            if rule.consume(self._rng, "list", kind, ""):
                self.injected["stale_list"] += 1
                return True
        return False

    def debug_info(self) -> dict:
        return {
            "seed": self.seed,
            "injected": dict(sorted(self.injected.items())),
            "active_rules": len(self.rules) + len(self._watch_rules)
            + len(self._stale_rules) + len(self._reclaim_rules)
            + len(self._crash_upload_rules) + len(self._fail_upload_rules)
            + len(self._tear_rules) + len(self._corrupt_rules)
            + len(self._slow_rules) + len(self._stale_staging_rules),
        }


class FakeKube:
    """The in-memory apiserver. All methods are async and deep-copy at the boundary."""

    WRITE_VERBS = ("create", "update", "update_status", "patch", "delete")

    def __init__(self, scheme: Scheme | None = None):
        self.scheme = scheme or DEFAULT_SCHEME
        self._store: dict[str, dict[tuple[str | None, str], dict]] = defaultdict(dict)
        self._rv = 0
        self._watches: list[_Watch] = []
        self._mutators: list[tuple[str, Mutator]] = []      # (kind-glob, fn)
        self._validators: list[tuple[str, Validator]] = []
        self._pod_logs: dict[tuple[str | None, str], str] = {}
        self._lock = asyncio.Lock()
        # Per-verb request counter (client entry points only — cascade GC
        # and admission are server-side work, not requests). Lets tests and
        # the bench PROVE write elision: a steady-state no-op reconcile
        # must move none of the write verbs.
        self.requests: dict[str, int] = defaultdict(int)
        # Bounded request log with the headers a real client would have
        # sent — in particular X-Request-Id carrying the active trace id,
        # mirroring HttpKube — plus per-request start/end monotonic
        # stamps, so latency tests can prove which requests overlapped.
        # Tests pin controller → request-header → flight-recorder
        # trace-id propagation against it.
        self.request_log: deque[dict] = deque(maxlen=1000)
        # Injectable per-request latency (set_latency): simulated network
        # RTT, slept OUTSIDE the store lock so concurrent requests
        # overlap exactly as real round trips do.
        self.latency = 0.0
        self.latency_jitter = 0.0
        self._rng = random.Random(0)  # deterministic jitter for tests
        # In-flight high-water gauge: the proof that requests actually
        # overlap (serial clients never exceed 1).
        self._in_flight = 0
        self.in_flight_peak = 0
        # Optional client-side flow control (runtime/flowcontrol.py),
        # mirroring HttpKube so lane behavior is testable in tier-1.
        self.flow = None
        # Optional API fault injection (use_faults): checked in _admit
        # after flow admission + RTT, so every fault composes with the
        # latency and flow-control mirrors.
        self.faults: FaultPlan | None = None
        # Previous LIST snapshot per kind — what a stale LIST serves.
        self._list_snapshots: dict[str, tuple[list[dict], str]] = {}

    # ---- latency / concurrency instrumentation --------------------------------

    def set_latency(self, seconds: float, jitter: float = 0.0) -> None:
        """Inject per-request latency (+ uniform jitter) — the simulated
        RTT every request pays before touching the store."""
        self.latency = seconds
        self.latency_jitter = jitter

    def use_flow_control(self, flow) -> None:
        """Route every request through a FlowControl lane gate, as
        HttpKube does on the wire."""
        self.flow = flow

    def use_faults(self, plan: FaultPlan | None) -> None:
        """Attach (or with None, detach) a FaultPlan; see its docstring."""
        self.faults = plan

    def reset_in_flight_peak(self) -> None:
        self.in_flight_peak = 0

    def _log_request(self, verb: str, kind: str, name: str | None = None,
                     namespace: str | None = None) -> dict:
        self.requests[verb] += 1
        trace_id = tracing.current_trace_id()
        entry = {
            "verb": verb,
            "kind": kind,
            "name": name,
            "namespace": namespace,
            "headers": {"X-Request-Id": trace_id} if trace_id else {},
            # start is stamped at ADMISSION (_admit), not arrival: the
            # [start, end] window means "being served", so overlap
            # assertions aren't muddied by flow-lane queue wait.
            "start": None,
            "end": None,
        }
        self.request_log.append(entry)
        tracing.note_api_call(verb, kind)
        return entry

    async def _admit(self, entry: dict) -> None:
        """Flow-control admission + RTT sleep; in-flight counts requests
        being SERVED (a lane-queued request isn't in flight yet), and the
        entry's ``start`` is stamped here for the same reason.
        Balanced under cancellation: anything acquired here is undone
        before re-raising, so callers only pair ``_finish`` with a fully
        admitted request."""
        verb, kind = entry["verb"], entry["kind"]
        if self.flow is not None:
            await self.flow.acquire(verb, kind)
        entry["start"] = time.monotonic()
        self._in_flight += 1
        if self._in_flight > self.in_flight_peak:
            self.in_flight_peak = self._in_flight
        if self.latency > 0.0:
            delay = self.latency
            if self.latency_jitter:
                delay += self._rng.uniform(0.0, self.latency_jitter)
            try:
                await asyncio.sleep(delay)
            except BaseException:  # cancelled mid-RTT: undo the admission
                self._in_flight -= 1
                if self.flow is not None:
                    self.flow.release(verb, kind)
                raise
        if self.faults is not None:
            # Injection AFTER lane admission + RTT: the request paid the
            # round trip, then the server failed it — exactly where a real
            # 5xx/429/409 lands. Undo the admission before raising so the
            # caller's _finish pairing stays balanced (same contract as a
            # mid-RTT cancellation above).
            err = self.faults.error_for(verb, kind, entry.get("name"))
            if err is not None:
                entry["fault"] = err.reason
                entry["end"] = time.monotonic()
                self._in_flight -= 1
                if self.flow is not None:
                    self.flow.release(verb, kind)
                raise err

    def _finish(self, entry: dict) -> None:
        self._in_flight -= 1
        entry["end"] = time.monotonic()
        if self.flow is not None:
            self.flow.release(entry["verb"], entry["kind"])

    def write_count(self) -> int:
        """Mutating requests issued so far (no-op writes the server
        swallowed still count — the client paid the round-trip)."""
        return sum(self.requests[v] for v in self.WRITE_VERBS)

    def reset_counts(self) -> None:
        self.requests.clear()
        self.request_log.clear()

    # ---- admission plugin registration ---------------------------------------

    def add_mutator(self, kind_glob: str, fn: Mutator) -> None:
        self._mutators.append((kind_glob, fn))

    def add_validator(self, kind_glob: str, fn: Validator) -> None:
        self._validators.append((kind_glob, fn))

    # ---- internals -----------------------------------------------------------

    def _next_rv(self) -> str:
        self._rv += 1
        return str(self._rv)

    def _bucket(self, kind: str) -> dict[tuple[str | None, str], dict]:
        gvk = self.scheme.by_kind(kind)  # raises for unknown kinds
        return self._store[gvk.key]

    def _key(self, kind: str, obj_or_name, namespace: str | None) -> tuple[str | None, str]:
        gvk = self.scheme.by_kind(kind)
        if isinstance(obj_or_name, dict):
            name, namespace = name_of(obj_or_name), namespace_of(obj_or_name)
        else:
            name = obj_or_name
        return (namespace if gvk.namespaced else None, name)

    async def _run_admission(self, obj: dict, op: str, old: dict | None = None) -> None:
        info = {"operation": op, "old": deepcopy(old) if old else None}
        for glob, fn in self._mutators:
            if fnmatch.fnmatch(obj.get("kind", ""), glob):
                res = fn(obj, info)
                if asyncio.iscoroutine(res):
                    await res
        for glob, fn in self._validators:
            if fnmatch.fnmatch(obj.get("kind", ""), glob):
                res = fn(obj, info)
                if asyncio.iscoroutine(res):
                    await res

    def _notify(self, event: str, obj: dict) -> None:
        for w in self._watches:
            if w.kind == obj.get("kind") and w.wants(obj):
                w.queue.put_nowait((event, deepcopy(obj)))

    async def _cascade_delete(self, parent: dict) -> None:
        """Background GC: delete dependents whose ownerReference points here."""
        uid = get_meta(parent).get("uid")
        if not uid:
            return
        for bucket in list(self._store.values()):
            for key, obj in list(bucket.items()):
                refs = get_meta(obj).get("ownerReferences", [])
                if any(r.get("uid") == uid for r in refs):
                    await self._delete_obj(obj["kind"], key)

    # ---- KubeApi surface -----------------------------------------------------

    async def get(self, kind: str, name: str, namespace: str | None = None) -> dict:
        entry = self._log_request("get", kind, name, namespace)
        await self._admit(entry)

        try:
            bucket = self._bucket(kind)
            key = self._key(kind, name, namespace)
            obj = bucket.get(key)
            if obj is None:
                raise NotFound(f"{kind} {key[0]}/{key[1]} not found")
            return deepcopy(obj)
        finally:
            self._finish(entry)

    async def list(
        self,
        kind: str,
        namespace: str | None = None,
        label_selector: str | dict | None = None,
        field_selector: Callable[[dict], bool] | None = None,
        copy: bool = True,
    ) -> list[dict]:
        """List objects; the returned list holds defensive copies by
        default (``field_selector`` predicates always run against the live
        store dicts and must be pure — don't mutate or retain their
        argument).

        ``copy=False`` hands out the LIVE store dicts for read-only scans —
        a FakeKube-only escape hatch (HttpKube has no such parameter, so
        production controller code can't grow a dependency on it) used by
        the kubelet simulator and load test, whose per-event ownership
        scans dominated the control-plane bench's profile otherwise.
        Callers must not mutate the returned objects.
        """
        entry = self._log_request("list", kind, namespace=namespace)
        await self._admit(entry)

        try:
            items, _rv = self._list_locked(
                kind, namespace, label_selector, field_selector, copy)
            return items
        finally:
            self._finish(entry)

    def _list_locked(
        self, kind, namespace, label_selector, field_selector, copy,
    ) -> tuple[list[dict], str]:
        selector = (
            parse_label_selector(label_selector)
            if isinstance(label_selector, str)
            else label_selector
        )
        gvk_key = self.scheme.by_kind(kind).key
        source = self._bucket(kind).values()
        rv = str(self._rv)
        stale = False
        if (self.faults is not None and gvk_key in self._list_snapshots
                and self.faults.list_is_stale(kind)):
            # Stale snapshot: the previous LIST's view of the kind — an
            # old-resourceVersion read. Served from copies, never the
            # live store.
            source, rv = self._list_snapshots[gvk_key]
            stale = True
        out = []
        for obj in source:
            if namespace and namespace_of(obj) != namespace:
                continue
            if not matches_selector(get_meta(obj).get("labels"), selector):
                continue
            if field_selector and not field_selector(obj):
                continue
            out.append(deepcopy(obj) if copy else obj)
        out.sort(key=lambda o: (namespace_of(o) or "", name_of(o)))
        if not stale and self.faults is not None:
            # Remember this (fresh) view so a later injected stale LIST
            # has a genuinely older snapshot to serve. Only while a fault
            # plan is attached — the O(bucket) copy must not tax the
            # copy=False fast paths (kubelet sim, load test) otherwise.
            self._list_snapshots[gvk_key] = (
                [deepcopy(o) for o in self._bucket(kind).values()], rv)
        return out, rv

    async def list_with_rv(
        self,
        kind: str,
        namespace: str | None = None,
        label_selector: str | dict | None = None,
        field_selector: Callable[[dict], bool] | None = None,
    ) -> tuple[list[dict], str | None]:
        entry = self._log_request("list", kind, namespace=namespace)
        await self._admit(entry)
        try:
            return self._list_locked(
                kind, namespace, label_selector, field_selector, True)
        finally:
            self._finish(entry)

    async def create(self, kind: str, obj: dict, namespace: str | None = None) -> dict:
        entry = self._log_request(
            "create", kind, name_of(obj), namespace or namespace_of(obj))
        await self._admit(entry)

        try:
            return await self._create_locked(kind, obj, namespace)
        finally:
            self._finish(entry)

    async def _create_locked(
        self, kind: str, obj: dict, namespace: str | None = None
    ) -> dict:
        async with self._lock:
            obj = deepcopy(obj)
            obj.setdefault("kind", kind)
            obj.setdefault("apiVersion", self.scheme.by_kind(kind).api_version)
            meta = get_meta(obj)
            if namespace and self.scheme.by_kind(kind).namespaced:
                meta.setdefault("namespace", namespace)
            if not meta.get("name"):
                if meta.get("generateName"):
                    meta["name"] = meta["generateName"] + uuid.uuid4().hex[:6]
                else:
                    raise Invalid(f"{kind}: metadata.name required")
            bucket = self._bucket(kind)
            key = self._key(kind, obj, None)
            if key in bucket:
                raise AlreadyExists(f"{kind} {key} already exists")
            await self._run_admission(obj, "CREATE")
            meta["uid"] = str(uuid.uuid4())
            meta["resourceVersion"] = self._next_rv()
            meta["generation"] = 1
            meta.setdefault("creationTimestamp", _now())
            bucket[self._key(kind, obj, None)] = deepcopy(obj)
            self._notify("ADDED", obj)
            return deepcopy(obj)

    async def update(self, kind: str, obj: dict) -> dict:
        entry = self._log_request("update", kind, name_of(obj), namespace_of(obj))
        await self._admit(entry)

        try:
            return await self._update_locked(kind, obj)
        finally:
            self._finish(entry)

    async def _update_locked(self, kind: str, obj: dict) -> dict:
        async with self._lock:
            obj = deepcopy(obj)
            bucket = self._bucket(kind)
            key = self._key(kind, obj, None)
            current = bucket.get(key)
            if current is None:
                raise NotFound(f"{kind} {key} not found")
            meta, cur_meta = get_meta(obj), get_meta(current)
            if meta.get("resourceVersion") and meta["resourceVersion"] != cur_meta["resourceVersion"]:
                raise Conflict(
                    f"{kind} {key}: resourceVersion {meta['resourceVersion']} != "
                    f"{cur_meta['resourceVersion']}"
                )
            await self._run_admission(obj, "UPDATE", old=current)
            # status is a subresource: full updates never change it
            if "status" in current:
                obj["status"] = deepcopy(current["status"])
            else:
                obj.pop("status", None)
            meta["uid"] = cur_meta["uid"]
            meta["creationTimestamp"] = cur_meta["creationTimestamp"]
            meta["resourceVersion"] = cur_meta["resourceVersion"]
            meta["generation"] = cur_meta.get("generation", 1)
            if obj == current and not cur_meta.get("deletionTimestamp"):
                return deepcopy(current)  # no-op update: no rv bump, no event
            meta["resourceVersion"] = self._next_rv()
            spec_changed = obj.get("spec") != current.get("spec")
            meta["generation"] = cur_meta.get("generation", 1) + (1 if spec_changed else 0)
            # deleting objects: removing the last finalizer completes deletion
            if cur_meta.get("deletionTimestamp"):
                meta["deletionTimestamp"] = cur_meta["deletionTimestamp"]
                if not meta.get("finalizers"):
                    del bucket[key]
                    self._notify("DELETED", obj)
                    await self._cascade_delete(obj)
                    return deepcopy(obj)
            bucket[key] = deepcopy(obj)
            self._notify("MODIFIED", obj)
            return deepcopy(obj)

    async def update_status(self, kind: str, obj: dict) -> dict:
        entry = self._log_request(
            "update_status", kind, name_of(obj), namespace_of(obj))
        await self._admit(entry)

        try:
            return await self._update_status_locked(kind, obj)
        finally:
            self._finish(entry)

    async def _update_status_locked(self, kind: str, obj: dict) -> dict:
        async with self._lock:
            bucket = self._bucket(kind)
            key = self._key(kind, obj, None)
            current = bucket.get(key)
            if current is None:
                raise NotFound(f"{kind} {key} not found")
            new = deepcopy(current)
            if "status" in obj:
                new["status"] = deepcopy(obj["status"])
            if new == current:  # no-op writes don't bump rv (real-apiserver semantics)
                return deepcopy(current)
            get_meta(new)["resourceVersion"] = self._next_rv()
            bucket[key] = deepcopy(new)
            self._notify("MODIFIED", new)
            return deepcopy(new)

    async def patch(
        self,
        kind: str,
        name: str,
        patch: dict,
        namespace: str | None = None,
        subresource: str | None = None,
    ) -> dict:
        """Strategic-ish merge patch: dicts merge recursively, None deletes,
        lists replace (the k8s merge-patch rule)."""
        entry = self._log_request("patch", kind, name, namespace)
        await self._admit(entry)

        try:
            return await self._patch_locked(kind, name, patch, namespace,
                                            subresource)
        finally:
            self._finish(entry)

    async def _patch_locked(
        self,
        kind: str,
        name: str,
        patch: dict,
        namespace: str | None = None,
        subresource: str | None = None,
    ) -> dict:
        async with self._lock:
            bucket = self._bucket(kind)
            key = self._key(kind, name, namespace)
            current = bucket.get(key)
            if current is None:
                raise NotFound(f"{kind} {key} not found")
            new = deepcopy(current)

            def merge(dst: dict, src: dict) -> None:
                for k, v in src.items():
                    if v is None:
                        dst.pop(k, None)
                    elif isinstance(v, dict) and isinstance(dst.get(k), dict):
                        merge(dst[k], v)
                    else:
                        dst[k] = copy.deepcopy(v)

            if subresource == "status":
                merge(new.setdefault("status", {}), patch.get("status", patch))
            else:
                merge(new, patch)
                await self._run_admission(new, "UPDATE", old=current)
                if "status" in current:
                    new["status"] = deepcopy(current["status"])
            if new == current:  # no-op patch: no rv bump, no event (apiserver semantics)
                return deepcopy(current)
            meta = get_meta(new)
            meta["resourceVersion"] = self._next_rv()
            if new.get("spec") != current.get("spec"):
                meta["generation"] = get_meta(current).get("generation", 1) + 1
            # Removing the last finalizer from a deleting object completes the
            # delete (same two-phase semantics as update()).
            if get_meta(current).get("deletionTimestamp") and not meta.get("finalizers"):
                del bucket[key]
                self._notify("DELETED", new)
                await self._cascade_delete(new)
                return deepcopy(new)
            bucket[key] = deepcopy(new)
            self._notify("MODIFIED", new)
            return deepcopy(new)

    async def delete(self, kind: str, name: str, namespace: str | None = None) -> None:
        entry = self._log_request("delete", kind, name, namespace)
        await self._admit(entry)

        try:
            async with self._lock:
                key = self._key(kind, name, namespace)
                await self._delete_obj(kind, key)
        finally:
            self._finish(entry)

    async def _delete_obj(self, kind: str, key: tuple[str | None, str]) -> None:
        bucket = self._bucket(kind)
        obj = bucket.get(key)
        if obj is None:
            raise NotFound(f"{kind} {key} not found")
        meta = get_meta(obj)
        if meta.get("finalizers"):
            if not meta.get("deletionTimestamp"):
                meta["deletionTimestamp"] = _now()
                meta["resourceVersion"] = self._next_rv()
                self._notify("MODIFIED", obj)
            return
        del bucket[key]
        self._notify("DELETED", obj)
        await self._cascade_delete(obj)

    def watch(
        self,
        kind: str,
        namespace: str | None = None,
        label_selector: str | dict | None = None,
        *,
        field_selector: Callable[[dict], bool] | None = None,
        send_initial: bool = True,
        resource_version: str | None = None,
    ) -> AsyncIterator[tuple[str, dict]]:
        """Watch registration is EAGER (at call time, not first iteration) so a
        synchronous list→watch sequence observes every event — the in-memory
        equivalent of resourceVersion continuity (``resource_version`` is
        accepted and ignored). ``field_selector`` mirrors list(): a pure
        predicate over the live store dict, re-evaluated per event."""
        selector = (
            parse_label_selector(label_selector)
            if isinstance(label_selector, str)
            else label_selector
        )
        w = _Watch(kind, namespace, selector, field_selector)
        if send_initial:
            for obj in self._bucket(kind).values():
                if w.wants(obj):
                    w.queue.put_nowait(("ADDED", deepcopy(obj)))
        self._watches.append(w)
        return self._drain_watch(w)

    async def _drain_watch(self, w: _Watch) -> AsyncIterator[tuple[str, dict]]:
        try:
            while True:
                item = await w.queue.get()
                if item is None:
                    return
                yield item
                if self.faults is not None and \
                        self.faults.watch_should_reset(w.kind):
                    # Mid-stream reset: the server closed the stream after
                    # this event (network blip, apiserver restart, 410
                    # Gone). The client sees a cleanly-ended watch and must
                    # relist to regain resourceVersion continuity.
                    return
        finally:
            if w in self._watches:
                self._watches.remove(w)

    def close_watches(self) -> None:
        for w in self._watches:
            w.queue.put_nowait(None)

    # ---- pod logs (kubelet surface) ------------------------------------------

    def set_pod_logs(self, namespace: str, name: str, text: str) -> None:
        self._pod_logs[(namespace, name)] = text

    async def pod_logs(
        self, name: str, namespace: str, container: str | None = None,
        tail_lines: int | None = None,
    ) -> str:
        """Kubelet log read. Tests seed with set_pod_logs; unseeded running
        pods synthesize a plausible startup log."""
        if (namespace, name) not in self._pod_logs:
            pod = await self.get("Pod", name, namespace)  # NotFound propagates
            phase = deep_get(pod, "status", "phase", default="Pending")
            self._pod_logs[(namespace, name)] = (
                f"[s6-init] making user provided files available\n"
                f"[{name}] phase={phase}\n"
            )
        text = self._pod_logs[(namespace, name)]
        if tail_lines is not None:
            if tail_lines <= 0:
                return ""  # kubelet semantics: tailLines=0 → nothing
            lines = text.splitlines()[-tail_lines:]
            text = "\n".join(lines) + ("\n" if lines else "")
        return text

    # ---- test conveniences ---------------------------------------------------

    async def get_or_none(self, kind: str, name: str, namespace: str | None = None) -> dict | None:
        try:
            return await self.get(kind, name, namespace)
        except NotFound:
            return None

    def dump(self) -> dict[str, list[str]]:
        return {
            key: [f"{ns or '-'}/{n}" for (ns, n) in sorted(bucket, key=lambda t: (t[0] or "", t[1]))]
            for key, bucket in self._store.items()
            if bucket
        }
