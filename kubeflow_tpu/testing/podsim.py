"""Kubelet/controller-manager simulator for the fake apiserver.

envtest famously has no kubelet — pods never materialise, so the reference's
tests assert only on generated objects. For e2e-style flows (and the bench's
cold-start measurement) we go one step further: this simulator watches
StatefulSets and Deployments and plays the role of the statefulset/deployment
controllers + kubelet — creating pods through the admission chain (so
PodDefault injection really runs), marking them Running/Ready after a
configurable latency, and mirroring readiness into workload status.
"""

from __future__ import annotations

import asyncio
import time

from kubeflow_tpu.runtime.aiotasks import reap
from kubeflow_tpu.runtime.errors import AlreadyExists, ApiError, NotFound
from kubeflow_tpu.runtime.objects import (
    deep_get,
    deepcopy,
    get_meta,
    name_of,
    namespace_of,
    set_controller_owner,
)
from kubeflow_tpu.testing.fakekube import FakeKube


def _fake_pod_ip(name: str) -> str:
    """Deterministic cluster-range IP per pod name (kubelet assigns these;
    controllers that probe pods directly need one to exist)."""
    h = sum(name.encode()) % 254 + 1
    return f"10.244.0.{h}"


class PodSimulator:
    def __init__(
        self,
        kube: FakeKube,
        *,
        start_latency: float = 0.0,
        image_pull_latency: float = 0.0,
        runtime_start_latency: float = 0.0,
        failure_injector=None,
    ):
        """``failure_injector(pod) -> None | "fail" | "crash" | "crash:<ctr>"
        | "disrupt" | "disrupt:<reason>"`` — fault injection the reference
        never had (SURVEY.md §5 "No fault injection framework"): "fail"
        leaves the pod phase=Failed (scheduling/image errors); "crash"
        marks one in-place restart of every container (the signal the
        slice-atomic restart logic keys on); "crash:<name>" restarts only
        the named container (e.g. a sidecar), leaving the rest healthy;
        "disrupt" brings the pod up healthy but stamped with a
        DisruptionTarget=True condition (default reason
        PreemptionByScheduler) — a spot preemption / node drain in
        flight, containers still running.

        Cold-start latency model (ISSUE 14): real pod starts are
        dominated by two costs a reconcile-speed sim hides —
        ``image_pull_latency`` (paid ONCE per (node, image): kubelet's
        image cache makes later pulls free, which is exactly what warm
        pools and image streaming exploit) and ``runtime_start_latency``
        (paid by EVERY fresh pod: interpreter + imports + device-client
        attach). A warm-pool CLAIM creates no pod, so it pays neither —
        the asymmetry ``bench.py coldstart`` measures."""
        self.kube = kube
        self.start_latency = start_latency
        self.image_pull_latency = image_pull_latency
        self.runtime_start_latency = runtime_start_latency
        self._pulled_images: set[tuple] = set()
        self.failure_injector = failure_injector
        self._tasks: list[asyncio.Task] = []
        # Strong refs: asyncio holds tasks weakly; un-referenced _run_pod
        # tasks can be GC'd mid-flight (pods stuck Pending, flaky tests).
        self._pod_tasks: set[asyncio.Task] = set()
        # (namespace, pod name) with a live _run_pod task — the stuck-pod
        # backstop in _reconcile_workload must not double-drive a pod
        # whose first run is still in flight (a one-shot failure injector
        # consulted twice would lose its verdict).
        self._pods_in_flight: set[tuple] = set()
        # (namespace, owner uid) → pod names: the simulator's own owner
        # index, updated synchronously on its own creates/deletes and from
        # the pod watch for external actors. Replaces the per-event
        # namespace-wide pod scans that made the kubelet sim O(pods-in-ns)
        # per event — O(N²) across the load test's shared namespace.
        self._owner_pods: dict[tuple, set[str]] = {}
        # Short-TTL Node cache for scheduler-like node assignment: pods
        # with a nodeSelector get spec.nodeName stamped from a matching
        # Node (round-robin by ordinal), so node-level signals — spot
        # revocation taints, maintenance — map to real pods in the sim.
        # Clusters with no Node objects behave exactly as before.
        self._nodes_cache: tuple[float, list] = (-1.0, [])
        self._running = False

    async def start(self) -> None:
        self._running = True
        self._tasks = [
            asyncio.create_task(self._watch_workloads("StatefulSet")),
            asyncio.create_task(self._watch_workloads("Deployment")),
            asyncio.create_task(self._watch_pods()),
        ]

    async def stop(self) -> None:
        self._running = False
        for t in [*self._tasks, *self._pod_tasks]:
            t.cancel()
        await reap(*self._tasks, *self._pod_tasks)
        self._pod_tasks.clear()

    async def _watch_workloads(self, kind: str) -> None:
        # Re-establish on every close (injected watch reset, apiserver
        # restart): a kubelet whose watch dies does not stop being the
        # kubelet. send_initial on re-watch doubles as the resync — any
        # workload whose events were lost in the gap reconciles again.
        while self._running:
            async for _event, obj in self.kube.watch(kind):
                if not self._running:
                    return
                try:
                    await self._reconcile_workload(kind, obj)
                except ApiError:
                    pass
            await asyncio.sleep(0.02)

    def _index_pod(self, event: str, pod: dict) -> dict | None:
        """Fold one pod event into the owner index; returns the pod's
        controller ownerReference (None for unowned pods)."""
        owner = next(
            (r for r in get_meta(pod).get("ownerReferences", [])
             if r.get("controller")),
            None,
        )
        if not owner or not owner.get("uid"):
            return owner
        key = (namespace_of(pod), owner["uid"])
        if event == "DELETED":
            names = self._owner_pods.get(key)
            if names is not None:
                names.discard(name_of(pod))
                if not names:
                    del self._owner_pods[key]
        else:
            self._owner_pods.setdefault(key, set()).add(name_of(pod))
        return owner

    async def _watch_pods(self) -> None:
        """The real STS/Deployment controllers watch pods: an out-of-band pod
        delete must trigger recreation from the owning workload. The same
        stream keeps the owner index current for pods other actors
        create/delete behind the simulator's back."""
        while self._running:
            async for event, pod in self.kube.watch("Pod"):
                if not self._running:
                    return
                owner = self._index_pod(event, pod)
                if event != "DELETED":
                    continue
                if not owner or owner.get("kind") not in ("StatefulSet", "Deployment"):
                    continue
                try:
                    wl = await self.kube.get_or_none(
                        owner["kind"], owner["name"], namespace_of(pod)
                    )
                    if wl is not None:
                        await self._reconcile_workload(owner["kind"], wl)
                except ApiError:
                    pass
            await asyncio.sleep(0.02)

    async def _reconcile_workload(self, kind: str, obj: dict) -> None:
        ns, name = namespace_of(obj), name_of(obj)
        # Re-fetch: the event may be stale (workload deleted since it was
        # queued) — acting on it would resurrect pods for a dead workload.
        obj = await self.kube.get_or_none(kind, name, ns)
        if obj is None or get_meta(obj).get("deletionTimestamp"):
            return
        replicas = deep_get(obj, "spec", "replicas", default=1)
        template = deep_get(obj, "spec", "template", default={})
        nodes = (await self._list_nodes()
                 if deep_get(template, "spec", "nodeSelector") else [])
        want: dict[str, dict] = {}
        for i in range(replicas):
            pod_name = f"{name}-{i}" if kind == "StatefulSet" else f"{name}-rs-{i}"
            want[pod_name] = self._pod_from_template(
                pod_name, ns, template, obj, ordinal=i, nodes=nodes)

        # Owner index, not a namespace scan; the simulator's own writes
        # update it synchronously below, so it cannot lag its own actions
        # (external deletes land via the pod watch; a double create hits
        # AlreadyExists and a double delete hits NotFound — both benign).
        owner_key = (ns, get_meta(obj).get("uid"))
        existing = set(self._owner_pods.get(owner_key, ()))
        for pod_name, pod in want.items():
            if pod_name not in existing:
                try:
                    created = await self.kube.create("Pod", pod)
                except AlreadyExists:
                    continue
                self._owner_pods.setdefault(owner_key, set()).add(pod_name)
                self._spawn_pod_task(created)
            else:
                # Stuck-pod backstop: a pod whose _run_pod task died under
                # an injected fault storm (status patch never landed — no
                # phase) gets re-driven on the next workload reconcile,
                # exactly as a real kubelet re-syncs pods it owns. Guarded
                # by _pods_in_flight so an in-flight first run — and its
                # one-shot failure-injector verdict — is never doubled.
                if (ns, pod_name) in self._pods_in_flight:
                    continue
                live = await self.kube.get_or_none("Pod", pod_name, ns)
                if live is not None and not deep_get(live, "status", "phase"):
                    self._spawn_pod_task(live)
        for pod_name in existing:
            if pod_name not in want:
                try:
                    await self.kube.delete("Pod", pod_name, ns)
                except NotFound:
                    pass
                names = self._owner_pods.get(owner_key)
                if names is not None:
                    names.discard(pod_name)
        await self._mirror_status(kind, obj, len(want))

    async def _list_nodes(self) -> list:
        """Node objects for pod placement, cached briefly — one LIST per
        cache window instead of one per workload reconcile."""
        stamp, nodes = self._nodes_cache
        now = time.monotonic()
        if now - stamp < 0.5 and stamp >= 0:
            return nodes
        try:
            nodes = await self.kube.list("Node", copy=False)
        except ApiError:
            nodes = []
        self._nodes_cache = (now, nodes)
        return nodes

    def _pod_from_template(self, pod_name: str, ns: str, template: dict,
                           owner: dict, *, ordinal: int = 0,
                           nodes: list | None = None) -> dict:
        labels = dict(deep_get(template, "metadata", "labels", default={}))
        if owner.get("kind") == "StatefulSet":
            # The real STS controller stamps the stable pod identity label
            # (and, ≥1.28, the ordinal index) — controllers select on these.
            labels["statefulset.kubernetes.io/pod-name"] = pod_name
            labels["apps.kubernetes.io/pod-index"] = pod_name.rsplit("-", 1)[-1]
        pod = {
            "apiVersion": "v1",
            "kind": "Pod",
            "metadata": {
                "name": pod_name,
                "namespace": ns,
                "labels": labels,
                "annotations": dict(deep_get(template, "metadata", "annotations", default={})),
            },
            "spec": deepcopy(template.get("spec", {})),
        }
        selector = pod["spec"].get("nodeSelector") or {}
        if nodes and selector and not pod["spec"].get("nodeName"):
            # Scheduler stand-in: bind to a matching node, round-robin by
            # ordinal, so node taints/deletions reach the right pods.
            matching = [
                name_of(n) for n in nodes
                if all((deep_get(n, "metadata", "labels",
                                 default={}) or {}).get(k) == v
                       for k, v in selector.items())
            ]
            if matching:
                matching.sort()
                pod["spec"]["nodeName"] = matching[ordinal % len(matching)]
        set_controller_owner(pod, owner)
        return pod

    def _spawn_pod_task(self, pod: dict) -> None:
        key = (namespace_of(pod), name_of(pod))
        self._pods_in_flight.add(key)
        task = asyncio.create_task(self._run_pod(pod))
        self._pod_tasks.add(task)

        def _done(t, key=key):
            self._pod_tasks.discard(t)
            self._pods_in_flight.discard(key)

        task.add_done_callback(_done)

    async def _patch_status_retrying(self, kind: str, name: str, ns: str,
                                     status: dict) -> None:
        """Kubelet-style bounded retry: a transient apiserver error (5xx/
        429/409) must not leave a pod Pending forever — the real kubelet
        retries status syncs until they land. NotFound ends the retry (the
        object is gone); persistent failure gives up after ~2s and leaves
        the stuck-pod backstop to re-drive it."""
        delay = 0.02
        for attempt in range(8):
            try:
                await self.kube.patch(kind, name, {"status": status}, ns,
                                      subresource="status")
                return
            except NotFound:
                return
            except ApiError:
                if attempt == 7:
                    return
                await asyncio.sleep(delay)
                delay = min(delay * 2, 0.5)

    async def _run_pod(self, pod: dict) -> None:
        delay = self.start_latency
        if self.image_pull_latency or self.runtime_start_latency:
            image = (deep_get(pod, "spec", "containers", default=[{}])
                     or [{}])[0].get("image", "")
            node = deep_get(pod, "spec", "nodeName") or ""
            if self.image_pull_latency:
                if (node, image) not in self._pulled_images:
                    self._pulled_images.add((node, image))
                    delay += self.image_pull_latency
            delay += self.runtime_start_latency
        if delay:
            await asyncio.sleep(delay)
        ns, name = namespace_of(pod), name_of(pod)
        fault = self.failure_injector(pod) if self.failure_injector else None
        if fault == "fail":
            await self._patch_status_retrying(
                "Pod", name, ns,
                {"phase": "Failed", "reason": "Injected", "conditions": []})
            return
        if fault == "crash" or (isinstance(fault, str) and fault.startswith("crash:")):
            only = fault.split(":", 1)[1] if ":" in fault else None

            def ctr_status(c):
                cname = c.get("name", "main")
                crashed = only is None or cname == only
                st = {
                    "name": cname,
                    "ready": not crashed,
                    "restartCount": 1 if crashed else 0,
                    "state": {"running": {"startedAt": "now"}},
                }
                if crashed:
                    st["lastState"] = {
                        "terminated": {"exitCode": 137, "reason": "OOMKilled"}
                    }
                return st

            # A single crashed sidecar leaves the pod Running and (after
            # kubelet restarts it in place) Ready; a whole-pod crash flips
            # the Ready condition.
            pod_ready = "True" if only is not None else "False"
            await self._patch_status_retrying(
                "Pod", name, ns,
                {
                    "phase": "Running",
                    "conditions": [{"type": "Ready", "status": pod_ready}],
                    "containerStatuses": [
                        ctr_status(c)
                        for c in deep_get(pod, "spec", "containers", default=[])
                    ],
                })
            return
        disrupt_reason = None
        if fault == "disrupt" or (
            isinstance(fault, str) and fault.startswith("disrupt:")
        ):
            disrupt_reason = (
                fault.split(":", 1)[1] if ":" in fault
                else "PreemptionByScheduler"
            )
        conditions = [{"type": "Ready", "status": "True"}]
        if disrupt_reason:
            conditions.append({
                "type": "DisruptionTarget",
                "status": "True",
                "reason": disrupt_reason,
                "message": "injected disruption",
            })
        await self._patch_status_retrying(
            "Pod", name, ns,
            {
                "phase": "Running",
                "podIP": _fake_pod_ip(name),
                "conditions": conditions,
                "containerStatuses": [
                    {
                        "name": c.get("name", "main"),
                        "ready": True,
                        "restartCount": 0,
                        "state": {"running": {"startedAt": "now"}},
                    }
                    for c in deep_get(pod, "spec", "containers", default=[])
                ],
            })
        # The pod's controller ref names its workload directly — no scan.
        owner = next(
            (r for r in get_meta(pod).get("ownerReferences", [])
             if r.get("controller")),
            None,
        )
        if owner and owner.get("kind") in ("StatefulSet", "Deployment"):
            try:
                wl = await self.kube.get_or_none(owner["kind"], owner["name"], ns)
            except ApiError:
                return
            if wl is not None and get_meta(wl).get("uid") == owner.get("uid"):
                await self._mirror_status(
                    owner["kind"], wl,
                    deep_get(wl, "spec", "replicas", default=1))

    async def _mirror_status(self, kind: str, obj: dict, replicas: int) -> None:
        ns = namespace_of(obj)
        ready = 0
        # Names from the owner index, phases from fresh GETs (a pod whose
        # status another actor rewrote must count correctly) — O(replicas)
        # instead of a namespace-wide scan.
        for pod_name in list(
            self._owner_pods.get((ns, get_meta(obj).get("uid")), ())
        ):
            try:
                p = await self.kube.get_or_none("Pod", pod_name, ns)
            except ApiError:
                continue
            if p is not None and deep_get(p, "status", "phase") == "Running":
                ready += 1
        status = {"replicas": replicas, "readyReplicas": ready}
        if kind == "Deployment":
            status["availableReplicas"] = ready
        current = {
            k: deep_get(obj, "status", k) for k in status
        }
        if current == status:
            return  # avoid self-amplifying MODIFIED loops on our own watch
        await self._patch_status_retrying(kind, name_of(obj), ns, status)
