"""Notebook load test.

Reference: ``notebook-controller/loadtest/start_notebooks.py`` — spawn N
Notebook CRs from a template, wait, tear down. Ours runs against any
KubeApi (FakeKube for control-plane-only measurement, HttpKube for a real
cluster) and reports spawn latency percentiles — the number the reference
harness never recorded (SURVEY.md §6).
"""

from __future__ import annotations

import asyncio
import math
import time
from dataclasses import dataclass, field

from kubeflow_tpu.api import notebook as nbapi
from kubeflow_tpu.runtime.errors import ApiError
from kubeflow_tpu.runtime.objects import deep_get


@dataclass
class LoadTestReport:
    notebooks: int
    ready: int
    wall_seconds: float
    p50_ready_seconds: float | None
    p95_ready_seconds: float | None
    failures: list[str] = field(default_factory=list)

    def to_dict(self) -> dict:
        return self.__dict__.copy()


async def run_load_test(
    kube,
    *,
    count: int = 50,
    namespace: str = "loadtest",
    namespaces: list[str] | None = None,
    accelerator: str | None = None,
    topology: str | None = None,
    timeout: float = 120.0,
    cleanup: bool = True,
    poll_interval: float = 0.05,
) -> LoadTestReport:
    """``namespaces`` spreads the CRs round-robin across several
    namespaces — required to load every shard of a namespace-hash
    sharded control plane (a single namespace hashes to ONE shard and
    would benchmark one replica no matter how many are running)."""
    nss = list(namespaces) if namespaces else [namespace]
    t0 = time.perf_counter()
    keyed = [(nss[i % len(nss)], f"load-{i}") for i in range(count)]
    for ns, name in keyed:
        await kube.create(
            "Notebook",
            nbapi.new(name, ns, accelerator=accelerator, topology=topology),
        )

    from kubeflow_tpu.testing.fakekube import FakeKube

    # Read-only poll: the copy-free fast path exists only on FakeKube
    # (HttpKube keeps the standard signature).
    list_kwargs = {"copy": False} if isinstance(kube, FakeKube) else {}

    ready_at: dict[tuple, float] = {}
    failed: dict[tuple, str] = {}
    wanted = set(keyed)
    deadline = t0 + timeout
    while len(ready_at) + len(failed) < count and time.perf_counter() < deadline:
        # One list per namespace per poll pass (NOT a GET per notebook:
        # against a real apiserver the serialized round-trips would skew
        # the very spawn latencies being measured).
        listed: dict[tuple, dict] = {}
        for ns in nss:
            for nb in await kube.list("Notebook", ns, **list_kwargs):
                key = (ns, nb["metadata"]["name"])
                if key in wanted:
                    listed[key] = nb
        for key in keyed:
            if key in ready_at or key in failed:
                continue
            nb = listed.get(key)
            if nb is None:
                failed[key] = f"{key[0]}/{key[1]}: disappeared"
                continue
            want = deep_get(nb, "status", "tpu", "hosts", default=1) or 1
            if (deep_get(nb, "status", "readyReplicas", default=0) or 0) >= want:
                ready_at[key] = time.perf_counter() - t0
        await asyncio.sleep(poll_interval)

    wall = time.perf_counter() - t0
    for key in keyed:  # pending-at-deadline notebooks are failures too
        if key not in ready_at and key not in failed:
            failed[key] = f"{key[0]}/{key[1]}: not ready within {timeout}s"
    failures = list(failed.values())
    latencies = sorted(ready_at.values())

    def pct(p: float) -> float | None:
        """Nearest-rank percentile: ceil(p*n)-th smallest."""
        if not latencies:
            return None
        rank = max(1, math.ceil(p * len(latencies)))
        return latencies[rank - 1]

    if cleanup:
        for ns, name in keyed:
            try:
                await kube.delete("Notebook", name, ns)
            except ApiError:  # NotFound included — it subclasses ApiError
                pass  # cleanup is best-effort; the report already exists

    return LoadTestReport(
        notebooks=count,
        ready=len(ready_at),
        wall_seconds=round(wall, 3),
        p50_ready_seconds=round(pct(0.50), 4) if latencies else None,
        p95_ready_seconds=round(pct(0.95), 4) if latencies else None,
        failures=failures,
    )
