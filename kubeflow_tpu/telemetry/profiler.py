"""Low-overhead per-step training recorder.

Always-on (``KFTPU_TELEMETRY``, default on; ``bench.py
telemetry_overhead`` holds the paired A/B cost under 5%): the hot path
per step is two ``perf_counter`` reads and a deque append. Rolling
windows are summarized — p50 step time, achieved MFU against a declared
peak, compile-vs-run split, collective-overlap attribution (fed from the
paired serialize-mode measurement, :mod:`sections`), HBM high-water —
never raw per-step streams.

Honest timing under async dispatch: the first observed step is recorded
separately as the compile-inclusive step, and every ``sync_every``-th
step blocks on the step's output value so queued device work drains into
a measured step instead of accumulating invisibly. On window summaries
the p50 is robust to that boundary spike.
"""

from __future__ import annotations

import os
import time
from collections import deque

from kubeflow_tpu import telemetry as _pkg

TELEMETRY_WINDOW_ENV = "KFTPU_TELEMETRY_WINDOW"
DEFAULT_WINDOW = 32


def window_steps(environ=os.environ) -> int:
    raw = environ.get(TELEMETRY_WINDOW_ENV)
    try:
        value = int(raw) if raw is not None else DEFAULT_WINDOW
    except ValueError:
        return DEFAULT_WINDOW
    return max(2, value)


def overlap_fraction(overlapped_sec: float, serialized_sec: float) -> float:
    """Fraction of the serialized step hidden by comm/compute overlap:
    ``clamp((t_serialized - t_overlapped) / t_serialized, 0, 1)``."""
    if serialized_sec <= 0.0:
        return 0.0
    return max(0.0, min(1.0, (serialized_sec - overlapped_sec) / serialized_sec))


def _p50(values) -> float:
    ordered = sorted(values)
    n = len(ordered)
    mid = n // 2
    if n % 2:
        return ordered[mid]
    return 0.5 * (ordered[mid - 1] + ordered[mid])


def hbm_high_water_bytes(device=None) -> int | None:
    """Peak device-memory bytes, when the backend exposes memory_stats
    (TPU/GPU do; CPU returns None)."""
    try:
        if device is None:
            import jax

            device = jax.devices()[0]
        stats = device.memory_stats()
    except Exception:
        # Capability probe — backends without memory_stats (CPU) report
        # None rather than fail the step.
        return None
    if not stats:
        return None
    peak = stats.get("peak_bytes_in_use", stats.get("bytes_in_use"))
    return int(peak) if peak is not None else None


class StepProfiler:
    """Per-step recorder for one training run of one model family.

    ``flops_per_step`` and ``peak_flops`` are in FLOPs and FLOP/s; when
    both are known the summary carries achieved MFU with ``mfu_basis``
    naming what the peak was measured against (``"accelerator"`` on real
    chips, ``"host_matmul_probe"`` on the CPU dryrun mesh — the bench
    marks its basis explicitly rather than publishing a vacuous 0).
    """

    def __init__(
        self,
        family: str,
        *,
        flops_per_step: float = 0.0,
        tokens_per_step: int = 0,
        peak_flops: float = 0.0,
        mfu_basis: str = "accelerator",
        window: int | None = None,
        sync_every: int | None = None,
        clock=time.perf_counter,
        environ=os.environ,
    ):
        self.family = family
        self.flops_per_step = float(flops_per_step)
        self.tokens_per_step = int(tokens_per_step)
        self.peak_flops = float(peak_flops)
        self.mfu_basis = mfu_basis
        self.window = window if window is not None else window_steps(environ)
        self.sync_every = sync_every if sync_every is not None else self.window
        self._clock = clock
        self._environ = environ
        self._recent: deque[float] = deque(maxlen=self.window)
        self.steps = 0                  # measured steps (post-compile)
        self.last_step = 0              # caller's global step counter
        self.first_step_sec: float | None = None   # compile-inclusive
        self.run_sec_total = 0.0
        self.overlap: float | None = None
        self.serialized_step_sec: float | None = None
        self.hbm_bytes: int | None = None
        self._t0: float | None = None

    # ------------------------------------------------------------ hot path

    def enabled(self) -> bool:
        return _pkg.is_enabled(self._environ)

    def start(self) -> None:
        """Mark step start (pairs with :meth:`stop`)."""
        if self.enabled():
            self._t0 = self._clock()

    def stop(self, step: int | None = None, sync_value=None) -> None:
        if self._t0 is None:
            return
        t0, self._t0 = self._t0, None
        seconds = self._clock() - t0
        self.observe(step if step is not None else self.last_step + 1,
                     seconds, sync_value=sync_value)

    def observe(self, step: int, seconds: float, sync_value=None) -> None:
        """Record one step's wall time. ``sync_value`` (typically the
        loss) is blocked on at the first step and every ``sync_every``-th
        step so queued async work drains into a measured step."""
        if not self.enabled():
            return
        boundary = self.steps == 0 or (self.steps % self.sync_every == 0)
        if sync_value is not None and boundary:
            t_sync = self._clock()
            import jax

            jax.block_until_ready(sync_value)
            seconds += self._clock() - t_sync
        self.last_step = int(step)
        if self.first_step_sec is None:
            # First step pays tracing + compile; keep it out of the
            # rolling window so MFU reflects steady state.
            self.first_step_sec = seconds
            return
        self.steps += 1
        self.run_sec_total += seconds
        self._recent.append(seconds)

    # ------------------------------------------------------------ annotate

    def note_overlap(self, fraction: float,
                     serialized_step_sec: float | None = None) -> None:
        self.overlap = max(0.0, min(1.0, float(fraction)))
        if serialized_step_sec is not None:
            self.serialized_step_sec = float(serialized_step_sec)

    def note_hbm(self, device=None) -> None:
        peak = hbm_high_water_bytes(device)
        if peak is not None:
            self.hbm_bytes = max(self.hbm_bytes or 0, peak)

    # ------------------------------------------------------------ summary

    def step_p50_sec(self) -> float | None:
        if not self._recent:
            return None
        return _p50(self._recent)

    def mfu(self) -> float | None:
        p50 = self.step_p50_sec()
        if p50 is None or p50 <= 0 or not self.flops_per_step \
                or not self.peak_flops:
            return None
        return (self.flops_per_step / p50) / self.peak_flops

    def compile_sec(self) -> float | None:
        """Compile share of the first step: first-step wall minus the
        steady-state p50 (clamped — a cache hit can make them equal)."""
        if self.first_step_sec is None:
            return None
        p50 = self.step_p50_sec() or 0.0
        return max(0.0, self.first_step_sec - p50)

    def summary(self) -> dict:
        p50 = self.step_p50_sec()
        mean = (sum(self._recent) / len(self._recent)) if self._recent \
            else None
        achieved = (self.flops_per_step / p50) if p50 and self.flops_per_step \
            else None
        tokens_per_sec = (self.tokens_per_step / p50) \
            if p50 and self.tokens_per_step else None
        return {
            "family": self.family,
            "step": self.last_step,
            "steps_measured": self.steps,
            "window": self.window,
            "step_p50_sec": p50,
            "step_mean_sec": mean,
            "achieved_tflops": achieved / 1e12 if achieved else None,
            "mfu": self.mfu(),
            "mfu_basis": self.mfu_basis if self.mfu() is not None else None,
            "tokens_per_sec": tokens_per_sec,
            "first_step_sec": self.first_step_sec,
            "compile_sec": self.compile_sec(),
            "overlap_fraction": self.overlap,
            "serialized_step_sec": self.serialized_step_sec,
            "hbm_high_water_bytes": self.hbm_bytes,
        }
