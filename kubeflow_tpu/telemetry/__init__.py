"""Step-level training telemetry: profiler -> annotation -> scheduler.

The control plane became observable in PRs 3/13 (flight recorder, SLO
engine); this package makes the *workload* observable. A low-overhead
per-step recorder (:mod:`profiler`) runs inside the training loop,
summarizes rolling windows (never raw streams) into achieved MFU,
compile-vs-run split, collective-overlap attribution (:mod:`sections`)
and HBM high-water; a single writer (:mod:`publisher`) exports the
summary as a compact capped annotation plus Prometheus series; and the
fleet scheduler folds the numbers into a per-family x shape efficiency
ledger (:mod:`ledger`) so placement finally sees how well a gang uses
its chips.

Master switch is ``KFTPU_TELEMETRY`` (default on — the recorder is
cheap enough to leave always-on; ``bench.py telemetry_overhead`` gates
the paired A/B cost < 5%). ``set_enabled`` is the in-process override
the overhead bench flips between trials, mirroring
``runtime/timeline.py``.
"""

from __future__ import annotations

import os

TELEMETRY_ENABLED_ENV = "KFTPU_TELEMETRY"

_DISABLED_VALUES = ("off", "false", "0", "no", "disabled")

# In-process override for paired A/B benches (timeline/slo idiom):
# None -> follow the env var; True/False -> forced.
_enabled_override: bool | None = None


def telemetry_enabled(environ=os.environ) -> bool:
    """Default-on parse of the master switch (timeline semantics)."""
    raw = environ.get(TELEMETRY_ENABLED_ENV)
    if raw is None:
        return True
    return raw.strip().lower() not in _DISABLED_VALUES


def set_enabled(on: bool | None) -> None:
    """Force telemetry on/off in-process (``None`` restores the env)."""
    global _enabled_override
    _enabled_override = on


def is_enabled(environ=os.environ) -> bool:
    if _enabled_override is not None:
        return _enabled_override
    return telemetry_enabled(environ)


from kubeflow_tpu.telemetry.ledger import EfficiencyLedger  # noqa: E402
from kubeflow_tpu.telemetry.profiler import (  # noqa: E402
    StepProfiler,
    overlap_fraction,
)
from kubeflow_tpu.telemetry.publisher import TelemetryPublisher  # noqa: E402

__all__ = [
    "EfficiencyLedger",
    "StepProfiler",
    "TELEMETRY_ENABLED_ENV",
    "TelemetryPublisher",
    "is_enabled",
    "overlap_fraction",
    "set_enabled",
    "telemetry_enabled",
]
