"""Registered timed sections around the parallel-stack collectives.

Host-side timers cannot see inside a jitted step, so collective-overlap
attribution works the only way that is honest under XLA's scheduler:
every collective in ``parallel/{ring,ulysses,pipeline,moe}.py`` is
issued through :func:`collective`, which (a) wraps the op in a
``jax.named_scope`` whose name is a **registered literal** from
``SECTION_SPECS`` (the ``telemetry-contract`` analysis pass rejects
unregistered or non-literal names, so profiler traces and docs can rely
on the vocabulary), and (b) in *serialize mode* fences the op with
``jax.lax.optimization_barrier`` on both sides, forcing every collective
to complete before dependent compute may start.

The paired measurement — the same step compiled once normally and once
serialized — yields the overlap attribution number::

    overlap_fraction = clamp((t_serialized - t_overlapped) / t_serialized)

i.e. the fraction of serialized step time that XLA's schedule hides by
overlapping comms with compute. Serialize mode is a *trace-time* flag:
flip it with :func:`set_serialize_collectives` before building/compiling
the step function (``bench.py multichip`` compiles each arm fresh).
"""

from __future__ import annotations

import jax

# (name, module, description) — pure literals; the telemetry-contract
# pass reads this tuple from the AST and every ``collective(...)`` call
# site must name one of these.
SECTION_SPECS = (
    ("ring_kv_hop", "kubeflow_tpu/parallel/ring",
     "K/V block ppermute to the next ring neighbor (xla block impl)"),
    ("ring_flash_kv_hop", "kubeflow_tpu/parallel/ring",
     "K/V block ppermute in the flash-kernel ring forward"),
    ("ring_flash_grad_hop", "kubeflow_tpu/parallel/ring",
     "K/V + dK/dV accumulator ppermute in the flash ring backward"),
    ("ulysses_all_to_all", "kubeflow_tpu/parallel/ulysses",
     "heads<->sequence all_to_all (both directions of the exchange)"),
    ("pipeline_stage_hop", "kubeflow_tpu/parallel/pipeline",
     "microbatch activation ppermute to the next pipeline stage"),
    ("moe_dispatch_all_to_all", "kubeflow_tpu/parallel/moe",
     "token-slot all_to_all scattering tokens to their experts"),
    ("moe_combine_all_to_all", "kubeflow_tpu/parallel/moe",
     "expert-output all_to_all returning tokens to their home shard"),
)

SECTION_NAMES = frozenset(spec[0] for spec in SECTION_SPECS)

_serialize = False


def _barrier_tree(tree):
    """optimization_barrier over a pytree, skipping non-differentiable
    leaves (float0 cotangents for integer operands have no barrier
    lowering)."""
    from jax.dtypes import float0

    return jax.tree.map(
        lambda t: t if getattr(t, "dtype", None) == float0
        else jax.lax.optimization_barrier(t),
        tree,
    )


@jax.custom_vjp
def _fence(tree):
    return _barrier_tree(tree)


def _fence_fwd(tree):
    return _barrier_tree(tree), None


def _fence_bwd(_, cotangents):
    # Fence the cotangents too: the backward pass runs the TRANSPOSED
    # collective (all_to_all ↔ all_to_all, ppermute ↔ inverse ppermute),
    # and serialize mode must stop XLA from overlapping that one as well
    # — plus optimization_barrier has no differentiation rule of its own
    # (jax ≤ 0.4.x), so the custom VJP is what makes serialize-mode steps
    # trainable at all.
    return (_barrier_tree(cotangents),)


_fence.defvjp(_fence_fwd, _fence_bwd)


def set_serialize_collectives(on: bool) -> None:
    """Trace-time switch: fence registered collectives with optimization
    barriers so comms cannot overlap compute. Only affects functions
    *traced* while on — recompile the step for each arm of the A/B."""
    global _serialize
    _serialize = bool(on)


def serialize_collectives() -> bool:
    return _serialize


def collective(name: str, op, *operands, **kwargs):
    """Issue collective ``op(*operands, **kwargs)`` inside the registered
    timed section ``name``.

    ``name`` must be a literal from ``SECTION_SPECS`` (enforced both here
    at trace time and statically by the telemetry-contract pass). The
    named scope shows up in XLA profiler traces (``kftpu.<name>``) so
    ``sdk.capture_profile`` dumps attribute comm time to these labels.
    """
    if name not in SECTION_NAMES:
        raise ValueError(
            f"unregistered telemetry section {name!r}; add it to "
            f"telemetry/sections.py SECTION_SPECS"
        )
    with jax.named_scope("kftpu." + name):
        if _serialize:
            operands = _fence(operands)
        out = op(*operands, **kwargs)
        if _serialize:
            out = _fence(out)
    return out
