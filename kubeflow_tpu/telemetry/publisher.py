"""Single-writer export path for step telemetry.

Durability discipline mirrors ``runtime/timeline.py`` (PR 13): the
summary travels in ONE compact, capped JSON annotation
(``keys.NOTEBOOK_TPU_TELEMETRY``) so it survives controller restarts and
is readable by the notebook controller, JWA status, and the scheduler
without a side channel. This module is the annotation's only writer —
the OWNERS write-set in ``api/keys.py`` and the ``telemetry-contract``
analysis pass both pin that down; everything else (controller fold, JWA
message, efficiency ledger) is a *reader*.

Wire format (short keys — the cap is bytes, not fields)::

    {"v": 1, "seq": 7, "at": 1754550000.0, "family": "moe",
     "step": 1200, "mfu": 0.57, "basis": "accelerator",
     "step_sec": 0.012, "tok_s": 81000, "overlap": 0.41,
     "compile_sec": 8.2, "hbm": 123456789}

Publishes are rate-limited (``KFTPU_TELEMETRY_PUBLISH_SECONDS``) and the
encoded payload is capped (``KFTPU_TELEMETRY_MAX_CHARS``) by dropping
optional fields, never by emitting torn JSON.
"""

from __future__ import annotations

import json
import logging
import os
import time

from kubeflow_tpu.api import keys
from kubeflow_tpu.runtime.metrics import Registry, global_registry

logger = logging.getLogger(__name__)

TELEMETRY_ANNOTATION = keys.NOTEBOOK_TPU_TELEMETRY

PUBLISH_SECONDS_ENV = "KFTPU_TELEMETRY_PUBLISH_SECONDS"
DEFAULT_PUBLISH_SECONDS = 30.0

MAX_CHARS_ENV = "KFTPU_TELEMETRY_MAX_CHARS"
DEFAULT_MAX_CHARS = 2048

STALE_SECONDS_ENV = "KFTPU_TELEMETRY_STALE_SECONDS"
DEFAULT_STALE_SECONDS = 120.0

# Dropped one by one (front first) when the encoded payload exceeds the
# cap; the core fields (v/seq/at/family/step/mfu/step_sec) always fit.
_OPTIONAL_FIELDS = ("hbm", "compile_sec", "tok_s", "basis", "overlap")


def publish_seconds(environ=os.environ) -> float:
    raw = environ.get(PUBLISH_SECONDS_ENV)
    try:
        return float(raw) if raw is not None else DEFAULT_PUBLISH_SECONDS
    except ValueError:
        return DEFAULT_PUBLISH_SECONDS


def max_chars(environ=os.environ) -> int:
    raw = environ.get(MAX_CHARS_ENV)
    try:
        value = int(raw) if raw is not None else DEFAULT_MAX_CHARS
    except ValueError:
        return DEFAULT_MAX_CHARS
    return max(256, value)


def stale_after_seconds(environ=os.environ) -> float:
    raw = environ.get(STALE_SECONDS_ENV)
    try:
        return float(raw) if raw is not None else DEFAULT_STALE_SECONDS
    except ValueError:
        return DEFAULT_STALE_SECONDS


def _round(value, digits):
    return None if value is None else round(float(value), digits)


def encode(summary: dict, *, seq: int, at: float,
           cap: int | None = None) -> str:
    """Profiler summary -> capped wire JSON (compact separators)."""
    entry = {
        "v": 1,
        "seq": int(seq),
        "at": round(float(at), 3),
        "family": str(summary.get("family") or "")[:48],
        "step": int(summary.get("step") or 0),
        "mfu": _round(summary.get("mfu"), 4),
        "step_sec": _round(summary.get("step_p50_sec"), 6),
        "overlap": _round(summary.get("overlap_fraction"), 4),
        "basis": summary.get("mfu_basis"),
        "tok_s": _round(summary.get("tokens_per_sec"), 1),
        "compile_sec": _round(summary.get("compile_sec"), 3),
        "hbm": summary.get("hbm_high_water_bytes"),
    }
    entry = {k: v for k, v in entry.items() if v is not None}
    cap = cap if cap is not None else max_chars()
    payload = json.dumps(entry, separators=(",", ":"))
    for field in _OPTIONAL_FIELDS:
        if len(payload) <= cap:
            break
        entry.pop(field, None)
        payload = json.dumps(entry, separators=(",", ":"))
    return payload


def decode(annotations: dict | None) -> dict | None:
    """Annotation map -> telemetry entry, or None when absent/corrupt.
    Corruption degrades to 'no telemetry' (the stale path), never an
    exception into a reconcile."""
    raw = (annotations or {}).get(TELEMETRY_ANNOTATION)
    if not raw:
        return None
    try:
        entry = json.loads(raw)
    except (TypeError, ValueError):
        logger.warning("undecodable telemetry annotation: %.80r", raw)
        return None
    if not isinstance(entry, dict) or "at" not in entry:
        return None
    try:
        entry["at"] = float(entry["at"])
        entry["seq"] = int(entry.get("seq", 0))
        entry["step"] = int(entry.get("step", 0))
    except (TypeError, ValueError):
        return None
    return entry


def is_stale(entry: dict, now: float,
             stale_after: float | None = None) -> bool:
    window = stale_after if stale_after is not None else stale_after_seconds()
    return (now - float(entry.get("at", 0.0))) > window


def publish_metrics(summary: dict, registry: Registry | None = None) -> None:
    """Update the Prometheus series from a summary/entry dict. Used by
    the SDK-side publisher and by the controller fold (so the manager's
    /metrics carries fleet-wide training telemetry)."""
    registry = registry or global_registry
    family = str(summary.get("family") or "unknown")
    pairs = (
        ("tpu_training_mfu",
         "achieved model FLOPs utilization (rolling-window p50)",
         summary.get("mfu")),
        ("tpu_training_step_seconds",
         "training step wall time p50 over the rolling window",
         summary.get("step_p50_sec", summary.get("step_sec"))),
        ("tpu_training_overlap_fraction",
         "fraction of serialized step time hidden by comm/compute overlap",
         summary.get("overlap_fraction", summary.get("overlap"))),
        ("tpu_training_hbm_bytes",
         "HBM high-water mark for the training step",
         summary.get("hbm_high_water_bytes", summary.get("hbm"))),
    )
    for name, help_, value in pairs:
        if value is None:
            continue
        registry.gauge(name, help_, ["family"]).labels(
            family=family).set(float(value))


class TelemetryPublisher:
    """The one writer of the telemetry annotation.

    ``patcher(body)`` applies a merge-patch to the owning Notebook (the
    SDK wires ``sdk._in_cluster_patcher``; tests inject a recorder).
    Publishes are rate-limited to ``min_interval`` seconds unless
    ``force=True`` (final flush). A failed patch is counted and retried
    at the next window — telemetry must never take down the loop.
    """

    def __init__(self, patcher, *, min_interval: float | None = None,
                 cap: int | None = None, registry: Registry | None = None,
                 now_fn=time.time, clock=time.monotonic,
                 environ=os.environ):
        self._patcher = patcher
        self._min_interval = (min_interval if min_interval is not None
                              else publish_seconds(environ))
        self._cap = cap if cap is not None else max_chars(environ)
        self._registry = registry
        self._now_fn = now_fn
        self._clock = clock
        self.seq = 0
        self.errors = 0
        self.last_error: str | None = None
        self._last_publish: float | None = None

    def publish(self, summary: dict, *, force: bool = False) -> bool:
        now = self._clock()
        if (not force and self._last_publish is not None
                and now - self._last_publish < self._min_interval):
            return False
        self.seq += 1
        payload = encode(summary, seq=self.seq, at=self._now_fn(),
                         cap=self._cap)
        publish_metrics(summary, self._registry)
        try:
            self._patcher(
                {"metadata": {"annotations": {TELEMETRY_ANNOTATION: payload}}}
            )
        except Exception as exc:
            # Counted + logged; a failed telemetry patch must never take
            # down the training loop — the next window retries.
            self.errors += 1
            self.last_error = repr(exc)
            logger.warning("telemetry publish failed (attempt %d): %s",
                           self.seq, exc)
            return False
        self._last_publish = now
        return True
