"""Per-family x shape efficiency ledger — the scheduler placement signal.

Telemetry closes the loop: the notebook controller decodes each gang's
telemetry annotation and feeds (gang key, model family, chip shape,
achieved MFU) here; the fleet scheduler consults the ledger when ranking
*idle* preemption/defrag candidates and when explaining placement
("this family historically achieves X on this shape").

Strictly advisory ordering: a persistently-low-MFU gang is *preferred
within the idle tier only*. It never outranks the existing protections —
workload-class tiers (serving is never a victim), busy-vs-idle, and
priority all sort first; tests/test_telemetry.py pins that down.

State is EWMA per (family, shape) and per gang, pure and clock-free —
the scheduler snapshots it into debug_info/explain like every other
policy structure.
"""

from __future__ import annotations

import os

LOW_MFU_ENV = "KFTPU_TELEMETRY_LOW_MFU"
DEFAULT_LOW_MFU = 0.25

MIN_SAMPLES_ENV = "KFTPU_TELEMETRY_MIN_SAMPLES"
DEFAULT_MIN_SAMPLES = 5

# EWMA weight for the newest sample: heavy enough to track a family
# switching phases, light enough that one bad window is not "persistent".
EWMA_ALPHA = 0.3


def low_mfu_threshold(environ=os.environ) -> float:
    raw = environ.get(LOW_MFU_ENV)
    try:
        return float(raw) if raw is not None else DEFAULT_LOW_MFU
    except ValueError:
        return DEFAULT_LOW_MFU


def min_samples(environ=os.environ) -> int:
    raw = environ.get(MIN_SAMPLES_ENV)
    try:
        value = int(raw) if raw is not None else DEFAULT_MIN_SAMPLES
    except ValueError:
        return DEFAULT_MIN_SAMPLES
    return max(1, value)


class _Ewma:
    __slots__ = ("value", "samples")

    def __init__(self):
        self.value: float | None = None
        self.samples = 0

    def update(self, sample: float) -> None:
        sample = max(0.0, min(1.0, float(sample)))
        if self.value is None:
            self.value = sample
        else:
            self.value = (1 - EWMA_ALPHA) * self.value + EWMA_ALPHA * sample
        self.samples += 1


class EfficiencyLedger:
    def __init__(self, *, low_mfu: float | None = None,
                 samples_needed: int | None = None, environ=os.environ):
        self.low_mfu = (low_mfu if low_mfu is not None
                        else low_mfu_threshold(environ))
        self.samples_needed = (samples_needed if samples_needed is not None
                               else min_samples(environ))
        self._families: dict[tuple[str, str], _Ewma] = {}
        self._gangs: dict[str, dict] = {}

    # ------------------------------------------------------------- write

    def note(self, key: str, family: str, shape: str, mfu) -> None:
        """Record one telemetry window for gang ``key`` (deduplicated by
        annotation seq at the caller). ``mfu`` may be None (unknown basis)
        — the sighting still registers family/shape for explain."""
        family = str(family or "unknown")
        shape = str(shape or "unknown")
        gang = self._gangs.setdefault(
            key, {"family": family, "shape": shape, "ewma": _Ewma()})
        gang["family"], gang["shape"] = family, shape
        if mfu is None:
            return
        gang["ewma"].update(mfu)
        self._families.setdefault((family, shape), _Ewma()).update(mfu)

    def forget(self, key: str) -> None:
        """Drop a gang's row (released/stopped). Family x shape history
        — the placement prior — survives the gang."""
        self._gangs.pop(key, None)

    # -------------------------------------------------------------- read

    def expected_mfu(self, family: str, shape: str) -> float | None:
        ewma = self._families.get((str(family), str(shape)))
        return ewma.value if ewma is not None else None

    def gang_mfu(self, key: str) -> float | None:
        gang = self._gangs.get(key)
        return gang["ewma"].value if gang is not None else None

    def persistently_low(self, key: str) -> bool:
        """True once a gang has enough windows AND its EWMA sits under
        the low-MFU threshold — the only signal the scheduler's idle-tier
        ranking consumes."""
        gang = self._gangs.get(key)
        if gang is None:
            return False
        ewma = gang["ewma"]
        return (ewma.samples >= self.samples_needed
                and ewma.value is not None
                and ewma.value < self.low_mfu)

    def explain(self, key: str) -> dict | None:
        """The 'this family historically achieves X on this shape' block
        for the scheduler's explain endpoint."""
        gang = self._gangs.get(key)
        if gang is None:
            return None
        family, shape = gang["family"], gang["shape"]
        expected = self.expected_mfu(family, shape)
        fam = self._families.get((family, shape))
        return {
            "family": family,
            "shape": shape,
            "gang_mfu": _round4(gang["ewma"].value),
            "gang_samples": gang["ewma"].samples,
            "expected_mfu": _round4(expected),
            "family_samples": fam.samples if fam is not None else 0,
            "persistently_low": self.persistently_low(key),
            "low_mfu_threshold": self.low_mfu,
        }

    def debug_info(self) -> dict:
        return {
            "low_mfu_threshold": self.low_mfu,
            "min_samples": self.samples_needed,
            "families": {
                f"{family}@{shape}": {
                    "mfu": _round4(ewma.value), "samples": ewma.samples,
                }
                for (family, shape), ewma in sorted(self._families.items())
            },
            "gangs": {
                key: {
                    "family": gang["family"],
                    "shape": gang["shape"],
                    "mfu": _round4(gang["ewma"].value),
                    "samples": gang["ewma"].samples,
                    "persistently_low": self.persistently_low(key),
                }
                for key, gang in sorted(self._gangs.items())
            },
        }


def _round4(value):
    return None if value is None else round(float(value), 4)
