"""Ulysses-style all-to-all sequence parallelism.

The second long-context strategy next to ring attention
(``parallel/ring.py``): instead of rotating K/V blocks around the ICI ring,
attention *heads* are exchanged for *sequence* shards with two all-to-alls
(public DeepSpeed-Ulysses pattern, PAPERS.md):

    [b, S/P, H, d]  --a2a-->  [b, S, H/P, d]      (heads scatter, seq gather)
    full-sequence attention on H/P local heads    (exact softmax, no ring)
    [b, S, H/P, d]  --a2a-->  [b, S/P, H, d]      (seq scatter, heads gather)

Trade-offs vs ring, honestly reflected in when each is the right default:
Ulysses does O(1) collective rounds (two all-to-alls) and computes exact
attention with plain XLA-fused matmuls, but requires heads % P == 0 and
materializes full-sequence attention scores per device — peak activation
O(S²·H/P). Ring keeps memory at O((S/P)²) with P neighbor hops. Short/mid
contexts with enough heads → Ulysses; extreme contexts → ring. Both run on
the same mesh axes, so callers can switch per layer.

Implementation is original; ``jax.lax.all_to_all`` lowers onto ICI
all-to-all (a first-class collective on TPU tori).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from kubeflow_tpu.telemetry import sections


def _largest_divisor_block(s: int, cap: int = 1024) -> int:
    """Largest tileable block ≤ ``cap`` that divides ``s`` — the flash
    kernel tiles the sequence and requires s % block == 0, but ulysses
    callers pick S freely (e.g. S=1536 → block 768).

    ``s ≤ cap`` is always fine (one block). Beyond that, blocks must stay
    lane-friendly (multiples of 128 — Mosaic's sublane tiling, and a floor
    against degenerate tiny-block grids), so an awkward S (no 128-multiple
    divisor, e.g. 2×prime) raises the same clear error the kernel used to,
    here at the call site where the config that chose S is visible."""
    if s <= cap:
        return s
    for block in range(cap, 127, -1):
        if s % block == 0 and block % 128 == 0:
            return block
    raise ValueError(
        f"gathered sequence {s} has no block-sized divisor ≤ {cap} "
        f"(multiple of 128); choose a sequence length divisible by 128"
    )


def _a2a(x, axis_name: str, scatter_dim: int, gather_dim: int):
    """all_to_all with the manual-mode convention used inside shard_map:
    scatter ``scatter_dim`` across the axis, concatenate ``gather_dim``.
    Issued through the registered telemetry section so both directions of
    the heads<->sequence exchange are attributable/serializable."""
    return sections.collective(
        "ulysses_all_to_all", jax.lax.all_to_all,
        x, axis_name=axis_name, split_axis=scatter_dim,
        concat_axis=gather_dim, tiled=True,
    )


def ulysses_attention_local(q, k, v, axis_name: str, block_impl: str = "xla"):
    """Per-shard exact causal attention via two all-to-alls.

    Args: q/k/v ``[batch, s_local, heads, head_dim]`` with heads divisible
    by the axis size. Call inside ``shard_map``; returns the same shape.

    ``block_impl="flash"`` runs the gathered-sequence attention through the
    pallas flash kernel (ops/flash_attention.py) instead of materializing
    the [S, S] logits. Both long-context strategies are trainable end to
    end — ring via the per-hop custom VJP in parallel/ring.py, ulysses via
    this kernel's fused VJP — so the choice is the memory/collective
    trade-off: ring keeps O((S/P)²) activation per device at the cost of P
    neighbor hops; ulysses gathers the full sequence for H/P local heads
    in two all-to-alls. The post-a2a layout [b, S, H/P, d] is exactly the
    kernel's bshd contract.
    """
    p = jax.lax.psum(1, axis_name)
    b, s_local, h, d = q.shape
    if h % p:
        raise ValueError(
            f"ulysses needs heads % shards == 0, got {h} heads / {p} shards"
        )

    # [b, S/P, H, d] -> [b, S, H/P, d]: scatter heads (dim 2), gather seq
    # (dim 1). After this every device holds the FULL sequence for its
    # H/P heads, so causal attention is exact with a plain mask.
    q, k, v = (_a2a(t, axis_name, 2, 1) for t in (q, k, v))

    if block_impl == "flash":
        from kubeflow_tpu.ops import flash_attention

        # Pick blocks from the gathered sequence's divisors so any S works
        # (e.g. S=1536 → 768) instead of surfacing the kernel's ValueError
        # at this distance from the config that chose S.
        block = _largest_divisor_block(s_local * p)
        # The kernel derives its outputs' varying-axes metadata from the
        # inputs (always correct, whatever mesh the caller shard_maps on).
        out = flash_attention(q, k, v, block_q=block, block_k=block)
    elif block_impl == "xla":
        s_full = s_local * p
        scale = 1.0 / (d ** 0.5)
        logits = (
            jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
        )
        mask = jnp.tril(jnp.ones((s_full, s_full), bool))
        logits = jnp.where(mask[None, None], logits, -1e30)
        probs = jax.nn.softmax(logits, axis=-1).astype(v.dtype)
        out = jnp.einsum("bhqk,bkhd->bqhd", probs, v)
    else:
        raise ValueError(
            f"unknown block_impl {block_impl!r} (want 'xla' or 'flash')"
        )

    # [b, S, H/P, d] -> [b, S/P, H, d]: scatter seq back, gather heads.
    return _a2a(out, axis_name, 1, 2)


def ulysses_attention(q, k, v, mesh, axis_name: str = "seq",
                      block_impl: str = "xla"):
    """GSPMD entrypoint mirroring ``ring_attention``'s signature: q/k/v
    ``[batch, seq, heads, head_dim]`` sequence-sharded over ``axis_name``;
    other mesh axes shard batch. ``block_impl="flash"`` swaps the exact
    softmax for the pallas flash kernel (fwd+bwd — trainable); block sizes
    are chosen from the gathered sequence's divisors, so any S works."""
    from jax.sharding import PartitionSpec as P

    from kubeflow_tpu.parallel.mesh import shard_map_compat

    data_axes = tuple(n for n in mesh.axis_names if n != axis_name)
    batch_spec = data_axes[0] if len(data_axes) == 1 else (data_axes or None)
    spec = P(batch_spec if data_axes else None, axis_name, None, None)
    return shard_map_compat(
        partial(ulysses_attention_local, axis_name=axis_name,
                block_impl=block_impl),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )(q, k, v)


# ------------------------------------------------ ring x ulysses composition


def ring_ulysses_attention_local(q, k, v, ring_axis: str, uly_axis: str,
                                 mesh_axes=None, block_impl: str = "xla"):
    """Per-shard causal attention over a 2-D sequence mesh — the USP-style
    composition of both strategies. Call inside ``shard_map`` with the
    sequence sharded over ``(ring_axis, uly_axis)`` (ring-major):

    1. Ulysses all-to-all over ``uly_axis``: heads scatter, sequence
       gathers — device ``(r, u)`` ends up holding the *contiguous* ring
       block ``r`` (``S/P_ring`` tokens) for its ``H/P_uly`` heads. The
       ring-major token layout is what makes the gather contiguous, so
       ring block indices stay meaningful global positions.
    2. Ring attention over ``ring_axis`` on the gathered blocks — exact
       causal block masking, K/V hops between ring neighbors only.
    3. All-to-all back: sequence scatters, heads gather.

    The composition extends long-context scaling past either strategy
    alone: ring's per-chip memory O((S/P_ring)²) and hop count P_ring
    stay fixed while the ulysses axis multiplies total sequence capacity
    by P_uly at the cost of two all-to-alls (which are O(1) rounds).
    Requires ``heads % P_uly == 0``.
    """
    p_uly = jax.lax.psum(1, uly_axis)
    h = q.shape[2]
    if h % p_uly:
        raise ValueError(
            f"ring+ulysses needs heads % ulysses shards == 0, "
            f"got {h} heads / {p_uly} shards"
        )
    from kubeflow_tpu.parallel.ring import ring_attention_local

    # [b, S/(Pr*Pu), H, d] -> [b, S/Pr, H/Pu, d]
    q, k, v = (_a2a(t, uly_axis, 2, 1) for t in (q, k, v))
    out = ring_attention_local(q, k, v, axis_name=ring_axis,
                               mesh_axes=mesh_axes, block_impl=block_impl)
    # [b, S/Pr, H/Pu, d] -> [b, S/(Pr*Pu), H, d]
    return _a2a(out, uly_axis, 1, 2)


def ring_ulysses_attention(q, k, v, mesh, axis_name=("seq_ring", "seq_uly"),
                           block_impl: str = "xla"):
    """GSPMD entrypoint for the composed strategy: ``axis_name`` is the
    PAIR ``(ring_axis, uly_axis)`` and q/k/v ``[batch, seq, heads,
    head_dim]`` have their sequence dim sharded over both axes
    (ring-major, i.e. ``P(..., (ring_axis, uly_axis), ...)``) — which is
    exactly what ``longctx.shard_inputs`` produces when handed the tuple
    as its ``seq_axis``. Other mesh axes shard batch."""
    from jax.sharding import PartitionSpec as P

    from kubeflow_tpu.parallel.mesh import shard_map_compat

    ring_axis, uly_axis = axis_name
    data_axes = tuple(n for n in mesh.axis_names
                      if n not in (ring_axis, uly_axis))
    batch_spec = data_axes[0] if len(data_axes) == 1 else (data_axes or None)
    spec = P(batch_spec if data_axes else None,
             (ring_axis, uly_axis), None, None)
    return shard_map_compat(
        partial(ring_ulysses_attention_local, ring_axis=ring_axis,
                uly_axis=uly_axis, mesh_axes=tuple(mesh.axis_names),
                block_impl=block_impl),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=block_impl != "flash",
    )(q, k, v)
