"""Ring attention: causal attention over a sequence-sharded axis.

Long-context sequence parallelism the TPU way: the sequence dimension is
sharded across a mesh axis; each device keeps its Q block resident and the
K/V blocks rotate around the ring with ``jax.lax.ppermute`` (one neighbor
hop per step — exactly the traffic pattern ICI torus links are built for),
while a streaming (flash-style) online softmax accumulates the output. Peak
memory per chip is O(S/P · S/P) instead of O(S²); comm volume per step is
the K/V block, fully overlappable with the block matmul.

Pattern follows the public ring-attention literature (PAPERS.md); the
implementation is original and favors XLA-friendly structure: static trip
count ``fori_loop``, no data-dependent control flow, bf16 matmuls with f32
accumulation.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from kubeflow_tpu.telemetry import sections

_NEG_BIG = -1e30  # not -inf: keeps the online-softmax max finite pre-first-hit


def _block_causal_mask(q_block: jax.Array, k_block: jax.Array, s_local: int):
    """[s_local, s_local] causal mask between global blocks q_block/k_block."""
    q_pos = q_block * s_local + jnp.arange(s_local)[:, None]
    k_pos = k_block * s_local + jnp.arange(s_local)[None, :]
    return k_pos <= q_pos


def ring_attention_local(q, k, v, axis_name: str, mesh_axes=None,
                         block_impl: str = "xla"):
    """Per-shard causal ring attention. Call inside ``shard_map``.

    Args: q/k/v ``[batch, s_local, heads, head_dim]`` — this device's
    sequence block. ``mesh_axes`` is every manual axis of the enclosing
    shard_map (defaults to just ``axis_name``); the online-softmax carries
    must be marked varying over all of them, because the loop body's
    outputs inherit the q/k/v varying set (e.g. a ``data`` batch axis),
    and ``fori_loop`` requires carry types to be loop-invariant.
    Returns the attention output with the same shape.

    ``block_impl="flash"`` runs each hop through the pallas partial
    kernel AND is trainable: a hand-written custom VJP re-rotates K/V
    (with their gradient accumulators) around the ring while the partial
    backward kernels produce each block-pair's dq/dk/dv from the final
    logsumexp — see ``_ring_flash``.
    """
    if block_impl == "flash":
        vary = tuple(mesh_axes) if mesh_axes else (axis_name,)
        return _ring_flash(q, k, v, axis_name, vary)
    n_shards = jax.lax.psum(1, axis_name)
    my_block = jax.lax.axis_index(axis_name)
    b, s_local, h, d = q.shape
    scale = 1.0 / (d ** 0.5)
    perm = [(j, (j + 1) % n_shards) for j in range(n_shards)]

    # Online softmax state (f32): running max, denominator, numerator.
    # Freshly-created arrays are replicated w.r.t. the manual axis; mark
    # them device-varying so the fori_loop carry types stay consistent.
    m = jnp.full((b, h, s_local), _NEG_BIG, jnp.float32)
    l = jnp.zeros((b, h, s_local), jnp.float32)
    o = jnp.zeros((b, s_local, h, d), jnp.float32)
    vary_axes = tuple(mesh_axes) if mesh_axes else (axis_name,)
    m, l, o = (_mark_varying(t, vary_axes) for t in (m, l, o))

    def body(t, carry):
        k_t, v_t, m, l, o = carry
        src_block = (my_block - t) % n_shards

        logits = (
            jnp.einsum("bqhd,bkhd->bhqk", q, k_t).astype(jnp.float32)
            * scale
        )
        mask = _block_causal_mask(my_block, src_block, s_local)
        logits = jnp.where(mask[None, None, :, :], logits, _NEG_BIG)

        m_new = jnp.maximum(m, logits.max(axis=-1))
        correction = jnp.exp(m - m_new)
        p = jnp.exp(logits - m_new[..., None])
        l = l * correction + p.sum(axis=-1)
        o = o * correction.transpose(0, 2, 1)[..., None] + jnp.einsum(
            "bhqk,bkhd->bqhd", p.astype(v_t.dtype), v_t
        ).astype(jnp.float32)

        # Rotate K/V to the next device; AFTER the matmul so XLA can overlap
        # the collective-permute with the next iteration's compute. The
        # registered section makes the hop attributable in profiler traces
        # and serializable for the overlap A/B (telemetry/sections.py).
        k_t = sections.collective("ring_kv_hop", jax.lax.ppermute,
                                  k_t, axis_name=axis_name, perm=perm)
        v_t = sections.collective("ring_kv_hop", jax.lax.ppermute,
                                  v_t, axis_name=axis_name, perm=perm)
        return (k_t, v_t, m_new, l, o)

    _, _, m, l, o = jax.lax.fori_loop(0, n_shards, body, (k, v, m, l, o))
    denom = jnp.maximum(l, 1e-20).transpose(0, 2, 1)[..., None]
    return (o / denom).astype(q.dtype)


# ------------------------------------------------- trainable flash ring


def _mark_varying(t, axes):
    if hasattr(jax.lax, "pcast"):
        return jax.lax.pcast(t, tuple(axes), to="varying")
    if hasattr(jax.lax, "pvary"):
        return jax.lax.pvary(t, tuple(axes))
    # Pre-vma jax (< 0.5): shard_map has no varying-axes type system, so
    # carries need no marking — fresh arrays already unify with the loop
    # body's outputs.
    return t


def _ring_flash_fwd_loop(q, k, v, axis_name, vary_axes):
    """Flash-kernel ring forward; returns (normalized o, lse [b, h, s])."""
    from kubeflow_tpu.ops.flash_attention import flash_attention_partial

    n_shards = jax.lax.psum(1, axis_name)
    my_block = jax.lax.axis_index(axis_name)
    b, s_local, h, d = q.shape
    scale = 1.0 / (d ** 0.5)
    perm = [(j, (j + 1) % n_shards) for j in range(n_shards)]

    m = _mark_varying(jnp.full((b, h, s_local), _NEG_BIG, jnp.float32), vary_axes)
    l = _mark_varying(jnp.zeros((b, h, s_local), jnp.float32), vary_axes)
    o = _mark_varying(jnp.zeros((b, s_local, h, d), jnp.float32), vary_axes)

    def body(t, carry):
        k_t, v_t, m, l, o = carry
        src_block = (my_block - t) % n_shards
        o_blk, m_blk, l_blk = flash_attention_partial(
            q, k_t, v_t, my_block * s_local, src_block * s_local,
            scale=scale, vma=vary_axes,
        )
        m_new = jnp.maximum(m, m_blk)
        corr = jnp.exp(m - m_new)
        corr_blk = jnp.exp(m_blk - m_new)
        l = l * corr + l_blk * corr_blk
        o = (
            o * corr.transpose(0, 2, 1)[..., None]
            + o_blk.astype(jnp.float32) * corr_blk.transpose(0, 2, 1)[..., None]
        )
        k_t = sections.collective("ring_flash_kv_hop", jax.lax.ppermute,
                                  k_t, axis_name=axis_name, perm=perm)
        v_t = sections.collective("ring_flash_kv_hop", jax.lax.ppermute,
                                  v_t, axis_name=axis_name, perm=perm)
        return (k_t, v_t, m_new, l, o)

    _, _, m, l, o = jax.lax.fori_loop(0, n_shards, body, (k, v, m, l, o))
    l_safe = jnp.maximum(l, 1e-20)
    out = (o / l_safe.transpose(0, 2, 1)[..., None]).astype(q.dtype)
    lse = m + jnp.log(l_safe)
    return out, lse


@partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _ring_flash(q, k, v, axis_name, vary_axes):
    out, _ = _ring_flash_fwd_loop(q, k, v, axis_name, vary_axes)
    return out


def _ring_flash_vjp_fwd(q, k, v, axis_name, vary_axes):
    out, lse = _ring_flash_fwd_loop(q, k, v, axis_name, vary_axes)
    return out, (q, k, v, out, lse)


def _ring_flash_vjp_bwd(axis_name, vary_axes, res, do):
    """Second rotation around the ring: each hop's partial backward runs
    on the pallas kernels with the FINAL logsumexp (so the block-pair
    probabilities are the true softmax values), dq accumulates locally,
    and dk/dv accumulators travel WITH their K/V blocks — after P hops
    they are back on their home devices."""
    from kubeflow_tpu.ops.flash_attention import flash_attention_partial_grads

    q, k, v, out, lse = res
    n_shards = jax.lax.psum(1, axis_name)
    my_block = jax.lax.axis_index(axis_name)
    b, s_local, h, d = q.shape
    scale = 1.0 / (d ** 0.5)
    perm = [(j, (j + 1) % n_shards) for j in range(n_shards)]

    delta = jnp.einsum(
        "bshd,bshd->bhs", do.astype(jnp.float32), out.astype(jnp.float32)
    )
    dq = _mark_varying(jnp.zeros((b, s_local, h, d), jnp.float32), vary_axes)
    dk = _mark_varying(jnp.zeros((b, s_local, h, d), jnp.float32), vary_axes)
    dv = _mark_varying(jnp.zeros((b, s_local, h, d), jnp.float32), vary_axes)

    def body(t, carry):
        k_t, v_t, dk_t, dv_t, dq = carry
        src_block = (my_block - t) % n_shards
        dq_p, dk_p, dv_p = flash_attention_partial_grads(
            q, k_t, v_t, do, lse, delta,
            my_block * s_local, src_block * s_local,
            scale=scale, vma=vary_axes,
        )
        dq = dq + dq_p.astype(jnp.float32)
        dk_t = dk_t + dk_p.astype(jnp.float32)
        dv_t = dv_t + dv_p.astype(jnp.float32)
        k_t = sections.collective("ring_flash_grad_hop", jax.lax.ppermute,
                                  k_t, axis_name=axis_name, perm=perm)
        v_t = sections.collective("ring_flash_grad_hop", jax.lax.ppermute,
                                  v_t, axis_name=axis_name, perm=perm)
        dk_t = sections.collective("ring_flash_grad_hop", jax.lax.ppermute,
                                   dk_t, axis_name=axis_name, perm=perm)
        dv_t = sections.collective("ring_flash_grad_hop", jax.lax.ppermute,
                                   dv_t, axis_name=axis_name, perm=perm)
        return (k_t, v_t, dk_t, dv_t, dq)

    _, _, dk, dv, dq = jax.lax.fori_loop(
        0, n_shards, body, (k, v, dk, dv, dq)
    )
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


_ring_flash.defvjp(_ring_flash_vjp_fwd, _ring_flash_vjp_bwd)


def ring_attention(q, k, v, mesh, axis_name: str = "seq",
                   block_impl: str = "xla"):
    """GSPMD entrypoint: q/k/v ``[batch, seq, heads, head_dim]`` with the
    seq dimension sharded over ``axis_name``; other mesh axes (data) shard
    batch transparently. ``block_impl="flash"`` runs each hop's block
    attention as the pallas partial kernel — fwd AND bwd (the custom VJP
    re-rotates K/V with their gradient accumulators; see _ring_flash), so
    ring long-context training never materializes block logits in HBM."""
    from jax.sharding import PartitionSpec as P

    from kubeflow_tpu.parallel.mesh import shard_map_compat

    data_axes = tuple(n for n in mesh.axis_names if n != axis_name)
    batch_spec = data_axes[0] if len(data_axes) == 1 else (data_axes or None)
    spec = P(batch_spec if data_axes else None, axis_name, None, None)
    # check_vma off for the flash hop: the pallas kernel's scalar-prefetch
    # offsets are device-varying, which jax's manual-mode varying-axes
    # analysis can't express through interpret-mode slicing yet (the error
    # message itself prescribes this workaround; numerics are unaffected).
    return shard_map_compat(
        partial(ring_attention_local, axis_name=axis_name,
                mesh_axes=tuple(mesh.axis_names), block_impl=block_impl),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=block_impl != "flash",
    )(q, k, v)


def reference_causal_attention(q, k, v):
    """Unsharded reference for correctness tests."""
    b, s, h, d = q.shape
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) / (d ** 0.5)
    mask = jnp.tril(jnp.ones((s, s), bool))
    logits = jnp.where(mask[None, None], logits, _NEG_BIG)
    probs = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", probs.astype(v.dtype), v)
