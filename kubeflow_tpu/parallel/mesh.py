"""Device-mesh planning for TPU slices.

TPU-first design note: rather than translating any NCCL/MPI-style process
groups, parallelism is expressed as a named ``jax.sharding.Mesh`` whose axes
XLA lowers to ICI collectives. ``plan_mesh`` picks a (data, model) factoring
of the available devices; callers annotate shardings and let GSPMD insert
``all-reduce``/``all-gather`` on the right axis.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import numpy as np
from jax.sharding import Mesh


@dataclass(frozen=True)
class MeshPlan:
    """A chosen factoring of devices into named parallelism axes."""

    data: int
    model: int

    @property
    def n_devices(self) -> int:
        return self.data * self.model


def plan_mesh(n_devices: int, max_model: int = 8) -> MeshPlan:
    """Factor ``n_devices`` into (data, model) with the largest model axis
    that divides the device count and stays ≤ ``max_model``.

    Model (tensor) parallelism rides the fastest ICI links, so we prefer a
    wider model axis up to one host's chips; the rest becomes data parallel.
    """
    if n_devices < 1:
        raise ValueError("need at least one device")
    model = 1
    for cand in range(min(max_model, n_devices), 0, -1):
        if n_devices % cand == 0:
            model = cand
            break
    return MeshPlan(data=n_devices // model, model=model)


def make_mesh(devices=None, plan: MeshPlan | None = None) -> Mesh:
    """Build a ("data", "model") mesh over ``devices`` (default: all)."""
    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    if plan is None:
        plan = plan_mesh(len(devices))
    if plan.n_devices != len(devices):
        raise ValueError(f"plan {plan} does not cover {len(devices)} devices")
    grid = np.asarray(devices).reshape(plan.data, plan.model)
    return Mesh(grid, axis_names=("data", "model"))


def shard_map_compat(f, *, mesh, in_specs, out_specs, check_vma=True):
    """``shard_map`` across the jax API generations this stack meets.

    New jax exposes ``jax.shard_map`` with the varying-axes type system
    (``check_vma``); pre-vma jax (< 0.5) ships
    ``jax.experimental.shard_map.shard_map`` with ``check_rep``, whose
    static replication inference is too conservative for scan/custom-VJP
    bodies (it fails outright on the pipelined schedule), so there the
    check is disabled — runtime semantics are identical, only the static
    replication audit is skipped.
    """
    import inspect

    try:
        from jax import shard_map as _shard_map
    except ImportError:  # pragma: no cover - pre-0.6 namespace
        from jax.experimental.shard_map import shard_map as _shard_map

    params = inspect.signature(_shard_map).parameters
    kwargs = {}
    if "check_vma" in params:
        if not check_vma:
            kwargs["check_vma"] = False
    elif "check_rep" in params:
        kwargs["check_rep"] = False
    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, **kwargs)
