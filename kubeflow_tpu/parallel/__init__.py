"""Mesh / sharding helpers for multi-host TPU slices.

The control plane wires ``TPU_WORKER_*`` + the jax.distributed coordinator
(see ``kubeflow_tpu.tpu.topology``); this package is the in-notebook half:
building a ``jax.sharding.Mesh`` over the slice and sharding the validation
workloads (and user models) across it.
"""

from kubeflow_tpu.parallel.mesh import (
    MeshPlan,
    make_mesh,
    plan_mesh,
)
from kubeflow_tpu.parallel.pipeline import (
    pipeline_apply,
    pipeline_spans,
    stage_ring_perm,
)

__all__ = [
    "MeshPlan",
    "make_mesh",
    "plan_mesh",
    "pipeline_apply",
    "pipeline_spans",
    "stage_ring_perm",
]
