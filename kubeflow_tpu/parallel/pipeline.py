"""Pipeline parallelism: GPipe microbatch schedule over a mesh axis.

The TPU way to pipeline: stages are a named mesh axis ("stage"); each device
holds a contiguous slice of the layer stack (leaves stacked on a leading
layer dimension and sharded over the axis), and inter-stage activation
transfer is one ``jax.lax.ppermute`` neighbour hop per schedule tick —
exactly the point-to-point pattern ICI torus links are built for. The
schedule is a static-trip-count ``lax.scan`` of length
``n_micro + n_stages - 1`` (the GPipe bubble); no data-dependent control
flow, so XLA traces a single program and overlaps the collective-permute
with the next tick's compute.

No hand-written backward schedule is needed: ``ppermute`` is linear, so
``jax.grad`` transposes the forward scan into the reverse-order backward
pipeline automatically (activations rematerialized per scan default or via
``jax.checkpoint`` policies chosen by the caller).

Reference parity note: the reference control plane has no DP/TP/PP code
(SURVEY.md §2.4 — grep-verified absent); pipeline parallelism is part of
the TPU-native data-plane substrate (dp/tp/sp/ep/pp) this framework
validates on slices, alongside ring/ulysses sequence parallelism and the
MoE expert-parallel path.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from kubeflow_tpu.telemetry import sections


def _mark_varying(t, axes):
    """Mark ``t`` device-varying over ``axes`` (skipping any it already
    varies on — e.g. ``zeros_like`` of a data-varying input inherits
    ``{V:data}`` and pvary/pcast reject re-adding it)."""
    try:
        have = set(jax.typeof(t).vma)
    except AttributeError:  # pragma: no cover - older jax
        have = set()
    need = tuple(a for a in axes if a not in have)
    if not need:
        return t
    if hasattr(jax.lax, "pcast"):
        return jax.lax.pcast(t, need, to="varying")
    if hasattr(jax.lax, "pvary"):
        return jax.lax.pvary(t, need)
    # Pre-vma jax (< 0.5): no varying-axes type system, nothing to mark.
    return t


def stage_ring_perm(n_stages: int) -> list[tuple[int, int]]:
    """Stage i forwards its activations to stage i+1 (circular; the wrap
    link only ever carries bubble garbage that stage 0 discards)."""
    return [(i, (i + 1) % n_stages) for i in range(n_stages)]


def pipeline_spans(n_layers: int, n_stages: int) -> list[tuple[int, int]]:
    """Even [start, stop) layer spans per stage; n_layers % n_stages == 0."""
    if n_layers % n_stages:
        raise ValueError(f"{n_layers} layers not divisible by {n_stages} stages")
    per = n_layers // n_stages
    return [(i * per, (i + 1) * per) for i in range(n_stages)]


# Full-unroll ceiling for the schedule scan. Unrolling removes the scan's
# per-tick dispatch AND lets XLA schedule across tick boundaries (on one
# chip the injected microbatches are independent once ``idx == 0`` folds,
# so their GEMMs interleave; on real multi-stage meshes the ppermute chain
# keeps ticks ordered but XLA still overlaps the hop with the next tick's
# compute). Measured on a v5e chip (bench family ``pipelined_schedule``,
# n_micro=4, n_stages=1): scan 0.30 MFU, unroll=2 0.26 (worse — the partial
# unroll keeps the scan AND doubles its body), full unroll 0.42. Hence
# full-or-nothing: unroll completely when the tick count is small, keep the
# scan for long schedules where unrolled code size would bloat compiles.
UNROLL_MAX_TICKS = 16


def pipeline_apply(stage_fn, stage_params, x_micro, *, n_stages: int,
                   axis_name: str = "stage", mesh_axes=None,
                   force_schedule: bool = False,
                   unroll: int | bool | None = None):
    """Run microbatches through the stage ring. Call inside ``shard_map``.

    Args:
      stage_fn: ``(stage_params, h) -> h`` applying this device's slice of
        the layer stack to one microbatch activation ``h [mb, ...]``.
      stage_params: pytree of this device's local layer slice (leaves are
        the per-stage shard of layer-stacked arrays).
      x_micro: ``[n_micro, mb, ...]`` embedded input microbatches. Present
        on every stage (cheap relative to the layer stack); only stage 0's
        copy is consumed, which also confines input-path gradients to
        stage 0.
      n_stages: static size of the stage axis (shard_map callers know it
        from ``mesh.shape``; ``psum(1, axis)`` would be traced, not static,
        and the scan needs a static trip count).
      mesh_axes: every manual axis of the enclosing shard_map — the scan
        carries must be marked varying over all of them (same rule as
        ring_attention_local's online-softmax carries).
      force_schedule: run the general tick/scan schedule even at
        ``n_stages == 1`` (normally routed around — see below). The bench
        uses this so the schedule machinery's overhead is a *tracked*
        number on hardware rather than only compiled in multi-stage gates.
      unroll: scan unroll override. ``None`` (default) fully unrolls
        schedules of ≤ ``UNROLL_MAX_TICKS`` ticks and keeps the scan above
        that (see the constant's rationale).

    Returns ``[n_micro, mb, ...]`` outputs — valid on the LAST stage only;
    other stages hold that stage's local compute on drain-bubble garbage
    (reduce with a ``where(idx==last)`` + ``psum`` as models/pipelined.py
    does for the loss).
    """
    n_micro = x_micro.shape[0]
    vary = tuple(mesh_axes) if mesh_axes else (axis_name,)
    if n_stages == 1 and not force_schedule:
        # Degenerate single-stage pipeline: no bubble, no ppermute, no
        # schedule scan — and the microbatches fuse back into one batch so
        # the GEMMs run at full MXU tile sizes instead of n_micro small
        # ones. The general path below is correct here too but pays
        # schedule overhead for nothing (measured on the bench family).
        # Input must be marked varying over every manual axis first: the
        # layer scan inside stage_fn mixes in stage-varying params, and a
        # {data}-only carry type would mismatch its output (same rule as
        # the general path's state/outputs).
        flat = _mark_varying(
            x_micro.reshape((-1,) + tuple(x_micro.shape[2:])), vary)
        return stage_fn(stage_params, flat).reshape(x_micro.shape)
    idx = jax.lax.axis_index(axis_name)
    last = n_stages - 1
    perm = stage_ring_perm(n_stages)
    n_ticks = n_micro + n_stages - 1
    if unroll is None:
        unroll = n_ticks if n_ticks <= UNROLL_MAX_TICKS else 1

    # The injection stream rides the scan's ``xs`` — a static per-tick
    # slice instead of the dynamic ``x_micro[min(t, n_micro-1)]`` gather
    # (whose transpose was a scatter-add over the whole buffer every
    # backward tick). Drain-bubble ticks re-inject the last microbatch;
    # whatever they compute never reaches a valid output slot.
    if n_stages > 1:
        pad = jnp.broadcast_to(
            x_micro[-1:], (n_stages - 1,) + x_micro.shape[1:])
        xs = jnp.concatenate([x_micro, pad], axis=0)
    else:
        xs = x_micro

    state = _mark_varying(jnp.zeros(x_micro.shape[1:], x_micro.dtype), vary)

    def tick(state, inject):
        h = jnp.where(idx == 0, inject, state)
        out = stage_fn(stage_params, h)
        # Hop AFTER the compute so XLA overlaps the collective-permute with
        # the next tick's stage_fn. Registered section: attributable in
        # profiler traces, serializable for the overlap A/B.
        state = sections.collective("pipeline_stage_hop", jax.lax.ppermute,
                                    out, axis_name=axis_name, perm=perm)
        return state, out

    # Per-tick outputs ride ``ys``: the last stage finishes microbatch m at
    # tick m + last, so its results are one static slice of the stack — no
    # carried outputs buffer, no per-tick dynamic_update + where masking
    # (which re-wrote the full buffer every tick, forward and transposed).
    _, ys = jax.lax.scan(tick, state, xs, unroll=unroll)
    return jax.lax.slice_in_dim(ys, last, last + n_micro, axis=0)
