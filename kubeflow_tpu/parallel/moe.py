"""Expert parallelism: switch-style top-1 MoE over a mesh "expert" axis.

The GShard/Switch pattern, TPU-first (public pattern per PAPERS.md;
implementation original):

- tokens are data-sharded over every mesh axis (data and expert axes both
  carry batch); **experts** shard over the ``expert`` axis;
- routing assigns (expert, slot) seats per token (``router_slots``); the
  hot path inverts the mapping into a seat→token id table (int32
  scatter) and **gathers** the ``[E·C, d]`` slot rows, combining by
  gathered, gate-scaled ``jnp.take`` — ~3× faster fwd+bwd than a d-wide
  scatter-add for the bare layer on v5e, and both beat the dense GShard
  one-hot einsums, whose ``[T, E, C]`` matmuls cost about as much as the
  expert FF itself (``router_dispatch`` keeps the dense form as the
  test oracle; honest deployed-step numbers in docs/perf.md);
- two ``all_to_all``s move token slots expert-shard→expert-shard over ICI
  (dims: ``[E, C, d] → [E/P, P·C, d]`` and back);
- capacity truncation keeps every shape static for XLA.

An auxiliary load-balancing loss (Switch §2.2 form) is returned so
training can keep routing uniform.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from kubeflow_tpu.telemetry import sections


def router_slots(logits, n_experts: int, capacity: int, k: int = 1):
    """Top-k routing as per-choice slot assignments.

    Returns ``(choices, probs, top_idx)`` where ``choices`` is a list of
    ``(expert_idx [T], slot_pos [T], gate [T], keep [T])`` — the sparse
    form of the dispatch/combine tensors. Capacity is accounted
    choice-major (every token's first choice seats before any second
    choice); overflow tokens get ``keep=False`` and ride the residual.
    """
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)  # [T, E]
    topk_p, topk_idx = jax.lax.top_k(probs, k)                   # [T, k]
    if k == 1:
        # Switch semantics: the gate IS the router probability (see
        # router_dispatch below for why renormalizing would be wrong).
        gates = topk_p
    else:
        gates = topk_p / jnp.maximum(topk_p.sum(-1, keepdims=True), 1e-9)
    counts = jnp.zeros((n_experts,), jnp.int32)
    choices = []
    for j in range(k):  # static, tiny
        onehot = jax.nn.one_hot(topk_idx[:, j], n_experts, dtype=jnp.int32)
        pos = (jnp.cumsum(onehot, axis=0) + counts[None, :]) * onehot - 1
        pos_tok = pos.max(axis=-1)                               # [T]
        keep = (pos_tok >= 0) & (pos_tok < capacity)
        choices.append((topk_idx[:, j], pos_tok, gates[:, j], keep))
        counts = counts + onehot.sum(axis=0)
    return choices, probs, topk_idx[:, 0]


def router_dispatch(logits, n_experts: int, capacity: int, k: int = 1):
    """Top-k routing → (dispatch, combine [T, E, C], probs [T, E], idx [T]).

    The dense form of ``router_slots`` — same routing decisions, densified
    into the GShard one-hot tensors. The hot path (``moe_ffn_local``) uses
    the sparse form directly; this exists as the reference/oracle shape
    the tests pin the sparse path against, so the seat-assignment logic
    lives in exactly one place.
    """
    choices, probs, idx = router_slots(logits, n_experts, capacity, k=k)
    t = logits.shape[0]
    dispatch = jnp.zeros((t, n_experts, capacity), jnp.float32)
    combine = jnp.zeros((t, n_experts, capacity), jnp.float32)
    for expert_idx, pos, gate, keep in choices:
        onehot_e = jax.nn.one_hot(expert_idx, n_experts, dtype=jnp.float32)
        onehot_c = jax.nn.one_hot(
            jnp.where(keep, pos, capacity), capacity + 1, dtype=jnp.float32
        )[:, :capacity]
        disp_j = onehot_e[:, :, None] * onehot_c[:, None, :]
        dispatch = dispatch + disp_j
        combine = combine + disp_j * gate[:, None, None]
    return dispatch, combine, probs, idx


def load_balancing_loss(probs, idx, n_experts: int):
    """Switch aux loss: E · Σ_e f_e · P_e (uniform routing → 1.0)."""
    f = jnp.mean(jax.nn.one_hot(idx, n_experts, dtype=jnp.float32), axis=0)
    p = jnp.mean(probs, axis=0)
    return n_experts * jnp.sum(f * p)


# -- scatter-free dispatch/combine (custom VJPs) ----------------------------------
#
# Dispatch (seats ← tokens) and combine (tokens ← seats) are inverse
# permutations of each other, so each one's transpose is the OTHER's
# gather. XLA's autodiff would emit a d-wide scatter-add for every
# gather's backward instead; on v5e scatters are the single most
# lane-inefficient op in this layer (measured r5: the fwd+bwd layer
# drops 27.0 → 24.0 ms when both backwards become gathers). The only
# scatters left in the hot path are the two int32/f32 seat-table builds.


@jax.custom_vjp
def _dispatch_gather(x_pad, seat_tok, all_slots, keep_mask):
    """slots[s] = x_pad[seat_tok[s]] — [S, d] seat rows from [T+1, d]."""
    return jnp.take(x_pad, seat_tok, axis=0)


def _dispatch_fwd(x_pad, seat_tok, all_slots, keep_mask):
    return jnp.take(x_pad, seat_tok, axis=0), (all_slots, keep_mask)


def _dispatch_bwd(res, dslots):
    # dx[t] = Σ_j dslots[slot(t, j)] over kept choices — the combine-side
    # gather (seats are unique per token-choice, so this IS the full
    # transpose, no collisions dropped).
    all_slots, keep_mask = res
    n_seats = dslots.shape[0]
    dpad = jnp.concatenate(
        [dslots, jnp.zeros((1, dslots.shape[1]), dslots.dtype)], axis=0)
    contrib = jnp.take(
        dpad, jnp.where(keep_mask, all_slots, n_seats), axis=0)  # [T, k, d]
    dx_tok = contrib.sum(axis=1)
    dx = jnp.concatenate(
        [dx_tok, jnp.zeros((1, dx_tok.shape[1]), dx_tok.dtype)], axis=0)
    return (dx, None, None, None)


_dispatch_gather.defvjp(_dispatch_fwd, _dispatch_bwd)


@jax.custom_vjp
def _combine_gather(out_flat, all_slots, all_scales, keep_mask, seat_tok,
                    seat_scale):
    """y[t] = Σ_j out_flat[slot(t, j)] · scale(t, j) — [T, d].

    ``keep_mask`` is the router's boolean keep decision per (token,
    choice) — NOT derivable from ``all_scales > 0``: a kept second choice
    whose renormalized gate underflows to exactly 0.0 is still routed (its
    slot is valid) and must keep its true gate gradient.
    """
    g = jnp.take(out_flat, jnp.where(keep_mask, all_slots, 0), axis=0)
    return (g * all_scales[..., None].astype(out_flat.dtype)).sum(axis=1)


def _combine_fwd(out_flat, all_slots, all_scales, keep_mask, seat_tok,
                 seat_scale):
    y = _combine_gather(out_flat, all_slots, all_scales, keep_mask, seat_tok,
                        seat_scale)
    return y, (out_flat, all_slots, all_scales, keep_mask, seat_tok,
               seat_scale)


def _combine_bwd(res, dy):
    out_flat, all_slots, all_scales, keep_mask, seat_tok, seat_scale = res
    t = dy.shape[0]
    # dout[s] = dy[seat_tok[s]] · seat_scale[s] — the dispatch-side
    # gather (empty seats carry scale 0; their seat_tok points at the
    # pad row, which the zero scale kills anyway).
    dy_pad = jnp.concatenate(
        [dy, jnp.zeros((1, dy.shape[1]), dy.dtype)], axis=0)
    dout = jnp.take(dy_pad, seat_tok, axis=0) \
        * seat_scale[:, None].astype(dy.dtype)
    # Gate gradient — the router's learning signal: dscale[t, j] =
    # ⟨dy[t], out_flat[slot(t, j)]⟩ (one more gather; still no scatter).
    # Masked on the router's KEEP flags, not on all_scales > 0: a kept
    # expert whose renormalized gate underflowed to 0.0 contributes
    # nothing to y, but d y / d gate is its expert output — zeroing it
    # would freeze that gate at 0 forever.
    g = jnp.take(out_flat, jnp.where(keep_mask, all_slots, 0), axis=0)
    dscale = (g.astype(jnp.float32) * dy[:, None, :].astype(jnp.float32)
              ).sum(axis=-1)
    dscale = jnp.where(keep_mask, dscale, 0.0)
    return (dout, None, dscale, None, None, None)


_combine_gather.defvjp(_combine_fwd, _combine_bwd)


def moe_ffn_local(x, router_w, expert_w1, expert_w2, axis_name: str,
                  capacity_factor: float = 1.25, router_top_k: int = 1):
    """Per-shard switch/top-k FF layer. Call inside ``shard_map``.

    Args:
      x: ``[T, d]`` this shard's tokens.
      router_w: ``[d, E_global]`` replicated router.
      expert_w1: ``[E_local, d, ff]`` this shard's experts.
      expert_w2: ``[E_local, ff, d]``.
    Returns ``(y [T, d], aux_loss scalar)``.
    """
    p_e = jax.lax.psum(1, axis_name)
    e_local = expert_w1.shape[0]
    n_experts = e_local * p_e
    t, d = x.shape
    capacity = max(1, int(capacity_factor * router_top_k * t / n_experts))

    logits = (x @ router_w.astype(x.dtype)).astype(jnp.float32)  # [T, E]
    choices, probs, idx = router_slots(
        logits, n_experts, capacity, k=router_top_k
    )
    aux = load_balancing_loss(probs, idx, n_experts)

    # Sparse dispatch by seat inversion: scatter only int32 token ids
    # into a seat→token table (seats are unique per token-choice by
    # construction), then GATHER the [E·C, d] slot rows from x. Measured
    # on v5e at the bench shape: the standalone layer runs ~3× faster
    # fwd+bwd than the d-wide scatter-add (51 → 17 ms — XLA combines
    # wide row-updates serially). The dense one-hot einsum form
    # ([T,E,C]×[T,d]) is worse than either: 2·T·(E·C)·d FLOPs ≈ the
    # expert FF itself when E·C ≈ cf·k·T. Empty seats point at a zero
    # pad row; overflow hits the drop bucket. All k choices go through
    # ONE scatter and ONE combine gather ([T, k] indices) rather than k
    # of each — measured r5: 27.5 → 26.2 ms fwd+bwd for the bare layer.
    seat_tok = jnp.full((n_experts * capacity + 1,), t, jnp.int32)
    tok_ids = jnp.arange(t, dtype=jnp.int32)
    slot_k, scale_k, keep_k = [], [], []
    for expert_idx, pos, gate, keep in choices:
        slot_k.append(jnp.where(keep, expert_idx * capacity + pos,
                                n_experts * capacity))
        scale_k.append(gate * keep)
        keep_k.append(keep)
    all_slots = jnp.stack(slot_k, axis=1)                  # [T, k]
    all_scales = jnp.stack(scale_k, axis=1)                # [T, k] f32
    # The router's boolean keep decision, threaded through dispatch AND
    # combine: ``all_scales > 0`` is NOT equivalent — a kept choice whose
    # renormalized gate underflows to 0.0 still occupies its seat and must
    # keep its gate gradient (see _combine_bwd).
    keep_mask = jnp.stack(keep_k, axis=1)                  # [T, k] bool
    seat_tok = seat_tok.at[all_slots.reshape(-1)].set(
        jnp.repeat(tok_ids, len(choices)), mode="drop")
    # Per-seat gates for the combine transpose (drop-bucket writes land
    # on the sliced-off pad row).
    seat_scale = jnp.zeros((n_experts * capacity + 1,), jnp.float32) \
        .at[all_slots.reshape(-1)].set(all_scales.reshape(-1), mode="drop")
    x_pad = jnp.concatenate([x, jnp.zeros((1, d), x.dtype)], axis=0)
    slots = _dispatch_gather(x_pad, seat_tok[:-1], all_slots, keep_mask) \
        .reshape(n_experts, capacity, d)
    # a2a #1: scatter the E dim across expert shards, gather slots — each
    # shard now holds every data-peer's tokens for ITS experts:
    # [E, C, d] → [E_local, P·C, d]. Skipped when the expert axis is 1:
    # the collective is an identity there, but XLA still materializes its
    # copies (~0.3 ms/layer at bench shapes); multi-shard meshes (the
    # 8-device dryrun gate) always take it.
    if p_e > 1:
        slots = sections.collective(
            "moe_dispatch_all_to_all", jax.lax.all_to_all,
            slots, axis_name=axis_name, split_axis=0, concat_axis=1,
            tiled=True,
        )

    h = jnp.einsum("ecd,edf->ecf", slots, expert_w1.astype(x.dtype))
    h = jax.nn.gelu(h)
    out = jnp.einsum("ecf,efd->ecd", h, expert_w2.astype(x.dtype))

    # a2a #2: route results back to their data shards.
    if p_e > 1:
        out = sections.collective(
            "moe_combine_all_to_all", jax.lax.all_to_all,
            out, axis_name=axis_name, split_axis=1, concat_axis=0,
            tiled=True,
        )
    # Sparse combine: one gather of every token's k slot rows, scaled by
    # the (renormalized) gates; dropped tokens contribute zeros and ride
    # the residual connection upstream.
    out_flat = out.reshape(n_experts * capacity, d)
    y = _combine_gather(out_flat, all_slots, all_scales, keep_mask,
                        seat_tok[:-1], seat_scale[:-1])
    return y, aux


def moe_ffn(x, router_w, expert_w1, expert_w2, mesh,
            expert_axis: str = "expert", capacity_factor: float = 1.25,
            router_top_k: int = 1):
    """GSPMD entrypoint. ``x [batch, seq, d]`` batch-sharded over all mesh
    axes; experts sharded over ``expert_axis``. Returns ``(y, aux)``."""
    from jax.sharding import PartitionSpec as P

    from kubeflow_tpu.parallel.mesh import shard_map_compat

    batch_axes = tuple(mesh.axis_names)

    def local(x, rw, w1, w2):
        b, s, d = x.shape
        y, aux = moe_ffn_local(
            x.reshape(b * s, d), rw, w1, w2, expert_axis,
            capacity_factor=capacity_factor, router_top_k=router_top_k,
        )
        return y.reshape(b, s, d), jax.lax.pmean(
            aux, tuple(mesh.axis_names)
        )

    return shard_map_compat(
        local,
        mesh=mesh,
        in_specs=(
            P(batch_axes, None, None),
            P(),                           # router replicated
            P(expert_axis, None, None),    # experts sharded
            P(expert_axis, None, None),
        ),
        out_specs=(P(batch_axes, None, None), P()),
    )(x, router_w, expert_w1, expert_w2)
