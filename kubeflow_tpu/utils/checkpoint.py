"""Compatibility shim: the checkpoint stack moved to
:mod:`kubeflow_tpu.checkpoint` (the fabric absorbed this module).
Import :class:`CheckpointManager` from there; existing
``kubeflow_tpu.utils`` imports keep working through this re-export."""

from kubeflow_tpu.checkpoint.manager import CheckpointManager

__all__ = ["CheckpointManager"]
