"""In-notebook utilities that complete the product story around the
control plane: checkpoint/resume (Orbax) for the workloads the notebooks
run. The controllers stay unchanged — persistence is PVCs + object
storage (SURVEY.md §5 checkpoint/resume)."""

from kubeflow_tpu.utils.checkpoint import CheckpointManager

__all__ = ["CheckpointManager"]
