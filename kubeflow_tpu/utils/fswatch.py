"""Event-driven config-file watching (native inotify, polling fallback).

The reference hot-reloads its mounted namespace-labels file via fsnotify
(profile_controller.go:368-399). Here the same capability is a small C
library (native/fswatch.c, inotify on the file's directory — ConfigMap
updates are ..data symlink swaps, which never fire IN_MODIFY on the file
itself) loaded through ctypes. When the prebuilt library is missing it is
compiled once into a fresh private mkdtemp (never a fixed world-writable
path), off the event loop; failing that, ``FileWatcher`` degrades to mtime
polling with the same interface, so callers never branch.
"""

from __future__ import annotations

import asyncio
import ctypes
import logging
import os
import subprocess
import tempfile
import threading

log = logging.getLogger(__name__)

_SOURCE = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "native", "fswatch.c",
)
# Only the package-adjacent prebuilt library is loaded from a fixed path
# (shipped in the image via native/Makefile). The compile fallback goes to
# a per-process private directory — loading/building at a predictable
# world-writable location like /tmp/libkfswatch.so would let any local
# user plant code that runs with the controller's credentials.
_PREBUILT = os.path.join(os.path.dirname(_SOURCE), "libkfswatch.so")

_lib = None
_lib_lock = threading.Lock()
_lib_tried = False


def _load_library():
    """Load (compiling on first use) libkfswatch; None on failure.

    Blocking (compiler invocation up to 60 s) — call off the event loop.
    """
    global _lib, _lib_tried
    with _lib_lock:
        if _lib_tried:
            return _lib
        _lib_tried = True
        try:
            _lib = _bind(ctypes.CDLL(_PREBUILT))
            return _lib
        except OSError:
            pass
        try:
            build_dir = tempfile.mkdtemp(prefix="kfswatch-")
            target = os.path.join(build_dir, "libkfswatch.so")
            subprocess.run(
                ["cc", "-O2", "-fPIC", "-shared", "-o", target, _SOURCE],
                check=True, capture_output=True, timeout=60,
            )
            _lib = _bind(ctypes.CDLL(target))
            # Linux keeps the mapping alive after unlink — clean the temp
            # dir now so crash-looping processes don't accumulate them.
            try:
                os.unlink(target)
                os.rmdir(build_dir)
            except OSError:
                pass
        except (OSError, subprocess.SubprocessError) as e:
            log.debug("native fswatch unavailable (%s); falling back to polling", e)
            _lib = None
        return _lib


def _bind(lib):
    lib.kfs_watch_open.argtypes = [ctypes.c_char_p]
    lib.kfs_watch_open.restype = ctypes.c_int
    lib.kfs_watch_wait.argtypes = [ctypes.c_int, ctypes.c_int]
    lib.kfs_watch_wait.restype = ctypes.c_int
    lib.kfs_watch_close.argtypes = [ctypes.c_int]
    lib.kfs_watch_close.restype = None
    return lib


class FileWatcher:
    """Watch one file for changes; ``await wait(timeout)`` → bool changed.

    Change detection is always mtime-based (inotify events are for the
    whole directory, and a symlink swap may touch sibling files); the
    native layer only turns the poll cadence into an event-driven wakeup
    with sub-second latency. Native setup (library load, possibly a
    compile) happens lazily inside the first ``wait`` on an executor
    thread, so constructing a watcher never blocks the event loop.
    """

    def __init__(self, path: str):
        self.path = path
        self._last = self._mtime()
        self._fd: int | None = None
        self._setup_done = False
        # Close/wait/setup coordination: the fd may only be closed when no
        # executor thread is inside kfs_watch_wait (the kernel could
        # reassign the number under a blocked poll), and close() must not
        # block the event loop waiting for that poll — so the closing
        # thread hands the actual close() off to whichever side holds the
        # fd last (_closing flag).
        self._io_lock = threading.Lock()
        self._in_wait = 0  # count of executor threads inside kfs_watch_wait
        self._closing = False

    @property
    def native(self) -> bool:
        return self._fd is not None

    def _setup_native(self) -> None:
        """Runs on an executor thread (may compile the library)."""
        lib = _load_library()
        if lib is None:
            return
        fd = lib.kfs_watch_open(os.path.dirname(self.path).encode() or b".")
        if fd < 0:
            log.debug("inotify watch failed for %s; polling", self.path)
            return
        with self._io_lock:
            if self._closing:
                lib.kfs_watch_close(fd)  # close() raced the lazy setup
            else:
                self._fd = fd

    def _mtime(self):
        """Change signature: (inode, mtime, size), not mtime alone — a
        ConfigMap-style symlink swap always changes the resolved inode,
        but the old and new targets can carry the SAME mtime when they
        were written within one filesystem timestamp tick (tmpfs clock
        granularity), which made swap detection racy."""
        try:
            st = os.stat(self.path)
            return (st.st_ino, st.st_mtime_ns, st.st_size)
        except OSError:
            return None

    def _changed(self) -> bool:
        now = self._mtime()
        if now != self._last:
            self._last = now
            return True
        return False

    def _wait_native(self, timeout_ms: int) -> int:
        with self._io_lock:
            if self._fd is None or self._closing:
                return 0
            self._in_wait += 1
            fd = self._fd
        try:
            return _load_library().kfs_watch_wait(fd, timeout_ms)
        finally:
            with self._io_lock:
                self._in_wait -= 1
                if self._closing and self._in_wait == 0 and self._fd is not None:
                    _load_library().kfs_watch_close(self._fd)
                    self._fd = None

    async def wait(self, timeout: float = 2.0) -> bool:
        """Wait up to ``timeout`` seconds for a change to ``path``."""
        loop = asyncio.get_running_loop()
        if not self._setup_done:
            self._setup_done = True
            await loop.run_in_executor(None, self._setup_native)
        if self._fd is not None:
            await loop.run_in_executor(
                None, self._wait_native, int(timeout * 1000)
            )
        else:
            await asyncio.sleep(timeout)
        return self._changed()

    def close(self) -> None:
        """Non-blocking: if a wait is in flight on an executor thread, that
        thread performs the actual fd close when its poll returns."""
        with self._io_lock:
            self._closing = True
            if self._in_wait == 0 and self._fd is not None:
                _load_library().kfs_watch_close(self._fd)
                self._fd = None
