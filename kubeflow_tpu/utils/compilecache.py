"""Persistent XLA compilation cache wiring + warm-pool cache seeding.

Cold start on TPU is compile-dominated (measured: ~12 s AOT compile +
~15 s jitted init for the bench model, docs/perf.md). JAX ships a
persistent compilation cache keyed on the HLO + compile options + libtpu
version; pointing it at a directory that outlives the process turns every
repeat compile into a disk read. This module is the one place that knows
where that directory lives:

- **Notebook images**: `$KFTPU_COMPILE_CACHE_DIR` defaults to
  ``~/.cache/jax_compile`` — on the workspace PVC, so the cache survives
  stop/start cycles and slice-atomic restarts (the controller's stop
  semantics keep the PVC; SURVEY.md §5 checkpoint/resume). Exported by
  the jupyter-jax image (images/jupyter-jax/Dockerfile).
- **bench.py / local runs**: a repo-local ``.jax_cache/`` (gitignored).
- **Warm-pool pods** (ISSUE 14): the SDK warm-idle loop calls
  :func:`seed_cache` before parking, copying common program fingerprints
  from ``$KFTPU_COMPILE_CACHE_SEED_DIR`` (baked into the image or mounted
  from a shared volume) into the live cache dir — the first user step in
  a claimed pod then pays a disk read, not an XLA compile.

Failure semantics (ISSUE 14 satellite): cache-dir setup failures used to
be silent in the in-pod path — they are now logged ONCE per directory,
counted in ``compile_cache_setup_failures_total``, and surfaced through
:func:`cache_dir_ready`, the flag the seeder and readiness probes assert
on. Cache effectiveness is observable through :func:`note_compile`'s
hit/miss counters (an unchanged entry count across a compile = a hit).

No reference counterpart: the reference's images have no accelerator
runtime to cache for (its CUDA images pay framework JIT costs elsewhere).
"""

from __future__ import annotations

import json
import logging
import os
import shutil

log = logging.getLogger(__name__)

ENV_VAR = "KFTPU_COMPILE_CACHE_DIR"
SEED_DIR_ENV = "KFTPU_COMPILE_CACHE_SEED_DIR"
DEFAULT_IMAGE_DIR = "~/.cache/jax_compile"

# Optional manifest file inside a seed dir: a JSON list of entry file
# names to copy (a subset pin). Absent → every regular file seeds.
SEED_MANIFEST = "manifest.json"

# Module-level counters (the in-pod path must not require the metrics
# registry); mirrored into Prometheus lazily when the registry imports.
_counters = {"setup_failures": 0, "hits": 0, "misses": 0, "seeded": 0}
_setup_failed_dirs: set[str] = set()


def _prom_inc(name: str, help_: str) -> None:
    """Best-effort Prometheus mirror — the warm-idle loop and probes run
    in pods that may not serve /metrics; the module counters stay the
    source of truth either way."""
    try:
        from kubeflow_tpu.runtime.metrics import global_registry

        global_registry.counter(name, help_).inc()
    except Exception:  # kftpu: ignore[exception-swallow] metrics are a mirror; the module counter above already recorded the event
        pass


def setup_failures_total() -> int:
    return _counters["setup_failures"]


def cache_stats() -> dict:
    """Snapshot of the module counters (probes / bench attribution)."""
    return dict(_counters)


def default_cache_dir() -> str:
    return os.path.expanduser(os.environ.get(ENV_VAR) or DEFAULT_IMAGE_DIR)


def cache_entries(cache_dir: str | None = None) -> int:
    """Number of cached executables (0 for a missing/empty dir)."""
    d = cache_dir or default_cache_dir()
    try:
        return sum(1 for e in os.scandir(d) if e.is_file())
    except OSError:
        return 0


def cache_dir_ready(cache_dir: str | None = None) -> bool:
    """Is the cache directory usable (exists and writable)? The flag the
    warm-pool seeder and readiness probes assert on before promising a
    warm compile phase."""
    d = os.path.abspath(cache_dir or default_cache_dir())
    return os.path.isdir(d) and os.access(d, os.W_OK)


def enable_persistent_cache(cache_dir: str | None = None) -> str:
    """Point JAX's persistent compilation cache at ``cache_dir``.

    Idempotent; creates the directory. Must run before the first
    compilation (config flips after a compile don't retro-cache it).
    Returns the resolved directory.

    A directory that cannot be created/written (read-only image fs,
    broken PVC mount) no longer fails silently — or fatally: it is
    logged once, counted in ``compile_cache_setup_failures_total``, and
    the jax config is left untouched (compiles run uncached rather than
    erroring per-compile against a dead dir). ``cache_dir_ready``
    reports the outcome."""
    d = os.path.abspath(cache_dir or default_cache_dir())
    try:
        os.makedirs(d, exist_ok=True)
        if not os.access(d, os.W_OK):
            raise OSError(f"{d} is not writable")
    except OSError as e:
        _counters["setup_failures"] += 1
        _prom_inc("compile_cache_setup_failures_total",
                  "Compile-cache directory setup failures")
        if d not in _setup_failed_dirs:
            _setup_failed_dirs.add(d)
            log.error(
                "compile cache dir %s unusable (%s): compiles will run "
                "UNCACHED — cold-start compile savings are off until the "
                "mount/permissions are fixed", d, e)
        return d
    import jax

    jax.config.update("jax_compilation_cache_dir", d)
    # Cache everything: the default 1 s floor skips the many small
    # programs (init, host transfers) whose compiles still add up through
    # a remote relay, and the size floor skips tiny executables.
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    return d


def seed_cache(seed_dir: str | None = None,
               cache_dir: str | None = None) -> dict:
    """Pre-populate the compile cache from a manifest of common program
    fingerprints (ISSUE 14): copy every entry in ``seed_dir`` (default
    ``$KFTPU_COMPILE_CACHE_SEED_DIR``) into the live cache dir, skipping
    entries already present — first user steps in a warm-pool pod then
    hit the cache instead of paying an XLA compile. A ``manifest.json``
    (JSON list of file names) inside the seed dir pins the subset;
    absent, every regular file seeds.

    Returns ``{"seeded": n, "skipped": n, "ready": bool}``; a missing/
    unconfigured seed dir is a clean no-op (``seeded=0``), a broken
    CACHE dir is reported via ``ready=False`` (and was already counted
    by :func:`enable_persistent_cache`)."""
    src = seed_dir or os.environ.get(SEED_DIR_ENV)
    dst = os.path.abspath(cache_dir or default_cache_dir())
    out = {"seeded": 0, "skipped": 0, "ready": cache_dir_ready(dst)}
    if not src or not os.path.isdir(src) or not out["ready"]:
        return out
    names = None
    manifest = os.path.join(src, SEED_MANIFEST)
    if os.path.isfile(manifest):
        try:
            with open(manifest, encoding="utf-8") as fh:
                listed = json.load(fh)
            if isinstance(listed, list):
                names = {str(n) for n in listed}
        except (OSError, ValueError):
            log.warning("unreadable seed manifest %s; seeding every "
                        "entry in %s", manifest, src)
    try:
        entries = [e for e in os.scandir(src)
                   if e.is_file() and e.name != SEED_MANIFEST
                   and (names is None or e.name in names)]
    except OSError:
        return out
    for entry in entries:
        target = os.path.join(dst, entry.name)
        if os.path.exists(target):
            out["skipped"] += 1
            continue
        try:
            shutil.copyfile(entry.path, target)
        except OSError:
            out["ready"] = cache_dir_ready(dst)
            continue
        out["seeded"] += 1
    _counters["seeded"] += out["seeded"]
    return out


def note_compile(entries_before: int, entries_after: int) -> str:
    """Classify one compile against the cache and count it: an unchanged
    entry count means the executable came FROM the cache (hit); a grown
    count means XLA compiled and the result was written (miss — warm for
    next time). Surfaced per phase by the bench's fresh-process probe."""
    if entries_after <= entries_before:
        _counters["hits"] += 1
        _prom_inc("compile_cache_hits_total",
                  "Compiles served from the persistent cache")
        return "hit"
    _counters["misses"] += 1
    _prom_inc("compile_cache_misses_total",
              "Compiles that missed the persistent cache")
    return "miss"
