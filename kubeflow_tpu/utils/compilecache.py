"""Persistent XLA compilation cache wiring.

Cold start on TPU is compile-dominated (measured: ~12 s AOT compile +
~15 s jitted init for the bench model, docs/perf.md). JAX ships a
persistent compilation cache keyed on the HLO + compile options + libtpu
version; pointing it at a directory that outlives the process turns every
repeat compile into a disk read. This module is the one place that knows
where that directory lives:

- **Notebook images**: `$KFTPU_COMPILE_CACHE_DIR` defaults to
  ``~/.cache/jax_compile`` — on the workspace PVC, so the cache survives
  stop/start cycles and slice-atomic restarts (the controller's stop
  semantics keep the PVC; SURVEY.md §5 checkpoint/resume). Exported by
  the jupyter-jax image (images/jupyter-jax/Dockerfile).
- **bench.py / local runs**: a repo-local ``.jax_cache/`` (gitignored).

No reference counterpart: the reference's images have no accelerator
runtime to cache for (its CUDA images pay framework JIT costs elsewhere).
"""

from __future__ import annotations

import os

ENV_VAR = "KFTPU_COMPILE_CACHE_DIR"
DEFAULT_IMAGE_DIR = "~/.cache/jax_compile"


def default_cache_dir() -> str:
    return os.path.expanduser(os.environ.get(ENV_VAR) or DEFAULT_IMAGE_DIR)


def cache_entries(cache_dir: str | None = None) -> int:
    """Number of cached executables (0 for a missing/empty dir)."""
    d = cache_dir or default_cache_dir()
    try:
        return sum(1 for e in os.scandir(d) if e.is_file())
    except OSError:
        return 0


def enable_persistent_cache(cache_dir: str | None = None) -> str:
    """Point JAX's persistent compilation cache at ``cache_dir``.

    Idempotent; creates the directory. Must run before the first
    compilation (config flips after a compile don't retro-cache it).
    Returns the resolved directory.
    """
    import jax

    d = os.path.abspath(cache_dir or default_cache_dir())
    os.makedirs(d, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", d)
    # Cache everything: the default 1 s floor skips the many small
    # programs (init, host transfers) whose compiles still add up through
    # a remote relay, and the size floor skips tiny executables.
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    return d
