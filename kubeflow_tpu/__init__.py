"""kubeflow_tpu — a TPU-native Kubernetes notebook/workbench control plane.

A from-scratch rebuild of the Kubeflow Notebooks stack (reference:
rhoai-ide-konflux/kubeflow) with TPU as the first-class accelerator:

- ``kubeflow_tpu.tpu``         — pure TPU topology library (slices, hosts, env wiring)
- ``kubeflow_tpu.api``         — CRD types: Notebook, Profile, PodDefault, Tensorboard, PVCViewer
- ``kubeflow_tpu.runtime``     — controller runtime (client, informers, workqueue, manager)
- ``kubeflow_tpu.controllers`` — reconcilers (notebook, culling, profile, tensorboard, pvcviewer)
- ``kubeflow_tpu.webhooks``    — admission layer (PodDefault mutator, notebook mutator, defaulters)
- ``kubeflow_tpu.apps``        — CRUD web-app backends (jupyter, tensorboards, volumes), KFAM, dashboard
- ``kubeflow_tpu.models``      — slice-validation workloads (sharded transformer burn-in)
- ``kubeflow_tpu.ops``         — TPU compute ops (collectives probes, pallas kernels)
- ``kubeflow_tpu.parallel``    — mesh/sharding helpers for multi-host slices
- ``kubeflow_tpu.testing``     — fake kube-apiserver (envtest equivalent) + fake TPU runtime
"""

__version__ = "0.1.0"
