"""Preempt-to-checkpoint migration (ISSUE 7).

One drain protocol spanning scheduler → controller → pod/SDK turns every
"this gang must stop" decision — fleet preemption, idle culling, user
suspend — into checkpoint-then-park instead of a bare kill, and every
re-admission into a restore:

    Running → DrainRequested → Checkpointing → Checkpointed → Parked
                                                   │
                                  Restoring ◄──────┘ (re-admission)
                                      │
                                   Running

:mod:`kubeflow_tpu.migration.protocol` is the pure core: state
derivation from CR annotations, deadline math, and the patch shapes
every participant uses. The scheduler's runtime, the notebook
controller, the culler, and the in-pod SDK all import from here so the
wire contract cannot drift between layers.

Kill switches: ``KFTPU_MIGRATION=off`` restores the pre-migration
immediate stop everywhere; ``KFTPU_CULL_DRAIN=off`` restores bare-stop
culling only. ``KFTPU_DRAIN_GRACE`` bounds how long chips wait on a
checkpoint — a victim that cannot ack within it is hard-stopped exactly
as before (chips are never held hostage).
"""

from __future__ import annotations

from kubeflow_tpu.migration.protocol import (  # noqa: F401
    CHECKPOINTED,
    CHECKPOINTING,
    DEFAULT_DRAIN_GRACE_SECONDS,
    DRAIN_REQUESTED,
    PARKED,
    RESTORING,
    RUNNING,
    ack_patch,
    checkpoint_step,
    checkpointed_at,
    clear_drain_patch,
    cull_drain_enabled,
    derive_state,
    drain_acked,
    drain_deadline,
    drain_expired,
    drain_grace_seconds,
    drain_reason,
    drain_requested_at,
    migration_enabled,
    request_drain_patch,
    restore_hint,
)
