"""Pure core of the drain/checkpoint/restore protocol.

Everything here is a function of (annotations, now) — no I/O, no clock
reads — so the scheduler, the notebook controller, the culler, the SDK,
and tier-1 can all reason about the same state machine without an event
loop. The durable state lives in CR annotations (api/notebook.py), which
is what makes the protocol survive controller restarts and reach the pod
through the SDK's in-cluster CR fetch.

State machine (derive_state)::

    Running ──drain requested──► DrainRequested
                                      │ SDK stamps checkpointing-at
                                      ▼
                                 Checkpointing
                                      │ SDK stamps checkpointed-at (+path/step)
                                      ▼
                                 Checkpointed ──finalizer stops the CR──► Parked
                                      │
        Running ◄── all workers ready ── Restoring ◄── re-admitted with a
                                                       restore hint

A drain that outlives ``KFTPU_DRAIN_GRACE`` falls back to today's hard
stop: the finalizer (scheduler/culler/controller — identified by the
``drain-reason`` prefix it stamped) clears the drain marks and stops the
CR without a checkpoint. Chips are never held hostage to a wedged pod.

Drain reasons and their finalizers: ``preempt:idle``/``preempt:priority``
(scheduler preemption — park until user restart), ``spot-reclaim`` and
``defrag`` (elastic fleet, kubeflow_tpu/scheduler/elastic.py — park,
then auto-re-queue at original priority with aging credit), ``cull``
(idle culler), ``suspend`` (user/controller).
"""

from __future__ import annotations

import os

from kubeflow_tpu.api import notebook as nbapi
from kubeflow_tpu.runtime.objects import fmt_iso, parse_iso

# Derived lifecycle states (status.migration.state + /debug rows).
RUNNING = "Running"
DRAIN_REQUESTED = "DrainRequested"
CHECKPOINTING = "Checkpointing"
CHECKPOINTED = "Checkpointed"
PARKED = "Parked"
RESTORING = "Restoring"

DEFAULT_DRAIN_GRACE_SECONDS = 120.0

# Restore hint env the controller stamps into the pod template; the SDK's
# CheckpointManager/notebook code reads these to resume where it left off.
RESTORE_PATH_ENV = "KFTPU_RESTORE_CHECKPOINT_PATH"
RESTORE_STEP_ENV = "KFTPU_RESTORE_STEP"

# Knobs (docs/operations.md "Preempt-to-checkpoint migration"):
MIGRATION_ENV = "KFTPU_MIGRATION"
CULL_DRAIN_ENV = "KFTPU_CULL_DRAIN"
DRAIN_GRACE_ENV = "KFTPU_DRAIN_GRACE"
COMMIT_GRACE_ENV = "KFTPU_COMMIT_GRACE"


def migration_enabled(environ=os.environ) -> bool:
    """``KFTPU_MIGRATION`` master switch — anything but off/false/0/no
    leaves the drain protocol on. Off restores the pre-migration
    immediate stop on every path (preemption, culling, suspend)."""
    return environ.get(MIGRATION_ENV, "on").strip().lower() not in (
        "off", "false", "0", "no", "disabled",
    )


def cull_drain_enabled(environ=os.environ) -> bool:
    """``KFTPU_CULL_DRAIN`` — culling-only kill switch layered under the
    master one: off restores the bare idle-cull stop while preemption
    keeps draining."""
    return environ.get(CULL_DRAIN_ENV, "on").strip().lower() not in (
        "off", "false", "0", "no", "disabled",
    )


def drain_grace_seconds(environ=os.environ) -> float:
    """``KFTPU_DRAIN_GRACE`` — seconds a drain may hold chips before the
    hard-stop fallback fires."""
    raw = environ.get(DRAIN_GRACE_ENV)
    try:
        value = float(raw) if raw is not None else DEFAULT_DRAIN_GRACE_SECONDS
    except ValueError:
        return DEFAULT_DRAIN_GRACE_SECONDS
    return value if value > 0 else DEFAULT_DRAIN_GRACE_SECONDS


def commit_grace_seconds(environ=os.environ) -> float:
    """``KFTPU_COMMIT_GRACE`` — seconds after the snapshot ack the
    background upload may take before the park is marked commit-dirty
    and the drain counted as a fallback. Defaults to the drain grace:
    the upload gets the same patience the snapshot did."""
    raw = environ.get(COMMIT_GRACE_ENV)
    try:
        value = float(raw) if raw is not None else 0.0
    except ValueError:
        value = 0.0
    return value if value > 0 else drain_grace_seconds(environ)


# ---- annotation readers --------------------------------------------------------


def drain_requested_at(annotations: dict) -> float | None:
    return parse_iso(
        annotations.get(nbapi.DRAIN_REQUESTED_ANNOTATION) or "")


def drain_reason(annotations: dict) -> str:
    return annotations.get(nbapi.DRAIN_REASON_ANNOTATION) or ""


def checkpointed_at(annotations: dict) -> float | None:
    return parse_iso(
        annotations.get(nbapi.CHECKPOINTED_AT_ANNOTATION) or "")


def checkpoint_step(annotations: dict) -> int | None:
    raw = annotations.get(nbapi.CHECKPOINT_STEP_ANNOTATION)
    try:
        return int(raw) if raw is not None else None
    except ValueError:
        return None


def drain_acked(annotations: dict) -> bool:
    """Has the SDK committed a checkpoint for the CURRENT drain? The
    primary signal is the echo: the ack's ``checkpointed-for`` carries
    the raw drain-requested value it answers, so the comparison never
    involves two clocks (the controller stamps the request, the pod
    stamps the ack — skew between them must not make acks invisible or a
    stale checkpoint look fresh). The timestamp ordering remains as a
    fallback for acks stamped without the echo — but only alongside a
    ``checkpointing-at`` progress mark, which every drain request CLEARS
    and the SDK re-stamps when it starts saving for that drain: the
    checkpoint path/step/commit-time survive re-admission as the restore
    hint, and with second-granularity timestamps a surviving old commit
    could otherwise instant-"ack" a new drain issued in the same second
    (rapid spot-reclaim cycles hit exactly this)."""
    requested_raw = annotations.get(nbapi.DRAIN_REQUESTED_ANNOTATION)
    if not requested_raw:
        return False
    echo = annotations.get(nbapi.CHECKPOINTED_FOR_ANNOTATION)
    if echo is not None:
        return echo == requested_raw
    if not annotations.get(nbapi.CHECKPOINTING_AT_ANNOTATION):
        return False
    requested = drain_requested_at(annotations)
    acked = checkpointed_at(annotations)
    return requested is not None and acked is not None and acked >= requested


def drain_deadline(annotations: dict, grace: float) -> float | None:
    """When the hard-stop fallback fires (epoch seconds), or None when no
    drain is pending."""
    requested = drain_requested_at(annotations)
    return None if requested is None else requested + grace


def drain_expired(annotations: dict, now: float, grace: float) -> bool:
    deadline = drain_deadline(annotations, grace)
    return deadline is not None and now >= deadline and \
        not drain_acked(annotations)


def checkpoint_committed(annotations: dict) -> bool:
    """Has the checkpoint fabric durably committed the checkpoint for
    the CURRENT drain? Same echo discipline as :func:`drain_acked`: the
    commit's ``checkpoint-committed-for`` must carry the raw
    drain-requested value it answers, so a surviving commit mark from a
    previous park can never satisfy a new drain. With the drain marks
    already cleared (post-park), any committed-at mark counts — the
    commit outliving the drain is exactly the success case."""
    committed_raw = annotations.get(
        nbapi.CHECKPOINT_COMMITTED_AT_ANNOTATION)
    if not committed_raw:
        return False
    requested_raw = annotations.get(nbapi.DRAIN_REQUESTED_ANNOTATION)
    if not requested_raw:
        return True
    echo = annotations.get(nbapi.CHECKPOINT_COMMITTED_FOR_ANNOTATION)
    return echo == requested_raw


def commit_dirty(annotations: dict) -> bool:
    """True when a hard stop caught the upload still in flight — the
    durable 'this park's checkpoint may be stale' marker."""
    return bool(annotations.get(nbapi.CHECKPOINT_COMMIT_DIRTY_ANNOTATION))


def upload_progress(annotations: dict) -> tuple[int, int] | None:
    """(chunks done, chunks total) of the in-flight upload, or None."""
    raw = annotations.get(nbapi.CHECKPOINT_PROGRESS_ANNOTATION) or ""
    head, sep, tail = raw.partition("/")
    if not sep:
        return None
    try:
        done, total = int(head), int(tail)
    except ValueError:
        return None
    return (done, total) if total > 0 and 0 <= done <= total else None


def restore_tier(annotations: dict) -> str:
    """Which tier served the last restore ("staging" / "remote" / "")."""
    return annotations.get(nbapi.RESTORE_TIER_ANNOTATION) or ""


def restore_hint(annotations: dict) -> tuple[str, int | None] | None:
    """(checkpoint path, step) to restore from, or None. The path alone
    is enough (CheckpointManager.restore defaults to the latest step);
    the step is surfaced for status messages and determinism."""
    path = annotations.get(nbapi.CHECKPOINT_PATH_ANNOTATION)
    if not path:
        return None
    return path, checkpoint_step(annotations)


# ---- state derivation ----------------------------------------------------------


def derive_state(annotations: dict, *, stopped: bool,
                 ready_hosts: int = 0, want_hosts: int = 0) -> str:
    """The migration lifecycle state as a pure function of the CR. Only
    meaningful when migration is in play (a drain mark or a checkpoint
    exists); a plain notebook derives Running/Parked trivially.

    Parked requires BOTH a committed checkpoint and the drain-reason
    marker every drain park keeps: the checkpoint path/step annotations
    survive re-admission as the durable restore hint, so a later plain
    user stop — with no fresh checkpoint — must not present as a clean
    "Suspended (checkpoint @ step N)" park. Re-admission clears the
    reason, so only a stop that actually came from a drain qualifies."""
    if stopped:
        return PARKED if (checkpointed_at(annotations) is not None
                          and drain_reason(annotations)) else RUNNING
    if drain_requested_at(annotations) is not None:
        if drain_acked(annotations):
            return CHECKPOINTED
        if annotations.get(nbapi.CHECKPOINTING_AT_ANNOTATION):
            return CHECKPOINTING
        return DRAIN_REQUESTED
    if restore_hint(annotations) is not None and (
            want_hosts == 0 or ready_hosts < want_hosts):
        return RESTORING
    return RUNNING


# ---- patch shapes --------------------------------------------------------------
# Merge-patch annotation dicts, so every participant stamps the same keys.


def request_drain_patch(reason: str, now: float) -> dict:
    """Ask the pod to checkpoint: starts the grace clock. Stale progress
    marks from a PREVIOUS drain cycle are cleared so ack detection can't
    confuse an old checkpointing-at for fresh progress."""
    return {
        nbapi.DRAIN_REQUESTED_ANNOTATION: fmt_iso(now),
        nbapi.DRAIN_REASON_ANNOTATION: reason,
        nbapi.CHECKPOINTING_AT_ANNOTATION: None,
        # A new drain cycle starts with a clean commit slate: the
        # previous cycle's commit/dirty/progress marks must not satisfy
        # or confuse this cycle's commit wait.
        nbapi.CHECKPOINT_COMMITTED_AT_ANNOTATION: None,
        nbapi.CHECKPOINT_COMMITTED_FOR_ANNOTATION: None,
        nbapi.CHECKPOINT_COMMIT_DIRTY_ANNOTATION: None,
        nbapi.CHECKPOINT_PROGRESS_ANNOTATION: None,
    }


def ack_patch(path: str, step: int, now: float,
              *, for_request: str | None = None) -> dict:
    """The SDK's commit mark: checkpoint durable at (path, step).
    ``for_request`` echoes the raw drain-requested value being answered
    (see :func:`drain_acked` — the echo makes ack detection clock-skew
    immune); pass the annotation value the SDK read. The patch also
    (re)stamps ``checkpointing-at``: a commit implies a started save,
    and echo-less acks are only honored alongside that progress mark
    (every drain request clears it, so a pre-park checkpoint cannot
    instant-ack the next cycle's drain)."""
    patch = {
        nbapi.CHECKPOINTING_AT_ANNOTATION: fmt_iso(now),
        nbapi.CHECKPOINTED_AT_ANNOTATION: fmt_iso(now),
        nbapi.CHECKPOINT_PATH_ANNOTATION: path,
        nbapi.CHECKPOINT_STEP_ANNOTATION: str(step),
    }
    if for_request is not None:
        patch[nbapi.CHECKPOINTED_FOR_ANNOTATION] = for_request
    return patch


def commit_patch(now: float, *, for_request: str | None = None) -> dict:
    """The fabric's durable-commit mark, stamped by the SDK when the
    background uploader lands the manifest + pointer. Distinct from
    :func:`ack_patch` (the snapshot ack) — the scheduler frees chips on
    the ack but only hard-releases the restore guarantee on this.
    Clears the in-flight progress mark."""
    patch = {
        nbapi.CHECKPOINT_COMMITTED_AT_ANNOTATION: fmt_iso(now),
        nbapi.CHECKPOINT_COMMIT_DIRTY_ANNOTATION: None,
        nbapi.CHECKPOINT_PROGRESS_ANNOTATION: None,
    }
    if for_request is not None:
        patch[nbapi.CHECKPOINT_COMMITTED_FOR_ANNOTATION] = for_request
    return patch


def progress_patch(done: int, total: int) -> dict:
    """Upload progress ("k/N" chunks) for JWA's parked-uncommitted
    status message."""
    return {nbapi.CHECKPOINT_PROGRESS_ANNOTATION: f"{done}/{total}"}


def mark_commit_dirty_patch(now: float) -> dict:
    """Hard stop caught the upload in flight: the checkpoint annotations
    still point at the last *committed* step, but this cycle's upload
    never landed — mark the park dirty so status and restore policy can
    say so. Stamped by the drain finalizer alongside the fallback."""
    return {
        nbapi.CHECKPOINT_COMMIT_DIRTY_ANNOTATION: fmt_iso(now),
        nbapi.CHECKPOINT_PROGRESS_ANNOTATION: None,
    }


def restore_tier_patch(tier: str) -> dict:
    """Record which tier served a restore ("staging" / "remote") for
    JWA's restore-path status message; empty clears the mark."""
    return {nbapi.RESTORE_TIER_ANNOTATION: tier or None}


def clear_drain_patch(*, keep_checkpoint: bool = True,
                      keep_reason: bool = False) -> dict:
    """Drop the drain marks (re-admission, cancel, or hard-stop
    fallback). The checkpoint path/step survive by default — they are the
    durable restore hint; ``keep_checkpoint=False`` also drops those.
    ``keep_reason=True`` is the PARK variant: the drain-reason stays as
    the durable "this stop came from a drain" marker (derive_state's
    Parked gate and the controller's resume path key off it); it clears
    on re-admission via the default variant."""
    patch = {
        nbapi.DRAIN_REQUESTED_ANNOTATION: None,
        nbapi.CHECKPOINTING_AT_ANNOTATION: None,
        nbapi.CHECKPOINTED_FOR_ANNOTATION: None,
    }
    if not keep_reason:
        patch[nbapi.DRAIN_REASON_ANNOTATION] = None
    if not keep_checkpoint:
        patch.update({
            nbapi.CHECKPOINTED_AT_ANNOTATION: None,
            nbapi.CHECKPOINT_PATH_ANNOTATION: None,
            nbapi.CHECKPOINT_STEP_ANNOTATION: None,
            nbapi.CHECKPOINT_COMMITTED_AT_ANNOTATION: None,
            nbapi.CHECKPOINT_COMMITTED_FOR_ANNOTATION: None,
            nbapi.CHECKPOINT_COMMIT_DIRTY_ANNOTATION: None,
            nbapi.CHECKPOINT_PROGRESS_ANNOTATION: None,
        })
    return patch
