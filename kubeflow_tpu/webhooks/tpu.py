"""Per-worker TPU env injection at pod admission.

A StatefulSet template cannot vary env by ordinal, but at *pod* admission the
pod already has its final name ``<notebook>-<ordinal>`` — so this mutator is
a pure function of the pod: it reads the slice annotations the notebook
controller stamped on the template (``tpu.kubeflow.org/accelerator`` /
``tpu.kubeflow.org/topology``), parses the ordinal, and injects
``TPU_WORKER_ID`` / ``JAX_PROCESS_ID``.

This replaces the reference pattern of a PodDefault carrying static env
(SURVEY.md §2.4 row 4: "PodDefault injecting TPU_WORKER_ID…") with something
a PodDefault *cannot* express — per-ordinal values.
"""

from __future__ import annotations

import logging

from kubeflow_tpu.api.notebook import (
    TPU_ACCELERATOR_ANNOTATION,
    TPU_NUM_SLICES_ANNOTATION,
    TPU_SLICE_ID_ANNOTATION,
    TPU_TOPOLOGY_ANNOTATION,
)
from kubeflow_tpu.runtime.objects import get_meta, name_of
from kubeflow_tpu.tpu.topology import TopologyError, TpuSlice

log = logging.getLogger(__name__)


def ordinal_of(pod_name: str) -> int | None:
    base, _, ordinal = pod_name.rpartition("-")
    if base and ordinal.isdigit():
        return int(ordinal)
    return None


def mutate_pod(pod: dict) -> None:
    """Inject per-worker env into every container of an annotated TPU pod."""
    annotations = get_meta(pod).get("annotations") or {}
    accelerator = annotations.get(TPU_ACCELERATOR_ANNOTATION)
    topology = annotations.get(TPU_TOPOLOGY_ANNOTATION)
    if not accelerator or not topology:
        return
    ordinal = ordinal_of(name_of(pod))
    if ordinal is None:
        return
    try:
        tpu = TpuSlice.parse(accelerator, topology)
    except TopologyError as e:
        log.warning("pod %s: bad TPU annotations: %s", name_of(pod), e)
        return
    # Multislice: the controller stamps the slice id per StatefulSet, and
    # the global jax.distributed rank is sliceId·hostsPerSlice + ordinal
    # (tpu/topology.py MultiSlice.worker_env).
    try:
        slice_id = int(annotations.get(TPU_SLICE_ID_ANNOTATION, 0))
        num_slices = int(annotations.get(TPU_NUM_SLICES_ANNOTATION, 1))
    except ValueError:
        log.warning("pod %s: bad multislice annotations", name_of(pod))
        return
    worker_env = {
        "TPU_WORKER_ID": str(ordinal),
        "JAX_PROCESS_ID": str(slice_id * tpu.num_hosts + ordinal),
    }
    if ordinal >= tpu.num_hosts or slice_id >= num_slices:
        log.warning(
            "pod %s: ordinal %d / slice %d outside %d-host × %d-slice job",
            name_of(pod), ordinal, slice_id, tpu.num_hosts, num_slices,
        )
        return
    for ctr in pod.get("spec", {}).get("containers", []):
        env = list(ctr.get("env", []) or [])
        have = {e.get("name") for e in env}
        for k, v in worker_env.items():
            if k not in have:
                env.append({"name": k, "value": v})
            else:
                # Replace the whole entry: the controller bakes a downward
                # API valueFrom fallback into the template, and an entry
                # with both value and valueFrom is invalid.
                env = [
                    {"name": k, "value": v} if e.get("name") == k else e
                    for e in env
                ]
        ctr["env"] = env
