"""Wire every admission engine onto an apiserver admission chain.

Order matters and is fixed here (the reference's implicit multi-webhook
ordering made explicit): CR defaulting/validation first, then pod-level
PodDefault injection, then per-worker TPU env (which must see the final pod
name and the template annotations, and must win over anything a PodDefault
set for TPU_WORKER_ID).
"""

from __future__ import annotations

from kubeflow_tpu.api import notebook as nbapi
from kubeflow_tpu.api import poddefault as pdapi
from kubeflow_tpu.api import profile as profileapi
from kubeflow_tpu.api import pvcviewer as pvcapi
from kubeflow_tpu.api import tensorboard as tbapi
from kubeflow_tpu.webhooks import notebook as nb_webhook
from kubeflow_tpu.webhooks import poddefault as pd_webhook
from kubeflow_tpu.webhooks import tpu as tpu_webhook


def register_all(kube) -> None:
    """Register mutators/validators on a FakeKube-compatible admission chain.

    ``kube.add_mutator(kind_glob, fn)`` / ``add_validator`` — fns may be sync
    or async, called with (obj, request_info).
    """
    # CR defaulting (mutators run before validators). The Notebook mutator
    # also enforces restart blocking (webhooks/notebook.py).
    kube.add_mutator("Notebook", nb_webhook.mutate)

    # Image-alias resolution from the catalog ConfigMap (odh's ImageStream
    # resolution, notebook_webhook.go:539-645, without OpenShift).
    async def image_resolver(nb: dict, info: dict) -> None:
        if info.get("operation") in (None, "CREATE", "UPDATE"):
            await nb_webhook.resolve_image_from_catalog(kube, nb)

    kube.add_mutator("Notebook", image_resolver)
    kube.add_mutator("PVCViewer", lambda v, _i: pvcapi.default(v))

    # Profiles applied at an old served version are normalized to storage at
    # admission (same contract as the Notebook mutator's normalization).
    def profile_normalizer(p: dict, _info: dict) -> None:
        if p.get("apiVersion") in profileapi.SERVED_API_VERSIONS:
            p["apiVersion"] = profileapi.STORAGE_API_VERSION

    kube.add_mutator("Profile", profile_normalizer)

    # CR validation. Notebooks additionally fast-fail (CREATE only) when
    # the chip request can never fit the namespace tpuQuota ceiling or
    # the configured TPU fleet (webhooks/notebook.py validate_capacity)
    # — an impossible gang must be rejected with an actionable message,
    # not queue forever.
    async def notebook_validator(nb: dict, info: dict) -> None:
        nbapi.validate(nb)
        if info.get("operation") in (None, "CREATE"):
            await nb_webhook.validate_capacity(kube, nb)

    kube.add_validator("Notebook", notebook_validator)

    # Serving workload class (KFTPU_SERVING, kubeflow_tpu/serving): the
    # InferenceService mutator/validator register only with the switch
    # on, so =off restores the notebook-only admission chain
    # byte-for-byte. Capacity fast-fail mirrors the Notebook gate
    # (CREATE only) through the same TTL-cached Profile/fleet loaders.
    from kubeflow_tpu.serving import serving_enabled

    if serving_enabled():
        from kubeflow_tpu.webhooks import inferenceservice as isvc_webhook

        kube.add_mutator("InferenceService", isvc_webhook.mutate)

        async def isvc_validator(isvc: dict, info: dict) -> None:
            from kubeflow_tpu.api import inferenceservice as isvcapi

            isvcapi.validate(isvc)
            if info.get("operation") in (None, "CREATE"):
                await isvc_webhook.validate_capacity(kube, isvc)

        kube.add_validator("InferenceService", isvc_validator)
    kube.add_validator("PodDefault", lambda pd, _i: pdapi.validate(pd))
    kube.add_validator("Profile", lambda p, _i: profileapi.validate(p))
    kube.add_validator("Tensorboard", lambda tb, _i: tbapi.validate(tb))
    kube.add_validator("PVCViewer", lambda v, _i: pvcapi.validate(v))

    # Pod mutation: PodDefault injection, then per-worker TPU env.
    async def poddefault_mutator(pod: dict, info: dict) -> None:
        if info.get("operation") == "CREATE":
            await pd_webhook.mutate_pod(kube, pod)

    def tpu_mutator(pod: dict, info: dict) -> None:
        if info.get("operation") == "CREATE":
            tpu_webhook.mutate_pod(pod)

    kube.add_mutator("Pod", poddefault_mutator)
    kube.add_mutator("Pod", tpu_mutator)
