"""RFC 6902 JSON Patch generation.

The admission server must return the *difference* between the object the
apiserver sent and the mutated object (the reference marshals both and
diffs, ``admission-webhook/main.go:685-702`` via the jsonpatch lib). This
is that diff, from scratch: add/replace/remove ops, list-aware.
"""

from __future__ import annotations

from typing import Any


def _escape(token: str) -> str:
    return token.replace("~", "~0").replace("/", "~1")


def diff(old: Any, new: Any, path: str = "") -> list[dict]:
    """Minimal patch transforming ``old`` into ``new``."""
    if old == new:
        return []
    if isinstance(old, dict) and isinstance(new, dict):
        ops: list[dict] = []
        for key in old:
            if key not in new:
                ops.append({"op": "remove", "path": f"{path}/{_escape(str(key))}"})
        for key, value in new.items():
            sub = f"{path}/{_escape(str(key))}"
            if key not in old:
                ops.append({"op": "add", "path": sub, "value": value})
            else:
                ops.extend(diff(old[key], value, sub))
        return ops
    if isinstance(old, list) and isinstance(new, list):
        ops = []
        common = min(len(old), len(new))
        for i in range(common):
            ops.extend(diff(old[i], new[i], f"{path}/{i}"))
        # Removals from the tail, highest index first (indices shift on remove).
        for i in range(len(old) - 1, common - 1, -1):
            ops.append({"op": "remove", "path": f"{path}/{i}"})
        for i in range(common, len(new)):
            ops.append({"op": "add", "path": f"{path}/-", "value": new[i]})
        return ops
    return [{"op": "replace", "path": path or "", "value": new}]


def apply(doc: Any, patch: list[dict]) -> Any:
    """Reference applier (tests + dry-runs); raises on malformed patches."""
    import copy

    doc = copy.deepcopy(doc)

    def resolve(path: str) -> tuple[Any, str | int]:
        if not path.startswith("/"):
            raise ValueError(f"bad path {path!r}")
        parts = [p.replace("~1", "/").replace("~0", "~") for p in path[1:].split("/")]
        cur = doc
        for part in parts[:-1]:
            cur = cur[int(part)] if isinstance(cur, list) else cur[part]
        last = parts[-1]
        if isinstance(cur, list) and last != "-":
            return cur, int(last)
        return cur, last

    for op in patch:
        kind, path = op["op"], op["path"]
        container, key = resolve(path)
        if kind == "add":
            if isinstance(container, list):
                if key == "-":
                    container.append(op["value"])
                else:
                    container.insert(key, op["value"])
            else:
                container[key] = op["value"]
        elif kind == "replace":
            container[key] = op["value"]
        elif kind == "remove":
            if isinstance(container, list):
                container.pop(key)
            else:
                del container[key]
        else:
            raise ValueError(f"unsupported op {kind!r}")
    return doc
