"""Admission layer: pure mutation/validation engines + their registrations.

The reference runs three separate admission servers (PodDefault webhook,
odh notebook webhook, pvcviewer defaulter). Here each engine is a pure
function over dict-shaped objects, registered on the apiserver's admission
chain (FakeKube in tests, the real webhook server in deployment) — one
admission layer, no cross-webhook races (SURVEY.md §7 hard-part (c)).
"""

from kubeflow_tpu.webhooks.poddefault import (
    apply_poddefaults,
    filter_poddefaults,
    safe_to_apply,
)
from kubeflow_tpu.webhooks.register import register_all

__all__ = [
    "apply_poddefaults",
    "filter_poddefaults",
    "safe_to_apply",
    "register_all",
]
