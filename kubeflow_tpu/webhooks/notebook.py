"""Notebook admission: defaulting + validation + restart blocking.

Restart blocking is the odh webhook's ``maybeRestartRunningNotebook``
protocol (``odh-notebook-controller/controllers/notebook_webhook.go:
312-368``): a spec edit that would restart a RUNNING notebook's pods is not
applied live — the pod-affecting fields are reverted to their current
values and the CR is annotated ``update-pending`` so the UI can show
"restart required". Edits to a *stopped* notebook apply directly (and clear
the annotation); the user's stop→start cycle is the restart consent.

On a TPU slice this matters more than it did in the reference: an
accidental restart doesn't bounce one pod, it bounces N workers and
re-queues the whole slice through the scheduler.
"""

from __future__ import annotations

import time
import weakref

from kubeflow_tpu.api import notebook as nbapi
from kubeflow_tpu.runtime.objects import annotations_of, deep_get, deepcopy

UPDATE_PENDING_ANNOTATION = nbapi.UPDATE_PENDING_ANNOTATION

# Spec paths whose change forces a pod restart (the template IS the pod;
# the tpu block changes replicas/selectors/env).
_POD_AFFECTING = (("spec", "template"), ("spec", "tpu"))


def _pod_affecting_changed(nb: dict, old: dict) -> bool:
    return any(
        deep_get(nb, *path) != deep_get(old, *path) for path in _POD_AFFECTING
    )


def mutate(nb: dict, info: dict) -> None:
    """Full Notebook mutator: block live restarts, default, validate."""
    # Old served versions (v1beta1/v1alpha1) are schema-identical; normalize
    # to the storage version so the rest of the stack sees one apiVersion
    # (the real apiserver does this rewrite itself for strategy:None CRD
    # conversion; the in-process fake goes through admission instead).
    if nb.get("apiVersion") in nbapi.SERVED_API_VERSIONS:
        nb["apiVersion"] = nbapi.STORAGE_API_VERSION
    old = info.get("old")
    if info.get("operation") == "UPDATE" and old is not None:
        if nbapi.is_stopped(old) or nbapi.is_stopped(nb):
            # Stopped (or stopping) notebooks accept edits; they apply on
            # the next start.
            annotations_of(nb).pop(UPDATE_PENDING_ANNOTATION, None)
        elif _pod_affecting_changed(nb, old):
            for path in _POD_AFFECTING:
                current = deep_get(old, *path)
                parent = nb.setdefault(path[0], {})
                if current is None:
                    parent.pop(path[1], None)
                else:
                    parent[path[1]] = deepcopy(current)
            annotations_of(nb)[UPDATE_PENDING_ANNOTATION] = "true"
    nbapi.default(nb)
    nbapi.validate(nb)


# ---- image-alias resolution --------------------------------------------------
#
# odh's SetContainerImageFromRegistry (notebook_webhook.go:539-645) resolves
# the spawner's "<stream>:<tag>" selection annotation to a pinned image
# reference from OpenShift ImageStreams. The k8s-native equivalent is an
# admin-curated ConfigMap catalog: data["images.yaml"] maps
# ``<stream>: {<tag>: <pinned reference>}``; the webhook rewrites the main
# container's image (and JUPYTER_IMAGE env) unless it is already
# digest-pinned (the analogue of the internal-registry short-circuit).

IMAGE_SELECTION_ANNOTATION = nbapi.IMAGE_SELECTION_ANNOTATION
IMAGE_CATALOG_CONFIGMAP = "notebook-images"
IMAGE_CATALOG_KEY = "images.yaml"


def _controller_namespace() -> str:
    from kubeflow_tpu.runtime.deployment import controller_namespace

    return controller_namespace()


def _catalog_lookup(catalog: dict, stream: str, tag: str) -> str | None:
    entry = catalog.get(stream)
    if isinstance(entry, dict):
        ref = entry.get(tag)
        if isinstance(ref, str) and ref:
            return ref
    return None


# Short TTL cache for the parsed catalog, per client object (weak keys so a
# test's FakeKube doesn't pin stale entries for the next test). Admission
# bursts — the 200-notebook load test — would otherwise GET the ConfigMap
# once per Notebook CREATE/UPDATE; this mirrors the controller's TTL-cached
# Role probe (controllers/notebook.py _namespace_has_role).
CATALOG_CACHE_TTL = 10.0
_catalog_cache: weakref.WeakKeyDictionary = weakref.WeakKeyDictionary()


async def _load_catalog(kube, ns: str, configmap: str) -> dict:
    now = time.monotonic()
    per_kube = None
    try:
        per_kube = _catalog_cache.setdefault(kube, {})
        hit = per_kube.get((ns, configmap))
        if hit and now - hit[0] < CATALOG_CACHE_TTL:
            return hit[1]
    except TypeError:  # non-weakrefable client: just skip caching
        per_kube = None
    cm = await kube.get_or_none("ConfigMap", configmap, ns)
    catalog: dict = {}
    if cm is not None:
        try:
            import yaml

            parsed = yaml.safe_load(
                (cm.get("data") or {}).get(IMAGE_CATALOG_KEY) or "")
            if isinstance(parsed, dict):
                catalog = parsed
        except Exception:
            catalog = {}
    if per_kube is not None:
        per_kube[(ns, configmap)] = (now, catalog)
    return catalog


async def resolve_image_from_catalog(
    kube,
    nb: dict,
    *,
    namespace: str | None = None,
    configmap: str = IMAGE_CATALOG_CONFIGMAP,
) -> bool:
    """Rewrite the main container's image from the catalog ConfigMap.

    Returns True when a rewrite happened. Missing catalog / unknown
    selection are soft no-ops (the reference logs and admits unchanged —
    the image may be directly pullable without a catalog entry).
    """
    selection = annotations_of(nb).get(IMAGE_SELECTION_ANNOTATION)
    if not selection or ":" not in selection:
        return False
    stream, _, tag = selection.rpartition(":")
    name = deep_get(nb, "metadata", "name")
    containers = deep_get(nb, "spec", "template", "spec", "containers") or []
    container = next((c for c in containers if c.get("name") == name), None)
    if container is None:
        return False
    if "@sha256:" in (container.get("image") or ""):
        return False  # already pinned; nothing to resolve
    catalog = await _load_catalog(
        kube, namespace or _controller_namespace(), configmap)
    ref = _catalog_lookup(catalog, stream, tag)
    if ref is None or ref == container.get("image"):
        return False
    container["image"] = ref
    for env in container.get("env") or []:
        if env.get("name") == "JUPYTER_IMAGE":
            env["value"] = selection
            break
    return True
