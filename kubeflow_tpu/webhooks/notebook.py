"""Notebook admission: defaulting + validation + restart blocking.

Restart blocking is the odh webhook's ``maybeRestartRunningNotebook``
protocol (``odh-notebook-controller/controllers/notebook_webhook.go:
312-368``): a spec edit that would restart a RUNNING notebook's pods is not
applied live — the pod-affecting fields are reverted to their current
values and the CR is annotated ``update-pending`` so the UI can show
"restart required". Edits to a *stopped* notebook apply directly (and clear
the annotation); the user's stop→start cycle is the restart consent.

On a TPU slice this matters more than it did in the reference: an
accidental restart doesn't bounce one pod, it bounces N workers and
re-queues the whole slice through the scheduler.
"""

from __future__ import annotations

import os
import time
import weakref

from kubeflow_tpu.api import notebook as nbapi
from kubeflow_tpu.runtime.errors import Invalid
from kubeflow_tpu.runtime.objects import annotations_of, deep_get, deepcopy

UPDATE_PENDING_ANNOTATION = nbapi.UPDATE_PENDING_ANNOTATION

# Fleet source knobs, shared with scheduler_options()/the scheduler
# runtime (docs/operations.md "TPU fleet scheduler"): the webhook reads
# them directly because admission runs in its own process.
FLEET_ENV = "KFTPU_FLEET"
FLEET_CONFIGMAP_ENV = "KFTPU_FLEET_CONFIGMAP"

# Spec paths whose change forces a pod restart (the template IS the pod;
# the tpu block changes replicas/selectors/env).
_POD_AFFECTING = (("spec", "template"), ("spec", "tpu"))


def _pod_affecting_changed(nb: dict, old: dict) -> bool:
    return any(
        deep_get(nb, *path) != deep_get(old, *path) for path in _POD_AFFECTING
    )


def mutate(nb: dict, info: dict) -> None:
    """Full Notebook mutator: block live restarts, default, validate."""
    # Old served versions (v1beta1/v1alpha1) are schema-identical; normalize
    # to the storage version so the rest of the stack sees one apiVersion
    # (the real apiserver does this rewrite itself for strategy:None CRD
    # conversion; the in-process fake goes through admission instead).
    if nb.get("apiVersion") in nbapi.SERVED_API_VERSIONS:
        nb["apiVersion"] = nbapi.STORAGE_API_VERSION
    old = info.get("old")
    if info.get("operation") == "UPDATE" and old is not None:
        if nbapi.is_stopped(old) or nbapi.is_stopped(nb) \
                or deep_get(old, "status", "scheduler", "state") == "Queued":
            # Stopped (or stopping) notebooks accept edits; they apply on
            # the next start. A gang Queued by the fleet scheduler has no
            # pods to protect either — and blocking spec.tpu edits there
            # would trap the user out of the remediation its own queue
            # reason suggests ("reduce spec.tpu.numSlices").
            annotations_of(nb).pop(UPDATE_PENDING_ANNOTATION, None)
        elif _pod_affecting_changed(nb, old):
            for path in _POD_AFFECTING:
                current = deep_get(old, *path)
                parent = nb.setdefault(path[0], {})
                if current is None:
                    parent.pop(path[1], None)
                else:
                    parent[path[1]] = deepcopy(current)
            annotations_of(nb)[UPDATE_PENDING_ANNOTATION] = "true"
    nbapi.default(nb)
    nbapi.validate(nb)


# ---- capacity fast-fail ------------------------------------------------------
#
# A chip request that can NEVER be satisfied must die at admission with an
# actionable message, not sit in the fleet scheduler's queue (or behind a
# ResourceQuota) forever. Two ceilings are checkable synchronously:
#
# - the namespace's Profile ``spec.tpuQuota`` (the per-tenant chip
#   ceiling the profile controller materialises as a ResourceQuota);
# - the configured fleet's whole-cluster capacity for the requested slice
#   shape (``KFTPU_FLEET`` — an auto-inferred fleet is deliberately NOT
#   checked here: node pools come and go, and a transient empty fleet
#   must not reject CRs that would queue and then run).
#
# CREATE-only: rejecting UPDATEs against a later-lowered ceiling would
# freeze the controller's own annotation/status patches on the CR.


async def validate_capacity(kube, nb: dict) -> None:
    """Raise Invalid when the notebook's gang can never fit."""
    ms = nbapi.multi_slice_of(nb)  # raises Invalid on a malformed block
    if ms is None:
        return
    name = deep_get(nb, "metadata", "name")
    ns = deep_get(nb, "metadata", "namespace")
    chips = ms.num_chips
    if ns and kube is not None:
        # Profiles are cluster-scoped and named after their namespace.
        # TTL-cached like the fleet ConfigMap: an admission burst must
        # not GET the same Profile once per CREATE.
        profile = await _ttl_cached(
            _profile_cache, kube, ns,
            lambda: kube.get_or_none("Profile", ns))
        quota = deep_get(profile or {}, "spec", "tpuQuota")
        if isinstance(quota, int) and not isinstance(quota, bool) \
                and chips > quota:
            raise Invalid(
                f"Notebook {name}: requests {chips} TPU chips but the "
                f"namespace ceiling (Profile {ns} spec.tpuQuota) is "
                f"{quota} — shrink spec.tpu.topology/numSlices or raise "
                "the quota")
    from kubeflow_tpu.scheduler import scheduler_enabled

    if not scheduler_enabled():
        # KFTPU_SCHEDULER=off must restore the pre-scheduler behavior
        # end to end: a stale KFTPU_FLEET left in the deployment env
        # must not keep rejecting CRs the capacity gate would run.
        return
    fleet = await _declared_fleet(kube)
    if fleet is not None and fleet.pools:
        acc = ms.slice.accelerator.name
        topo = ms.slice.topology_str
        ceiling = fleet.total_slices(acc, topo)
        if ceiling < ms.num_slices and not _flex_schedulable(fleet, ms):
            detail = (
                f"no configured node pool hosts {acc}:{topo} slices"
                if ceiling == 0 else
                f"the fleet holds at most {ceiling} {acc}:{topo} "
                f"slice(s), the gang needs {ms.num_slices}")
            raise Invalid(
                f"Notebook {name}: can never be scheduled — {detail}. "
                "Pick a shape from the configured fleet (KFTPU_FLEET) "
                "or reduce spec.tpu.numSlices")


def _flex_schedulable(fleet, ms) -> bool:
    """With the elastic fleet on, a single-host gang can borrow a host
    from a same-accelerator pool (scheduler/elastic.py flex placement) —
    the shape ceiling alone must not fast-fail it. One shared predicate
    (elastic.flex_capable) keeps this aligned with the scheduler's own
    eligibility rule."""
    from kubeflow_tpu.scheduler import elastic

    if not elastic.elastic_enabled():
        return False
    return elastic.flex_capable(fleet, ms.slice,
                                num_slices=ms.num_slices)


async def _declared_fleet(kube):
    """The operator-declared fleet for the fast-fail ceiling: the
    KFTPU_FLEET env spec, else the KFTPU_FLEET_CONFIGMAP ConfigMap
    (TTL-cached — admission bursts must not GET it per CREATE). An
    auto-inferred fleet (`KFTPU_FLEET=auto`) is deliberately excluded:
    node pools come and go, and a transiently empty fleet must not
    reject CRs that would queue and then run. Returns None when nothing
    is declared or the spec is broken (a bad spec must not block
    admissions)."""
    from kubeflow_tpu.scheduler.fleet import Fleet, FleetConfigError
    from kubeflow_tpu.scheduler.runtime import load_fleet_from_configmap

    spec = os.environ.get(FLEET_ENV, "").strip()
    if spec == "auto":
        return None
    if not spec:
        configmap = os.environ.get(FLEET_CONFIGMAP_ENV)
        if not configmap or kube is None:
            return None
        from kubeflow_tpu.runtime.deployment import controller_namespace

        ns = controller_namespace()
        return await _ttl_cached(
            _fleet_cache, kube, (ns, configmap),
            lambda: load_fleet_from_configmap(kube, configmap, ns))
    try:
        return Fleet.parse(spec)
    except FleetConfigError:
        return None


_fleet_cache: weakref.WeakKeyDictionary = weakref.WeakKeyDictionary()
_profile_cache: weakref.WeakKeyDictionary = weakref.WeakKeyDictionary()


# ---- image-alias resolution --------------------------------------------------
#
# odh's SetContainerImageFromRegistry (notebook_webhook.go:539-645) resolves
# the spawner's "<stream>:<tag>" selection annotation to a pinned image
# reference from OpenShift ImageStreams. The k8s-native equivalent is an
# admin-curated ConfigMap catalog: data["images.yaml"] maps
# ``<stream>: {<tag>: <pinned reference>}``; the webhook rewrites the main
# container's image (and JUPYTER_IMAGE env) unless it is already
# digest-pinned (the analogue of the internal-registry short-circuit).

IMAGE_SELECTION_ANNOTATION = nbapi.IMAGE_SELECTION_ANNOTATION
IMAGE_CATALOG_CONFIGMAP = "notebook-images"
IMAGE_CATALOG_KEY = "images.yaml"


def _controller_namespace() -> str:
    from kubeflow_tpu.runtime.deployment import controller_namespace

    return controller_namespace()


def _catalog_lookup(catalog: dict, stream: str, tag: str) -> str | None:
    entry = catalog.get(stream)
    if isinstance(entry, dict):
        ref = entry.get(tag)
        if isinstance(ref, str) and ref:
            return ref
    return None


# Short TTL cache for the parsed catalog, per client object (weak keys so a
# test's FakeKube doesn't pin stale entries for the next test). Admission
# bursts — the 200-notebook load test — would otherwise GET the ConfigMap
# once per Notebook CREATE/UPDATE; this mirrors the controller's TTL-cached
# Role probe (controllers/notebook.py _namespace_has_role).
CATALOG_CACHE_TTL = 10.0
_catalog_cache: weakref.WeakKeyDictionary = weakref.WeakKeyDictionary()


async def _ttl_cached(cache, kube, key, loader):
    """Per-client TTL memo for ConfigMap-backed admission lookups (the
    image catalog and the declared-fleet ceiling share it). Weak client
    keys so a test's FakeKube doesn't pin stale entries for the next
    test; a non-weakrefable client just skips caching."""
    now = time.monotonic()
    per_kube = None
    try:
        per_kube = cache.setdefault(kube, {})
        hit = per_kube.get(key)
        if hit and now - hit[0] < CATALOG_CACHE_TTL:
            return hit[1]
    except TypeError:
        per_kube = None
    value = await loader()
    if per_kube is not None:
        per_kube[key] = (now, value)
    return value


async def _load_catalog(kube, ns: str, configmap: str) -> dict:
    async def load() -> dict:
        cm = await kube.get_or_none("ConfigMap", configmap, ns)
        catalog: dict = {}
        if cm is not None:
            try:
                import yaml

                parsed = yaml.safe_load(
                    (cm.get("data") or {}).get(IMAGE_CATALOG_KEY) or "")
                if isinstance(parsed, dict):
                    catalog = parsed
            except Exception:
                catalog = {}
        return catalog

    return await _ttl_cached(_catalog_cache, kube, (ns, configmap), load)


async def resolve_image_from_catalog(
    kube,
    nb: dict,
    *,
    namespace: str | None = None,
    configmap: str = IMAGE_CATALOG_CONFIGMAP,
) -> bool:
    """Rewrite the main container's image from the catalog ConfigMap.

    Returns True when a rewrite happened. Missing catalog / unknown
    selection are soft no-ops (the reference logs and admits unchanged —
    the image may be directly pullable without a catalog entry).
    """
    selection = annotations_of(nb).get(IMAGE_SELECTION_ANNOTATION)
    if not selection or ":" not in selection:
        return False
    stream, _, tag = selection.rpartition(":")
    name = deep_get(nb, "metadata", "name")
    containers = deep_get(nb, "spec", "template", "spec", "containers") or []
    container = next((c for c in containers if c.get("name") == name), None)
    if container is None:
        return False
    if "@sha256:" in (container.get("image") or ""):
        return False  # already pinned; nothing to resolve
    catalog = await _load_catalog(
        kube, namespace or _controller_namespace(), configmap)
    ref = _catalog_lookup(catalog, stream, tag)
    if ref is None or ref == container.get("image"):
        return False
    container["image"] = ref
    for env in container.get("env") or []:
        if env.get("name") == "JUPYTER_IMAGE":
            env["value"] = selection
            break
    return True
