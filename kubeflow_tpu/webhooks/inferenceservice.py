"""InferenceService admission: defaulting + validation + capacity
fast-fail.

Same contract as the Notebook webhook's capacity gate
(webhooks/notebook.py): a service that can NEVER run must die at CREATE
with an actionable message, not sit Queued forever. Two ceilings are
checkable synchronously, through the SAME ``_ttl_cached`` loaders the
notebook gate uses (so the spec key, the cache TTL, and the bad-spec
tolerance cannot drift between the two workload classes):

- the namespace Profile's ``spec.tpuQuota`` — one replica's chips must
  fit under it, and so must the guaranteed floor
  (``minReplicas × chips``: the autoscaler will hold that many replicas
  admitted at all times);
- the declared fleet's shape ceiling — a single replica's gang must fit
  the fleet even fully drained (``maxReplicas`` deliberately is NOT
  checked against the ceiling: the autoscaler queues surplus replicas
  by design, and a burst ceiling above current capacity is exactly what
  scale-up intents exist for).

CREATE-only, like the notebook gate: rejecting UPDATEs against a
later-lowered ceiling would freeze the controller's own status patches.
"""

from __future__ import annotations

from kubeflow_tpu.api import inferenceservice as isvcapi
from kubeflow_tpu.runtime.errors import Invalid
from kubeflow_tpu.runtime.objects import deep_get
from kubeflow_tpu.webhooks.notebook import (
    _declared_fleet,
    _profile_cache,
    _ttl_cached,
)


def mutate(isvc: dict, _info: dict) -> None:
    """Full InferenceService mutator: default, then validate."""
    isvcapi.default(isvc)
    isvcapi.validate(isvc)


async def validate_capacity(kube, isvc: dict) -> None:
    """Raise Invalid when the service could never hold its replicas."""
    ms = isvcapi.multi_slice_of(isvc)  # raises Invalid on malformed tpu
    if ms is None:
        return
    name = deep_get(isvc, "metadata", "name")
    ns = deep_get(isvc, "metadata", "namespace")
    chips = ms.num_chips
    floor = max(1, isvcapi.min_replicas(isvc))
    if ns and kube is not None:
        profile = await _ttl_cached(
            _profile_cache, kube, ns,
            lambda: kube.get_or_none("Profile", ns))
        quota = deep_get(profile or {}, "spec", "tpuQuota")
        if isinstance(quota, int) and not isinstance(quota, bool):
            if chips > quota:
                raise Invalid(
                    f"InferenceService {name}: one replica needs {chips} "
                    f"TPU chips but the namespace ceiling (Profile {ns} "
                    f"spec.tpuQuota) is {quota} — shrink "
                    "spec.tpu.topology/numSlices or raise the quota")
            if floor * chips > quota:
                raise Invalid(
                    f"InferenceService {name}: the scaling floor needs "
                    f"{floor} replica(s) x {chips} chips = "
                    f"{floor * chips}, over the namespace ceiling "
                    f"(Profile {ns} spec.tpuQuota = {quota}) — lower "
                    "spec.scaling.minReplicas or raise the quota")
    from kubeflow_tpu.scheduler import scheduler_enabled
    from kubeflow_tpu.serving import serving_enabled

    if not (scheduler_enabled() and serving_enabled()):
        # Either kill switch restores the pre-gate behavior end to end.
        return
    fleet = await _declared_fleet(kube)
    if fleet is not None and fleet.pools:
        acc = ms.slice.accelerator.name
        topo = ms.slice.topology_str
        ceiling = fleet.total_slices(acc, topo)
        if ceiling < ms.num_slices:
            detail = (
                f"no configured node pool hosts {acc}:{topo} slices"
                if ceiling == 0 else
                f"the fleet holds at most {ceiling} {acc}:{topo} "
                f"slice(s), one replica needs {ms.num_slices}")
            raise Invalid(
                f"InferenceService {name}: no replica can ever be "
                f"scheduled — {detail}. Pick a shape from the configured "
                "fleet (KFTPU_FLEET) or reduce spec.tpu.numSlices")
        if floor * ms.num_slices > ceiling:
            raise Invalid(
                f"InferenceService {name}: the scaling floor needs "
                f"{floor} replica(s) x {ms.num_slices} {acc}:{topo} "
                f"slice(s) = {floor * ms.num_slices}, but the fleet "
                f"ceiling is {ceiling} — lower spec.scaling.minReplicas "
                "or grow the fleet")
