"""PodDefault → Pod injection engine.

Behavior-compatible with the reference webhook (``admission-webhook/main.go``):

- ``filter_poddefaults`` — label-selector match (:72-97)
- ``safe_to_apply``      — pure merge dry-run, conflict-as-error (:101-150)
- ``apply_poddefaults``  — the actual mutation (:480-597), stamping
  ``poddefault.admission.kubeflow.org/poddefault-<name>: <resourceVersion>``
- exclusion annotation ``poddefault.admission.kubeflow.org/exclude: "true"``
  and mirror-pod skip (:625-633)

Merge semantics (one generic keyed merge replaces the reference's six
hand-rolled Go functions, :168-475):

- keyed lists (env by name, volumes by name, volumeMounts by name AND by
  mountPath, containers by name, tolerations by key, imagePullSecrets by
  name): absent → append; present-and-identical → no-op; present-but-
  different → **conflict error**
- envFrom: plain append
- labels/annotations maps: absent → set; different value → conflict
- command/args: set only when the container has none (never overwritten)
- serviceAccountName/automountServiceAccountToken: last PodDefault wins
"""

from __future__ import annotations

from kubeflow_tpu.runtime.errors import Invalid
from kubeflow_tpu.runtime.objects import (
    deep_get,
    deepcopy,
    get_meta,
    matches_selector,
    name_of,
)

ANNOTATION_PREFIX = "poddefault.admission.kubeflow.org"
EXCLUDE_ANNOTATION = f"{ANNOTATION_PREFIX}/exclude"
MIRROR_POD_ANNOTATION = "kubernetes.io/config.mirror"
ISTIO_PROXY_CONTAINER = "istio-proxy"


class MergeConflict(Invalid):
    """A PodDefault collides with the pod (or another PodDefault)."""


def _merge_keyed(
    existing: list[dict],
    incoming: list[tuple[str, dict]],  # (poddefault-name, item)
    key_fns,
    what: str,
) -> list[dict]:
    """Generic conflict-checked merge. ``key_fns`` is one or more functions
    extracting an identity key; an item conflicts if ANY key matches an
    existing item that isn't deep-equal (the volumeMounts name+mountPath
    double check, main.go:266-311)."""
    if callable(key_fns):
        key_fns = (key_fns,)
    merged = [deepcopy(item) for item in existing]
    indexes: list[dict] = [
        {fn(item): item for item in merged if fn(item) is not None}
        for fn in key_fns
    ]
    errs: list[str] = []
    for pd_name, item in incoming:
        clash = None
        fresh = True
        for fn, index in zip(key_fns, indexes):
            key = fn(item)
            if key is None:
                continue
            found = index.get(key)
            if found is None:
                index[key] = item
            else:
                fresh = False
                if found != item:
                    clash = (fn(item), found)
        if clash is not None:
            errs.append(
                f"merging {what} for PodDefault {pd_name} conflicts on "
                f"{clash[0]!r}: {item} does not match existing {clash[1]}"
            )
        elif fresh:
            merged.append(deepcopy(item))
    if errs:
        raise MergeConflict("; ".join(errs))
    return merged


def _merge_map(existing: dict, incoming: list[tuple[str, dict]], what: str) -> dict:
    out = dict(existing or {})
    errs = []
    for pd_name, mapping in incoming:
        for k, v in (mapping or {}).items():
            if k in out and out[k] != v:
                errs.append(
                    f"merging {what} for PodDefault {pd_name} conflicts on "
                    f"{k!r}: {v!r} != {out[k]!r}"
                )
            else:
                out[k] = v
    if errs:
        raise MergeConflict("; ".join(errs))
    return out


def _collect(pds: list[dict], field: str) -> list[tuple[str, dict]]:
    out = []
    for pd in pds:
        for item in deep_get(pd, "spec", field, default=[]) or []:
            out.append((name_of(pd), item))
    return out


def _collect_maps(pds: list[dict], field: str) -> list[tuple[str, dict]]:
    return [
        (name_of(pd), deep_get(pd, "spec", field, default={}) or {}) for pd in pds
    ]


def filter_poddefaults(pds: list[dict], pod: dict) -> list[dict]:
    """PodDefaults whose spec.selector matches the pod's labels (main.go:72-97)."""
    labels = get_meta(pod).get("labels") or {}
    return [
        pd
        for pd in sorted(pds, key=name_of)
        if matches_selector(labels, deep_get(pd, "spec", "selector", default={}))
    ]


def is_excluded(pod: dict) -> bool:
    annotations = get_meta(pod).get("annotations") or {}
    return (
        annotations.get(EXCLUDE_ANNOTATION) == "true"
        or MIRROR_POD_ANNOTATION in annotations
    )


def safe_to_apply(pod: dict, pds: list[dict]) -> None:
    """Raise MergeConflict unless every PodDefault merges cleanly
    (main.go:101-150). Pure — never mutates the pod."""
    apply_poddefaults(deepcopy(pod), pds)


def apply_poddefaults(pod: dict, pds: list[dict]) -> dict:
    """Merge ``pds`` into ``pod`` in place; returns the pod (main.go:480-597).

    Conflicts raise (the reference *rejects* the pod on conflict,
    main.go:672-681 — same here, surfaced as an admission error).
    """
    if not pds:
        return pod
    spec = pod.setdefault("spec", {})

    spec_merges = (
        ("volumes", "volumes", (lambda v: v.get("name"),)),
        ("tolerations", "tolerations", (lambda t: t.get("key"),)),
        ("imagePullSecrets", "imagePullSecrets", (lambda s: s.get("name"),)),
        ("initContainers", "initContainers", (lambda c: c.get("name"),)),
        ("sidecars", "containers", (lambda c: c.get("name"),)),
    )
    for field, target, keys in spec_merges:
        incoming = _collect(pds, field)
        if incoming:
            spec[target] = _merge_keyed(
                spec.get(target, []) or [], incoming, keys, field
            )

    meta = get_meta(pod)
    for field in ("labels", "annotations"):
        merged = _merge_map(meta.get(field) or {}, _collect_maps(pds, field), field)
        if merged:
            meta[field] = merged

    for pd in pds:
        sa = deep_get(pd, "spec", "serviceAccountName")
        if sa:
            spec["serviceAccountName"] = sa
        automount = deep_get(pd, "spec", "automountServiceAccountToken")
        if automount is not None:
            spec["automountServiceAccountToken"] = automount

    env_in = _collect(pds, "env")
    mounts_in = _collect(pds, "volumeMounts")
    envfrom_in = _collect(pds, "envFrom")
    sidecar_names = {name for _, c in _collect(pds, "sidecars") for name in [c.get("name")]}
    for ctr in spec.get("containers", []):
        if ctr.get("name") in sidecar_names:
            continue  # freshly injected sidecars carry their own env/mounts
        if env_in:
            ctr["env"] = _merge_keyed(
                ctr.get("env", []) or [], env_in, (lambda e: e.get("name"),), "env"
            )
        if mounts_in:
            ctr["volumeMounts"] = _merge_keyed(
                ctr.get("volumeMounts", []) or [],
                mounts_in,
                (lambda m: m.get("name"), lambda m: m.get("mountPath")),
                "volumeMounts",
            )
        if envfrom_in:
            ctr["envFrom"] = (ctr.get("envFrom", []) or []) + [
                deepcopy(item) for _, item in envfrom_in
            ]
        _set_command_and_args(ctr, pds)

    annotations = meta.setdefault("annotations", {})
    for pd in pds:
        annotations[f"{ANNOTATION_PREFIX}/poddefault-{name_of(pd)}"] = get_meta(
            pd
        ).get("resourceVersion", "")
    return pod


def _set_command_and_args(ctr: dict, pds: list[dict]) -> None:
    """Command/args fill-if-absent, istio sidecar excluded (main.go:583-597)."""
    if ctr.get("name") == ISTIO_PROXY_CONTAINER:
        return
    for pd in pds:
        command = deep_get(pd, "spec", "command")
        if ctr.get("command") is None and command is not None:
            ctr["command"] = list(command)
        args = deep_get(pd, "spec", "args")
        if ctr.get("args") is None and args is not None:
            ctr["args"] = list(args)


async def mutate_pod(kube, pod: dict) -> None:
    """Admission entrypoint: list PodDefaults in the pod's namespace, filter,
    check, apply (main.go:599-704). Registered as a Pod mutator."""
    if is_excluded(pod):
        return
    namespace = get_meta(pod).get("namespace")
    if not namespace:
        return
    pds = await kube.list("PodDefault", namespace)
    matching = filter_poddefaults(pds, pod)
    if not matching:
        return
    apply_poddefaults(pod, matching)  # raises MergeConflict → admission reject
