"""AdmissionReview HTTPS server for real clusters.

The reference runs three separate webhook servers (PodDefault
``/apply-poddefault``, odh notebook ``/mutate-notebook-v1``, pvcviewer
defaulter); this is the single consolidated server, one endpoint per
engine, speaking ``admission.k8s.io/v1`` AdmissionReview with JSONPatch
responses (serve loop contract: ``admission-webhook/main.go:708-773``).

In tests the same engines run in-process on FakeKube's admission chain —
this module only adds the wire protocol.
"""

from __future__ import annotations

import base64
import json
import logging
import ssl

from aiohttp import web

from kubeflow_tpu.api import poddefault as pdapi
from kubeflow_tpu.api import profile as profileapi
from kubeflow_tpu.api import pvcviewer as pvcapi
from kubeflow_tpu.api import tensorboard as tbapi
from kubeflow_tpu.runtime.errors import ApiError
from kubeflow_tpu.runtime.metrics import global_registry
from kubeflow_tpu.runtime.objects import deepcopy
from kubeflow_tpu.runtime.tracing import Tracer, span
from kubeflow_tpu.webhooks import jsonpatch
from kubeflow_tpu.webhooks import notebook as nb_webhook
from kubeflow_tpu.webhooks import poddefault as pd_webhook
from kubeflow_tpu.webhooks import tpu as tpu_webhook

log = logging.getLogger(__name__)


def _allow(uid: str, patch: list[dict] | None = None) -> dict:
    response: dict = {"uid": uid, "allowed": True}
    if patch:
        response["patchType"] = "JSONPatch"
        response["patch"] = base64.b64encode(
            json.dumps(patch).encode()
        ).decode()
    return {
        "apiVersion": "admission.k8s.io/v1",
        "kind": "AdmissionReview",
        "response": response,
    }


def _deny(uid: str, message: str, code: int = 400) -> dict:
    return {
        "apiVersion": "admission.k8s.io/v1",
        "kind": "AdmissionReview",
        "response": {
            "uid": uid,
            "allowed": False,
            "status": {"message": message, "code": code},
        },
    }


def create_webhook_app(kube, *, registry=None, tracer=None) -> web.Application:
    registry = registry or global_registry
    app = web.Application()
    app["kube"] = kube
    # Admission spans + flight recorder: the same tracing machinery the
    # controllers use, so /debug/traces on the webhook answers "what did
    # admission do to kind/ns/name and how long did the mutator take".
    tracer = tracer or Tracer(registry)
    app["tracer"] = tracer
    # Admission observability (controller-runtime webhooks expose the same
    # shape; the reference's PodDefault server only klogs).
    m_admissions = registry.counter(
        "webhook_admission_total",
        "AdmissionReview requests by endpoint and outcome",
        ["path", "allowed"],
    )

    async def handle(request: web.Request, mutator) -> web.Response:
        try:
            review = await request.json()
        except ValueError:
            review = None
        if not isinstance(review, dict):
            # Counts valid-JSON-but-not-an-object bodies too — the failure
            # class this metric exists to surface.
            m_admissions.labels(path=request.path, allowed="false").inc()
            return web.json_response(
                _deny("", "could not decode AdmissionReview"), status=400
            )
        req = review.get("request") or {}
        uid = req.get("uid", "")
        obj = req.get("object") or {}
        operation = req.get("operation", "CREATE")
        old = req.get("oldObject") or None
        # Namespace fallback (main.go:616-619).
        if not obj.get("metadata", {}).get("namespace") and req.get("namespace"):
            obj.setdefault("metadata", {})["namespace"] = req["namespace"]
        original = deepcopy(obj)
        meta = obj.get("metadata") or {}
        admission_key = (
            obj.get("kind") or req.get("kind", {}).get("kind") or "?",
            meta.get("namespace"),
            meta.get("name") or meta.get("generateName") or "?",
        )
        # Reuse the apiserver's request id when it sent one, so the
        # admission trace correlates with the apiserver audit log.
        incoming_id = request.headers.get("X-Request-Id")
        with tracer.trace(
            "admission", key=admission_key, controller="webhook",
            trace_id=incoming_id, path=request.path, operation=operation,
        ) as root:
            try:
                with span("mutate"):
                    await mutator(request.app["kube"], obj, operation, old)
            except ApiError as e:
                # The deny response swallows the exception — fail() the
                # root explicitly or the flight recorder would file this
                # admission as outcome ok.
                root.fail(e.message)
                root.set_attribute("allowed", "false")
                m_admissions.labels(path=request.path, allowed="false").inc()
                resp = web.json_response(_deny(uid, e.message, e.code))
            except Exception as e:
                log.exception("webhook mutator failed")
                root.fail(repr(e))
                root.set_attribute("allowed", "false")
                m_admissions.labels(path=request.path, allowed="false").inc()
                resp = web.json_response(
                    _deny(uid, "internal webhook error", 500))
            else:
                root.set_attribute("allowed", "true")
                m_admissions.labels(path=request.path, allowed="true").inc()
                resp = web.json_response(
                    _allow(uid, jsonpatch.diff(original, obj)))
            if root.trace_id:
                resp.headers["X-Request-Id"] = root.trace_id
        return resp

    # -- Pod mutation: PodDefault injection + per-worker TPU env ------------
    async def mutate_pod(kube, pod, operation, _old):
        if operation == "CREATE":
            await pd_webhook.mutate_pod(kube, pod)
            tpu_webhook.mutate_pod(pod)

    # -- CR defaulting/validation (+ restart blocking for Notebooks) --------
    async def mutate_notebook(kube, nb, operation, old):
        nb_webhook.mutate(nb, {"operation": operation, "old": old})
        # Image-alias pinning from the catalog ConfigMap (same engine the
        # in-process chain registers; see webhooks/notebook.py).
        await nb_webhook.resolve_image_from_catalog(kube, nb)
        # Capacity fast-fail (CREATE only): a gang that exceeds the
        # namespace tpuQuota or the configured fleet's ceiling can never
        # run — reject it here instead of queueing it forever.
        if operation == "CREATE":
            await nb_webhook.validate_capacity(kube, nb)

    async def mutate_pvcviewer(_kube, viewer, _op, _old):
        pvcapi.default(viewer)
        pvcapi.validate(viewer)

    def route(mutator):
        async def handler(request: web.Request) -> web.Response:
            return await handle(request, mutator)

        return handler

    # /apply-poddefault is the reference's path (main.go:765); /mutate-pods
    # is the canonical alias.
    app.router.add_post("/apply-poddefault", route(mutate_pod))
    app.router.add_post("/mutate-pods", route(mutate_pod))
    app.router.add_post("/mutate-notebooks", route(mutate_notebook))
    app.router.add_post("/mutate-pvcviewers", route(mutate_pvcviewer))

    for path, validator in (
        ("/validate-poddefaults", pdapi.validate),
        ("/validate-profiles", profileapi.validate),
        ("/validate-tensorboards", tbapi.validate),
    ):
        async def validate_handler(request, _v=validator):
            async def fn(_kube, obj, _op, _old):
                _v(obj)

            return await handle(request, fn)

        app.router.add_post(path, validate_handler)

    # -- CRD version conversion (apiextensions.k8s.io/v1 ConversionReview) --
    # Reference: notebook-controller serves v1/v1beta1/v1alpha1 with the
    # hub/spoke no-op conversion (api/v1beta1/notebook_conversion.go) wired
    # via config/crd/patches/webhook_in_notebooks.yaml's /convert path.
    async def convert(request: web.Request) -> web.Response:
        from kubeflow_tpu.api import notebook as nbapi
        from kubeflow_tpu.api import profile as profile_api

        converters = {
            nbapi.KIND: nbapi.convert,
            profile_api.KIND: profile_api.convert,
        }

        try:
            review = await request.json()
        except ValueError:
            return web.json_response(
                {"error": "could not decode ConversionReview"}, status=400
            )
        req = review.get("request") or {}
        uid = req.get("uid", "")
        desired = req.get("desiredAPIVersion", "")
        converted, failed = [], None
        for obj in req.get("objects") or []:
            try:
                fn = converters.get(obj.get("kind"))
                if fn is not None:
                    converted.append(fn(obj, desired))
                else:
                    # Other CRDs are single-version today; identity-convert
                    # anything already at the desired version.
                    if obj.get("apiVersion") != desired:
                        raise ApiError(
                            f"no conversion for {obj.get('kind')} "
                            f"{obj.get('apiVersion')} -> {desired}"
                        )
                    converted.append(obj)
            except ApiError as e:
                failed = e.message
                break
        result = (
            {"status": "Failed", "message": failed}
            if failed
            else {"status": "Success"}
        )
        return web.json_response(
            {
                "apiVersion": "apiextensions.k8s.io/v1",
                "kind": "ConversionReview",
                "response": {
                    "uid": uid,
                    "result": result,
                    **({} if failed else {"convertedObjects": converted}),
                },
            }
        )

    app.router.add_post("/convert", convert)

    async def healthz(_request):
        return web.json_response({"status": "ok"})

    async def metrics(_request):
        return web.Response(text=registry.expose(), content_type="text/plain")

    async def debug_traces(request: web.Request) -> web.Response:
        """Recent admission flight-recorder entries (key=Kind/ns/name)."""
        try:
            limit = int(request.query.get("limit", "50"))
        except ValueError:
            limit = 50
        return web.json_response({
            "traces": tracer.recorder.entries(
                key=request.query.get("key"), limit=limit
            ),
        })

    app.router.add_get("/metrics", metrics)
    app.router.add_get("/healthz", healthz)
    app.router.add_get("/debug/traces", debug_traces)
    return app


def ssl_context(cert_file: str, key_file: str) -> ssl.SSLContext:
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    ctx.load_cert_chain(cert_file, key_file)
    return ctx


async def rotate_certs(ctx: ssl.SSLContext, cert_file: str, key_file: str,
                       *, watcher=None, poll_seconds: float = 30.0) -> None:
    """Reload renewed certs into the live SSLContext — cert-manager /
    service-ca rotate the files in place, and ``load_cert_chain`` on an
    in-use context makes every NEW handshake present the new chain, so
    the admission server never needs the pod restart the reference
    relies on. Half-written files mid-rotation (cert swapped before key)
    fail the load and retry on the next change event. Run as an asyncio
    task; cancel to stop."""
    from kubeflow_tpu.utils.fswatch import FileWatcher

    w = watcher or FileWatcher(cert_file)
    retry_pending = False
    try:
        while True:
            changed = await w.wait(timeout=poll_seconds)
            # Only the cert file's mtime is watched; a renewal that
            # writes cert-then-key can fail the load on the first event
            # and never fire another. While a failed load is pending,
            # retry on every wakeup (timeouts included) until it sticks.
            if not changed and not retry_pending:
                continue
            try:
                # Validate the pair in a throwaway context FIRST:
                # load_cert_chain installs the cert before the key check
                # can raise, so loading a half-rotated pair directly
                # into the live context would leave it serving
                # new-cert/old-key — a handshake outage, not a stale
                # cert. Only a pair that loads cleanly touches ctx.
                probe = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
                probe.load_cert_chain(cert_file, key_file)
                ctx.load_cert_chain(cert_file, key_file)
                log.info("webhook TLS certs reloaded from %s", cert_file)
                retry_pending = False
            except (ssl.SSLError, OSError) as e:
                log.warning("cert reload failed (mid-rotation?): %s — "
                            "will retry; old chain keeps serving", e)
                retry_pending = True
    finally:
        if hasattr(w, "close"):
            w.close()
