"""CheckpointFabric — snapshot-then-ack async checkpointing over the
tiered chunk store.

The fabric splits a checkpoint into the two phases that matter to a
drain deadline:

1. **Snapshot (synchronous, fast):** :meth:`CheckpointFabric.save_async`
   copies every device array to host memory (``np.asarray``) before it
   returns. Once it returns, the training state is safe from the pod's
   demise *as data* — this is the point :class:`kubeflow_tpu.sdk.
   CheckpointGuard` acks the drain, and what the ``drain_roundtrip``
   SLI clocks.
2. **Commit (background, durable):** a single uploader thread chunks
   the snapshot, writes content-addressed chunks to the staging tier
   and then the remote tier (bounded retry + exponential backoff),
   lands the manifest with a two-phase rename, and finally advances the
   remote ``COMMITTED`` pointer — the only instant at which the step
   becomes restorable. ``checkpoint_commit`` clocks snapshot→commit.

Restore inverts the tiers: the remote committed pointer is
authoritative (a stale staging pointer can never win), chunks are
served from staging when their hashes verify and fall through to the
remote tier otherwise, and any torn manifest or corrupt chunk causes a
fall-back to the *previous* committed step with
``tpu_checkpoint_integrity_failures_total`` incremented — never a
partial pytree and never an exception into the training loop while an
older committed step exists.

Saves are strictly ordered through one worker queue, so commit order is
save order and retention GC can never race an in-flight delta upload.
"""

from __future__ import annotations

import os
import queue
import threading
import time

import numpy as np

from ..runtime import slo
from ..runtime.metrics import Registry, global_registry
from .store import (
    ChunkCorruptionError,
    DirectoryTier,
    StagingTier,
    TornManifestError,
    chunk_hash,
    split_chunks,
)

# Env knobs (all documented in docs/operations.md, "Checkpoint fabric").
STAGING_DIR_ENV = "KFTPU_CKPT_STAGING_DIR"
STAGING_BYTES_ENV = "KFTPU_CKPT_STAGING_BYTES"
CHUNK_BYTES_ENV = "KFTPU_CKPT_CHUNK_BYTES"
FULL_INTERVAL_ENV = "KFTPU_CKPT_FULL_INTERVAL"
UPLOAD_RETRIES_ENV = "KFTPU_CKPT_UPLOAD_RETRIES"
BACKOFF_ENV = "KFTPU_CKPT_BACKOFF_SECONDS"

_DEFAULT_CHUNK_BYTES = 4 << 20
_DEFAULT_FULL_INTERVAL = 4
_DEFAULT_RETRIES = 3
_DEFAULT_BACKOFF = 0.05


class CheckpointIntegrityError(Exception):
    """No committed step could be restored intact — every candidate was
    torn or corrupt. Only raised when fallback is exhausted."""


class _UploadCrash(Exception):
    """Injected crash-mid-upload: the uploading process died. Not
    retried — the step simply never commits."""


class SaveHandle:
    """Tracks one async save from snapshot to durable commit."""

    def __init__(self, step: int):
        self.step = step
        self.committed = False
        self.error: Exception | None = None
        self.bytes_written = 0
        self.chunks_total = 0
        self.chunks_done = 0
        self._done = threading.Event()

    def done(self) -> bool:
        return self._done.is_set()

    def wait(self, timeout: float | None = None) -> bool:
        return self._done.wait(timeout)

    def result(self, timeout: float | None = None) -> bool:
        """Block until the background commit finishes; True iff the step
        durably committed."""
        if not self._done.wait(timeout):
            raise TimeoutError(f"save of step {self.step} still in flight")
        return self.committed

    def _finish(self, committed: bool, error: Exception | None = None):
        self.committed = committed
        self.error = error
        self._done.set()


def _flatten(tree, prefix=""):
    """Pure-python pytree flatten: (keypath, leaf) pairs + a rebuildable
    skeleton. Works on dict/list/tuple containers and anything
    ``np.asarray`` accepts as a leaf (numpy or jax arrays, scalars)."""
    leaves: list[tuple[str, object]] = []

    def walk(node, path):
        if isinstance(node, dict):
            return {k: walk(v, f"{path}/{k}") for k, v in sorted(node.items())}
        if isinstance(node, (list, tuple)):
            kind = "list" if isinstance(node, list) else "tuple"
            return {"__seq__": kind,
                    "items": [walk(v, f"{path}[{i}]")
                              for i, v in enumerate(node)]}
        leaves.append((path or "/", node))
        return {"__leaf__": len(leaves) - 1}

    skeleton = walk(tree, prefix)
    return leaves, skeleton


def _unflatten(skeleton, leaves):
    if isinstance(skeleton, dict):
        if "__leaf__" in skeleton:
            return leaves[skeleton["__leaf__"]]
        if "__seq__" in skeleton:
            items = [_unflatten(s, leaves) for s in skeleton["items"]]
            return items if skeleton["__seq__"] == "list" else tuple(items)
        return {k: _unflatten(v, leaves) for k, v in skeleton.items()}
    raise TornManifestError(f"bad skeleton node: {skeleton!r}")


def _snapshot_leaf(x) -> np.ndarray:
    # np.asarray on a jax array performs the device→host transfer; on
    # numpy it is a no-op view. Copy so donated/overwritten buffers
    # can't mutate the snapshot after ack.
    return np.array(np.asarray(x))


class CheckpointFabric:
    """Async multi-tier checkpoint fabric. Drop-in for the
    ``CheckpointManager`` surface the SDK guard uses (``directory`` /
    ``save`` / ``wait`` / ``restore`` / ``latest_step`` / ``close``)
    plus the async path (:meth:`save_async`) that makes
    snapshot-then-ack possible."""

    def __init__(
        self,
        directory: str,
        *,
        staging_dir: str | None = None,
        keep: int = 3,
        save_interval_steps: int = 1,
        chunk_bytes: int | None = None,
        full_interval: int | None = None,
        upload_retries: int | None = None,
        backoff_seconds: float | None = None,
        remote_op_delay: float = 0.0,
        registry: Registry | None = None,
        faults=None,
        environ=os.environ,
    ):
        self.directory = directory
        self.keep = keep
        self.interval = max(1, save_interval_steps)
        self.chunk_bytes = int(
            chunk_bytes if chunk_bytes is not None
            else environ.get(CHUNK_BYTES_ENV, _DEFAULT_CHUNK_BYTES))
        self.full_interval = max(1, int(
            full_interval if full_interval is not None
            else environ.get(FULL_INTERVAL_ENV, _DEFAULT_FULL_INTERVAL)))
        self.upload_retries = int(
            upload_retries if upload_retries is not None
            else environ.get(UPLOAD_RETRIES_ENV, _DEFAULT_RETRIES))
        self.backoff_seconds = float(
            backoff_seconds if backoff_seconds is not None
            else environ.get(BACKOFF_ENV, _DEFAULT_BACKOFF))
        self.faults = faults

        self.remote = DirectoryTier(directory, op_delay=remote_op_delay,
                                    faults=faults)
        staging_dir = staging_dir or environ.get(STAGING_DIR_ENV) or None
        self.staging: StagingTier | None = None
        if staging_dir:
            self.staging = StagingTier(
                staging_dir,
                max_bytes=int(environ.get(STAGING_BYTES_ENV, 1 << 30)),
                faults=faults)

        reg = registry or global_registry
        self._m_commits = reg.counter(
            "tpu_checkpoint_commits_total",
            "Durably committed checkpoint steps", ["kind"])
        self._m_bytes = reg.counter(
            "tpu_checkpoint_bytes_total",
            "Bytes written to checkpoint storage", ["tier"])
        self._m_tier_hits = reg.counter(
            "tpu_checkpoint_tier_hits_total",
            "Restore reads served per tier", ["tier"])
        self._m_integrity = reg.counter(
            "tpu_checkpoint_integrity_failures_total",
            "Torn manifests / corrupt chunks detected on restore")

        self.last_restore: dict | None = None
        self._save_count = 0
        self._closed = False
        self._lock = threading.Lock()
        self._inflight: list[SaveHandle] = []
        self._queue: queue.Queue = queue.Queue()
        self._worker = threading.Thread(
            target=self._drain_queue, name="ckpt-uploader", daemon=True)
        self._worker.start()

    # ---- save path ---------------------------------------------------------

    def save(self, step: int, pytree, force: bool = False) -> bool:
        """CheckpointManager-compatible save: snapshot now, commit in the
        background (pair with :meth:`wait` for synchronous semantics)."""
        if not force and step % self.interval != 0:
            return False
        self.save_async(step, pytree)
        return True

    def save_async(self, step: int, pytree, *, on_progress=None,
                   on_commit=None) -> SaveHandle:
        """Snapshot ``pytree`` to host memory synchronously, then return;
        the uploader thread owns chunking, tiered upload, manifest commit,
        retention, and the callbacks. The returned handle resolves when
        the step is durably committed (or the upload died)."""
        if self._closed:
            raise RuntimeError("fabric is closed")
        leaves, skeleton = _flatten(pytree)
        snapshot = [(path, _snapshot_leaf(x)) for path, x in leaves]
        handle = SaveHandle(step)
        with self._lock:
            self._save_count += 1
            full = (self._save_count - 1) % self.full_interval == 0
            self._inflight.append(handle)
        self._queue.put((handle, snapshot, skeleton, full,
                         time.monotonic(), on_progress, on_commit))
        return handle

    def _drain_queue(self) -> None:
        while True:
            item = self._queue.get()
            if item is None:
                return
            handle, snapshot, skeleton, full, t0, on_progress, on_commit = item
            try:
                self._upload(handle, snapshot, skeleton, full, t0,
                             on_progress, on_commit)
            except _UploadCrash as exc:
                handle._finish(False, exc)
            except Exception as exc:  # never kill the uploader thread
                handle._finish(False, exc)
            finally:
                with self._lock:
                    if handle in self._inflight:
                        self._inflight.remove(handle)

    def _upload(self, handle: SaveHandle, snapshot, skeleton, full: bool,
                t0: float, on_progress, on_commit) -> None:
        # Serialize + chunk on the worker (keeps the ack path lean).
        leaf_specs = []
        plan: list[tuple[str, bytes]] = []   # (digest, data) in order
        for path, arr in snapshot:
            data = arr.tobytes()
            hashes = []
            for piece in split_chunks(data, self.chunk_bytes):
                digest = chunk_hash(piece)
                hashes.append(digest)
                plan.append((digest, piece))
            leaf_specs.append({"key": path, "dtype": str(arr.dtype),
                               "shape": list(arr.shape), "chunks": hashes})
        manifest = {"step": handle.step, "kind": "full" if full else "delta",
                    "leaves": leaf_specs, "tree": skeleton}
        handle.chunks_total = len(plan)

        # Staging first: cheap, local, and what a same-node restore hits.
        if self.staging is not None:
            for digest, piece in plan:
                written = self.staging.put_chunk(digest, piece)
                if written:
                    self._m_bytes.labels(tier="staging").inc(written)

        # Remote upload with bounded retry/backoff. A full checkpoint
        # re-verifies every chunk's presence by rewriting it through the
        # idempotent put; a delta trusts has_chunk for dedup.
        attempt = 0
        while True:
            try:
                done = 0
                for digest, piece in plan:
                    if self._probe("should_crash_upload"):
                        raise _UploadCrash(
                            f"crash mid-upload at chunk {done}/{len(plan)}")
                    if self._probe("should_fail_upload"):
                        raise OSError("injected transient upload failure")
                    if full or not self.remote.has_chunk(digest):
                        written = self.remote.put_chunk(digest, piece)
                        handle.bytes_written += written
                        if written:
                            self._m_bytes.labels(tier="remote").inc(written)
                    done += 1
                    handle.chunks_done = done
                    if on_progress is not None:
                        on_progress(done, len(plan))
                self.remote.put_manifest(handle.step, manifest)
                self.remote.commit(handle.step)
                break
            except _UploadCrash:
                raise
            except (OSError, IOError) as exc:
                attempt += 1
                if attempt > self.upload_retries:
                    raise OSError(
                        f"upload of step {handle.step} failed after "
                        f"{attempt} attempts: {exc}") from exc
                time.sleep(self.backoff_seconds * (2 ** (attempt - 1)))  # kftpu: ignore[no-blocking-in-async] runs on the ckpt-uploader worker thread, never the event loop

        # Mirror the commit to staging (the stale-staging fault may
        # silently skip the pointer advance — restore tolerates that
        # because the remote pointer is authoritative).
        if self.staging is not None:
            self.staging.put_manifest(handle.step, manifest)
            self.staging.commit(handle.step)

        self._m_commits.labels(kind=manifest["kind"]).inc()
        self._retain()
        handle._finish(True)
        if on_commit is not None:
            on_commit(handle.step, time.monotonic() - t0)

    def _probe(self, name: str) -> bool:
        fn = getattr(self.faults, name, None)
        return bool(fn()) if callable(fn) else False

    def _retain(self) -> None:
        """Keep the newest ``keep`` manifests; GC unreferenced chunks.
        Runs on the worker thread after a commit, so it can never
        collect under an in-flight upload (the queue serializes)."""
        for tier in filter(None, (self.remote, self.staging)):
            steps = tier.manifest_steps()
            drop = steps[:-self.keep] if self.keep > 0 else []
            committed = tier.committed_step()
            live: set[str] = set()
            for step in steps:
                if step in drop and step != committed:
                    tier.drop_manifest(step)
                    continue
                try:
                    m = tier.get_manifest(step)
                except (TornManifestError, FileNotFoundError):
                    continue
                for leaf in m.get("leaves", ()):
                    live.update(leaf.get("chunks", ()))
            tier.gc(live)

    # ---- restore path ------------------------------------------------------

    def latest_step(self) -> int | None:
        """The last durably *committed* step — an in-flight upload is
        invisible here by design."""
        return self.remote.committed_step()

    def all_steps(self) -> list[int]:
        return self.remote.manifest_steps()

    def restore(self, step: int | None = None, abstract=None):
        """Restore ``step`` (default: last committed). Integrity failures
        (torn manifest, corrupt chunk) fall back to the previous committed
        step and count ``tpu_checkpoint_integrity_failures_total`` —
        callers only see an exception when no intact step exists."""
        t0 = time.monotonic()
        committed = self.remote.committed_step()
        if step is None:
            if committed is None:
                raise FileNotFoundError(
                    f"no committed checkpoint under {self.directory}")
            target = committed
        else:
            available = self.all_steps()
            if step not in available:
                raise FileNotFoundError(
                    f"no checkpoint for step {step} under "
                    f"{self.directory}; available steps: "
                    f"{available or 'none'}")
            target = step

        candidates = [target] + [s for s in sorted(self.all_steps(),
                                                   reverse=True)
                                 if s < target]
        last_error: Exception | None = None
        for candidate in candidates:
            try:
                tree, tier = self._restore_step(candidate)
            except (TornManifestError, ChunkCorruptionError,
                    FileNotFoundError) as exc:
                self._m_integrity.inc()
                last_error = exc
                continue
            elapsed = time.monotonic() - t0
            self.last_restore = {"step": candidate, "tier": tier,
                                 "seconds": elapsed,
                                 "fallback": candidate != target}
            slo.observe("restore", elapsed, key=self.directory)
            if abstract is not None:
                tree = self._apply_abstract(tree, abstract)
            return tree
        raise CheckpointIntegrityError(
            f"no intact checkpoint restorable under {self.directory} "
            f"(tried steps {candidates}): {last_error}")

    def _restore_step(self, step: int):
        """Restore one exact step through the tiers, verifying every
        hash; raises on the first unrecoverable integrity problem."""
        manifest = None
        if self.staging is not None:
            try:
                manifest = self.staging.get_manifest(step)
            except (TornManifestError, FileNotFoundError):
                manifest = None
        if manifest is None:
            manifest = self.remote.get_manifest(step)

        used_remote = False
        leaves = []
        for spec in manifest["leaves"]:
            buf = bytearray()
            for digest in spec["chunks"]:
                piece = None
                if self.staging is not None and \
                        self.staging.has_chunk(digest):
                    try:
                        piece = self.staging.get_chunk(digest)
                        self._m_tier_hits.labels(tier="staging").inc()
                    except ChunkCorruptionError:
                        piece = None
                if piece is None:
                    piece = self.remote.get_chunk(digest)
                    self._m_tier_hits.labels(tier="remote").inc()
                    used_remote = True
                buf.extend(piece)
            arr = np.frombuffer(bytes(buf), dtype=np.dtype(spec["dtype"]))
            leaves.append(arr.reshape(tuple(spec["shape"])))
        tree = _unflatten(manifest["tree"], leaves)
        return tree, ("remote" if used_remote else "staging"
                      if self.staging is not None else "remote")

    @staticmethod
    def _apply_abstract(tree, abstract):
        """Place restored host arrays per an abstract pytree of
        ShapeDtypeStructs (sharding-aware when jax is importable)."""
        import jax

        def place(x, a):
            sharding = getattr(a, "sharding", None)
            if sharding is not None:
                return jax.device_put(jax.numpy.asarray(x), sharding)
            return jax.numpy.asarray(x)

        return jax.tree.map(place, tree, abstract)

    # ---- lifecycle ---------------------------------------------------------

    def pending(self) -> list[SaveHandle]:
        with self._lock:
            return list(self._inflight)

    def wait(self) -> None:
        """Block until every queued save has committed (or failed)."""
        while True:
            with self._lock:
                handles = list(self._inflight)
            if not handles and self._queue.empty():
                return
            for h in handles:
                h.wait()
            if self._queue.empty() and not self.pending():
                return

    def close(self) -> None:
        """Block on in-flight commits, then stop the uploader. After
        close there are no orphaned ``.tmp`` files in either tier."""
        if self._closed:
            return
        self._closed = True
        self.wait()
        self._queue.put(None)
        self._worker.join(timeout=30)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
