"""Checkpoint/resume for in-notebook training — Orbax over PVC or GCS.

The reference's checkpoint story is PVC persistence: ``$HOME`` survives
stop/start cycles (SURVEY.md §5; base image ``01-copy-tmp-home``). This
module completes the TPU side: a thin, opinionated wrapper over Orbax
that handles the slice realities —

- **Multi-host**: every worker participates in the save (Orbax writes a
  per-process shard and the coordinator commits atomically), so a
  ``gs://`` path works from an N-host slice out of the box. A PVC path
  works single-host (RWO volumes mount on one worker).
- **Preemption/culling**: saves are atomic (Orbax's commit protocol), so
  a slice culled or restarted mid-save resumes from the last complete
  step; ``restore_latest`` finds it.
- **Sharding-aware restore**: pass an ``abstract`` pytree (from
  ``jax.eval_shape`` + shardings) and arrays come back placed on the
  mesh, not gathered to host.

For the drain-path async fabric (snapshot-then-ack, tiered restore) see
:class:`kubeflow_tpu.checkpoint.CheckpointFabric` — same surface, plus
``save_async``.

Usage in a notebook::

    mgr = CheckpointManager("gs://bucket/run7", keep=3)
    step = mgr.latest_step()
    if step is not None:
        params = mgr.restore(step, abstract=jax.eval_shape(init, key))
    ...
    mgr.save(step, params)          # every worker calls this
"""

from __future__ import annotations

import os
from typing import Any


class CheckpointManager:
    """Orbax CheckpointManager with slice-friendly defaults."""

    def __init__(self, directory: str, *, keep: int = 3,
                 save_interval_steps: int = 1):
        import orbax.checkpoint as ocp

        self._ocp = ocp
        # Local paths must be absolute for Orbax; bucket schemes pass
        # through (gs:// via tensorstore).
        if "://" not in directory:
            directory = os.path.abspath(directory)
        self.directory = directory
        self.manager = ocp.CheckpointManager(
            directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=keep,
                save_interval_steps=save_interval_steps,
                # keep the step directories atomic-committed; partial
                # writes from a culled slice are invisible to restore.
                enable_async_checkpointing=True,
            ),
        )

    # ---- save ----------------------------------------------------------------

    def save(self, step: int, pytree: Any, *, force: bool = False) -> bool:
        """Save (async). Every process of a multi-host slice must call
        this with its shard of the (possibly sharded) pytree."""
        return self.manager.save(
            step,
            args=self._ocp.args.StandardSave(pytree),
            force=force,
        )

    def wait(self) -> None:
        """Block until in-flight async saves committed (call before exit)."""
        self.manager.wait_until_finished()

    # ---- restore -------------------------------------------------------------

    def latest_step(self) -> int | None:
        return self.manager.latest_step()

    def all_steps(self) -> list[int]:
        return sorted(self.manager.all_steps())

    def restore(self, step: int | None = None, *, abstract: Any = None) -> Any:
        """Restore ``step`` (default latest). With ``abstract`` (a pytree
        of ShapeDtypeStruct, e.g. from ``jax.eval_shape``, optionally
        carrying ``sharding``), arrays restore sharded onto the mesh."""
        if step is None:
            step = self.latest_step()
            if step is None:
                raise FileNotFoundError(
                    f"no checkpoints under {self.directory}")
        else:
            # Validate up front: a nonexistent step otherwise surfaces
            # as a raw Orbax/tensorstore path error deep in restore.
            available = self.all_steps()
            if step not in available:
                raise FileNotFoundError(
                    f"no checkpoint for step {step} under "
                    f"{self.directory}; available steps: "
                    f"{available or 'none'}")
        if abstract is not None:
            args = self._ocp.args.StandardRestore(abstract)
        else:
            args = self._ocp.args.StandardRestore()
        return self.manager.restore(step, args=args)

    def close(self) -> None:
        self.wait()
        self.manager.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
