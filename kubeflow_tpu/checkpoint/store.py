"""Content-addressed chunk store + atomic manifest commit — the fabric's
durable format.

A checkpoint step is never one opaque blob. It is:

- **chunks/**: content-hashed segments (``sha256(data)`` names the file),
  shared across steps — an unchanged leaf hashes to chunks the store
  already has, so a *delta* save writes only what changed;
- **manifests/manifest-<step>.json**: the step's leaf table (keypath →
  dtype/shape/chunk hashes) plus the tree skeleton, self-checksummed
  (``integrity`` = sha256 of the canonical body) so a torn or truncated
  manifest is *detectable*, not just malformed;
- **COMMITTED**: the last-committed-step pointer, advanced by a
  two-phase rename (write ``.tmp`` + fsync, then ``os.replace``) — the
  only mutation restore trusts. A crash anywhere before the rename
  leaves the previous step committed and the half-written one invisible.

Two tiers speak this format (:class:`DirectoryTier` for the durable
"object store" side, :class:`StagingTier` adding LRU-by-bytes eviction
for the host-local copy); :mod:`kubeflow_tpu.checkpoint.fabric` moves
chunks between them. Fault hooks (``faults=``) are duck-typed so
:class:`kubeflow_tpu.testing.fakekube.FaultPlan` can tear manifests,
corrupt reads, and slow a tier without this module importing testing
code.
"""

from __future__ import annotations

import hashlib
import json
import os
import time


class TornManifestError(Exception):
    """A manifest that is unreadable, truncated, or fails its own
    checksum — restore must refuse it and fall back, never parse around
    it."""


class ChunkCorruptionError(Exception):
    """A chunk whose bytes no longer hash to their name."""


def chunk_hash(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


def split_chunks(data: bytes, chunk_bytes: int) -> list[bytes]:
    if chunk_bytes <= 0:
        return [data]
    return [data[i:i + chunk_bytes]
            for i in range(0, max(len(data), 1), chunk_bytes)]


# ---- manifest encode/decode ----------------------------------------------------


def encode_manifest(manifest: dict) -> bytes:
    """Canonical JSON + a self-checksum trailer. The checksum covers the
    body exactly as serialized, so any truncation, bit-flip, or partial
    replication is caught by :func:`decode_manifest`."""
    body = dict(manifest)
    body.pop("integrity", None)
    canonical = json.dumps(body, sort_keys=True, separators=(",", ":"))
    body["integrity"] = hashlib.sha256(canonical.encode()).hexdigest()
    return json.dumps(body, sort_keys=True,
                      separators=(",", ":")).encode()


def decode_manifest(raw: bytes) -> dict:
    """Parse + verify; raises :class:`TornManifestError` on anything
    short of a bit-perfect manifest."""
    try:
        body = json.loads(raw)
    except (ValueError, UnicodeDecodeError) as exc:
        raise TornManifestError(f"unparseable manifest: {exc}") from exc
    if not isinstance(body, dict):
        raise TornManifestError("manifest is not an object")
    integrity = body.pop("integrity", None)
    canonical = json.dumps(body, sort_keys=True, separators=(",", ":"))
    want = hashlib.sha256(canonical.encode()).hexdigest()
    if integrity != want:
        raise TornManifestError(
            f"manifest checksum mismatch (got {integrity!r})")
    return body


# ---- fault-hook helpers --------------------------------------------------------
# The fabric's storage faults are duck-typed probes on whatever object
# the caller passes as ``faults`` (production passes None; the chaos
# soak passes its FaultPlan). A missing method means "fault never fires".


def _probe(faults, name: str, *args) -> bool:
    fn = getattr(faults, name, None)
    return bool(fn(*args)) if callable(fn) else False


def _delay(faults, tier: str) -> None:
    fn = getattr(faults, "storage_delay", None)
    if callable(fn):
        d = fn(tier)
        if d and d > 0:
            time.sleep(d)  # kftpu: ignore[no-blocking-in-async] tier ops run on the ckpt-uploader thread or via asyncio.to_thread


# ---- tiers ---------------------------------------------------------------------


class DirectoryTier:
    """One tier of the fabric over a directory: the durable "object
    store" shape. ``op_delay`` is the bench's simulated per-operation
    round trip (an object store is never free); ``faults`` is the
    duck-typed storage-fault hook."""

    name = "remote"

    def __init__(self, directory: str, *, op_delay: float = 0.0,
                 faults=None):
        self.directory = os.path.abspath(directory) \
            if "://" not in directory else directory
        self.op_delay = op_delay
        self.faults = faults
        self._chunk_dir = os.path.join(self.directory, "chunks")
        self._manifest_dir = os.path.join(self.directory, "manifests")
        os.makedirs(self._chunk_dir, exist_ok=True)
        os.makedirs(self._manifest_dir, exist_ok=True)

    # -- plumbing ------------------------------------------------------------

    def _pause(self) -> None:
        if self.op_delay > 0:
            time.sleep(self.op_delay)  # kftpu: ignore[no-blocking-in-async] tier ops run on the ckpt-uploader thread or via asyncio.to_thread
        _delay(self.faults, self.name)

    def _chunk_path(self, digest: str) -> str:
        return os.path.join(self._chunk_dir, digest)

    def _manifest_path(self, step: int) -> str:
        return os.path.join(self._manifest_dir, f"manifest-{step}.json")

    @staticmethod
    def _replace(tmp: str, final: str) -> None:
        with open(tmp, "rb") as fh:  # fsync before the rename: the
            os.fsync(fh.fileno())    # two-phase commit's first phase
        os.replace(tmp, final)

    # -- chunks --------------------------------------------------------------

    def has_chunk(self, digest: str) -> bool:
        return os.path.exists(self._chunk_path(digest))

    def put_chunk(self, digest: str, data: bytes) -> int:
        """Write one content-addressed chunk (idempotent). Returns bytes
        written (0 when the store already had it — the delta path)."""
        self._pause()
        path = self._chunk_path(digest)
        if os.path.exists(path):
            return 0
        tmp = path + ".tmp"
        with open(tmp, "wb") as fh:
            fh.write(data)
        self._replace(tmp, path)
        return len(data)

    def get_chunk(self, digest: str) -> bytes:
        """Read + verify one chunk; raises :class:`ChunkCorruptionError`
        when the bytes no longer match their name (bit rot, injected
        corruption)."""
        self._pause()
        with open(self._chunk_path(digest), "rb") as fh:
            data = fh.read()
        if _probe(self.faults, "should_corrupt_read", self.name):
            data = (b"\x00" if not data else
                    bytes([data[0] ^ 0xFF]) + data[1:])
        if chunk_hash(data) != digest:
            raise ChunkCorruptionError(
                f"{self.name} chunk {digest[:12]}… failed verification")
        return data

    # -- manifests + commit --------------------------------------------------

    def put_manifest(self, step: int, manifest: dict) -> None:
        """Two-phase manifest write. The torn-manifest fault emulates a
        non-atomic backend (partial object-store replication): the final
        path receives a truncated body — exactly what restore's checksum
        must catch."""
        self._pause()
        raw = encode_manifest(manifest)
        path = self._manifest_path(step)
        if _probe(self.faults, "should_tear_manifest", self.name):
            with open(path, "wb") as fh:
                fh.write(raw[:max(1, len(raw) // 2)])
            return
        tmp = path + ".tmp"
        with open(tmp, "wb") as fh:
            fh.write(raw)
        self._replace(tmp, path)

    def get_manifest(self, step: int) -> dict:
        self._pause()
        path = self._manifest_path(step)
        if not os.path.exists(path):
            raise FileNotFoundError(
                f"no manifest for step {step} under {self.directory}")
        with open(path, "rb") as fh:
            return decode_manifest(fh.read())

    def manifest_steps(self) -> list[int]:
        steps = []
        try:
            names = os.listdir(self._manifest_dir)
        except OSError:
            return []
        for n in names:
            if n.startswith("manifest-") and n.endswith(".json"):
                try:
                    steps.append(int(n[len("manifest-"):-len(".json")]))
                except ValueError:
                    continue
        return sorted(steps)

    def commit(self, step: int) -> None:
        """Advance the committed pointer — THE commit, via two-phase
        rename. Everything before this call is invisible to restore."""
        self._pause()
        pointer = os.path.join(self.directory, "COMMITTED")
        tmp = pointer + ".tmp"
        with open(tmp, "w") as fh:
            fh.write(str(step))
        self._replace(tmp, pointer)

    def committed_step(self) -> int | None:
        self._pause()
        pointer = os.path.join(self.directory, "COMMITTED")
        try:
            with open(pointer) as fh:
                return int(fh.read().strip())
        except (OSError, ValueError):
            return None

    # -- retention -----------------------------------------------------------

    def drop_manifest(self, step: int) -> None:
        try:
            os.remove(self._manifest_path(step))
        except OSError:
            pass

    def gc(self, live_hashes: set[str]) -> int:
        """Delete chunks no retained manifest references; returns bytes
        reclaimed. Callers only invoke this AFTER a commit, so the
        previous committed step's chunks are never collected while it is
        still the restore guarantee."""
        freed = 0
        try:
            names = os.listdir(self._chunk_dir)
        except OSError:
            return 0
        for digest in names:
            if digest.endswith(".tmp") or digest in live_hashes:
                continue
            path = self._chunk_path(digest)
            try:
                freed += os.path.getsize(path)
                os.remove(path)
            except OSError:
                continue
        return freed

    def bytes_used(self) -> int:
        total = 0
        for root in (self._chunk_dir, self._manifest_dir):
            try:
                for n in os.listdir(root):
                    try:
                        total += os.path.getsize(os.path.join(root, n))
                    except OSError:
                        continue
            except OSError:
                continue
        return total

    def orphaned_tmp_files(self) -> list[str]:
        """Leftover first-phase files — must be empty after close()."""
        out = []
        for root in (self.directory, self._chunk_dir, self._manifest_dir):
            try:
                out.extend(os.path.join(root, n) for n in os.listdir(root)
                           if n.endswith(".tmp"))
            except OSError:
                continue
        return out


class StagingTier(DirectoryTier):
    """The host-local staging copy: same format, bounded by
    ``max_bytes`` with LRU-by-bytes chunk eviction (touch on read). A
    parked replica restoring on the same node is served from here and
    never touches the remote tier."""

    name = "staging"

    def __init__(self, directory: str, *, max_bytes: int = 1 << 30,
                 faults=None):
        super().__init__(directory, faults=faults)
        self.max_bytes = max_bytes
        # digest → (last-touch monotonic, size); rebuilt lazily from disk
        # so a new process over an existing staging dir still evicts.
        self._lru: dict[str, tuple[float, int]] = {}
        for digest in (os.listdir(self._chunk_dir)
                       if os.path.isdir(self._chunk_dir) else ()):
            if not digest.endswith(".tmp"):
                try:
                    size = os.path.getsize(self._chunk_path(digest))
                except OSError:
                    continue
                self._lru[digest] = (0.0, size)

    def put_chunk(self, digest: str, data: bytes) -> int:
        written = super().put_chunk(digest, data)
        self._lru[digest] = (time.monotonic(),
                             self._lru.get(digest, (0, len(data)))[1]
                             if written == 0 else len(data))
        self._evict()
        return written

    def get_chunk(self, digest: str) -> bytes:
        data = super().get_chunk(digest)
        if digest in self._lru:
            self._lru[digest] = (time.monotonic(), self._lru[digest][1])
        return data

    def commit(self, step: int) -> None:
        # Stale-staging fault: the local pointer silently fails to
        # advance (node-local disk lagging the object store). Restore
        # must never trust a stale staging pointer over the remote one.
        if _probe(self.faults, "should_skip_staging_commit"):
            return
        super().commit(step)

    def _evict(self) -> None:
        used = sum(size for _, size in self._lru.values())
        if used <= self.max_bytes:
            return
        for digest, (_, size) in sorted(self._lru.items(),
                                        key=lambda kv: kv[1][0]):
            if used <= self.max_bytes:
                break
            try:
                os.remove(self._chunk_path(digest))
            except OSError:
                pass
            used -= size
            self._lru.pop(digest, None)
