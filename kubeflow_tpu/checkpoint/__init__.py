"""Checkpoint fabric: crash-safe async multi-tier checkpoints.

Two save paths share one package:

- :class:`CheckpointManager` — the Orbax wrapper for in-notebook
  training loops (PVC or ``gs://`` paths, sharded restore);
- :class:`CheckpointFabric` — the drain-path fabric: snapshot-then-ack
  (``save_async``), content-hashed chunks with an atomic manifest
  commit, and tiered restore (host-local staging → object store) with
  integrity fallback to the previous committed step.

The :class:`kubeflow_tpu.sdk.CheckpointGuard` speaks to either: with a
fabric it acks the drain at snapshot and reports the durable commit via
the migration protocol's ``checkpoint-committed-at`` mark; with a plain
manager it falls back to the synchronous save-then-ack path.
"""

from .fabric import (
    CheckpointFabric,
    CheckpointIntegrityError,
    SaveHandle,
)
from .manager import CheckpointManager
from .store import (
    ChunkCorruptionError,
    DirectoryTier,
    StagingTier,
    TornManifestError,
)

__all__ = [
    "CheckpointFabric",
    "CheckpointIntegrityError",
    "CheckpointManager",
    "ChunkCorruptionError",
    "DirectoryTier",
    "SaveHandle",
    "StagingTier",
    "TornManifestError",
]
