"""TPU inference serving: the second workload class (ISSUE 11).

Everything the control plane scheduled before this package was a
notebook — interactive, one user each. An
:class:`~kubeflow_tpu.api.inferenceservice` CR is the other shape the
north star needs: always-on model serving under bursty traffic from many
users. The pieces, least pure on top:

- :mod:`kubeflow_tpu.serving.autoscaler` — pure replica-count policy
  (request-rate/concurrency driven, min/max bounds, scale-to-zero after
  an idle window, scale-down stabilization). Property-tested clock-free.
- :mod:`kubeflow_tpu.serving.engine` — the JAX serving loop: batched
  ``jit`` forward with continuous batching on the
  ``parallel/mesh.py`` substrate, plus the park/warm-restore state the
  scale-to-zero story rides (parked weights + retained compiled fn make
  scale-from-zero a device transfer, not a cold compile).
- :mod:`kubeflow_tpu.serving.loadgen` — seeded, trace-driven open-loop
  load generator (arrivals don't wait for completions — queueing shows
  up in p99, exactly like production traffic).
- :mod:`kubeflow_tpu.serving.controller` — the InferenceService
  reconciler: per-replica slice StatefulSets + a Service, each replica
  admitted through the fleet scheduler as a gang
  (``TpuFleetScheduler.serving_admission`` — one chip ledger with the
  notebooks), scale-to-zero parking through a checkpoint drain (the PR 6
  park idiom), and warm restore on the first burst.

Kill switch: ``KFTPU_SERVING=off`` (:func:`serving_enabled`) restores
the PR 5–8 notebook-only control plane byte-for-byte — no serving
controller, no serving webhooks, no serving routes.
"""

from __future__ import annotations

import os

SERVING_ENV = "KFTPU_SERVING"


def serving_enabled(environ=os.environ) -> bool:
    """The ``KFTPU_SERVING`` master switch — anything but off/false/0/no
    leaves the serving workload class on (it is inert until an
    InferenceService CR exists)."""
    return environ.get(SERVING_ENV, "on").strip().lower() not in (
        "off", "false", "0", "no", "disabled",
    )
