"""Pure replica-autoscaling policy for InferenceServices.

Knative-KPA-shaped, reduced to a clock-free function of
``(config, observed signals, current state, now)`` so tier-1 can
property-test it under seeded random traffic without an event loop:

- **demand**: replicas needed = max(rate/target_rate,
  inflight/target_inflight), ceil'd — whichever signal is hotter wins
  (a slow model saturates on concurrency long before rate).
- **bounds**: the recommendation is always clamped to
  ``[min_replicas, max_replicas]``.
- **scale-up is immediate**: burst traffic must not wait out a window.
- **scale-down is stabilized**: the effective recommendation is the
  MAXIMUM over the trailing ``scale_down_stabilization_seconds`` — one
  quiet sample between two bursts must not flap replicas (and with them
  whole TPU slice gangs) down and back up.
- **scale-to-zero is a separate, stricter gate**: only with
  ``min_replicas == 0``, zero demand, AND no request for
  ``scale_to_zero_after_seconds`` — an idle *window*, not an idle
  sample. A service that has never seen a request idles from
  ``created_at``.
- **SLO-driven scaling (v2, ISSUE 19)**: when the SLO engine is on,
  ``Signals.burn_rate`` carries the ``serving_latency`` error-budget
  burn rate and the policy scales to protect the *objective*, not a
  proxy: critical burn forces an aggressive step-up, warning burn adds
  a replica, any burn above budget blocks scale-down. With
  ``burn_rate=None`` (``KFTPU_SLO`` off) every code path below is
  byte-for-byte the raw rate/concurrency policy — the kill-switch test
  pins that.

The ledger is deliberately not consulted here: the fleet scheduler owns
chips. The autoscaler says how many replicas the service *wants*; each
wanted replica then bids through
``TpuFleetScheduler.serving_admission`` and may sit Queued — desired
and admitted are different numbers, and the controller surfaces both.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field


@dataclass(frozen=True)
class AutoscalerConfig:
    min_replicas: int = 0
    max_replicas: int = 1
    # Demand targets: how much load one replica is sized for.
    target_rate_per_replica: float = 8.0       # requests/sec
    target_inflight_per_replica: float = 4.0   # concurrent requests
    # Scale-to-zero: only after this long with no request (and only when
    # min_replicas == 0).
    scale_to_zero_after_seconds: float = 300.0
    # Scale-down hold: the recommendation may only drop once it has been
    # below the current count for this long.
    scale_down_stabilization_seconds: float = 60.0
    # SLO-driven scaling thresholds over the serving_latency error
    # budget — literals deliberately mirror runtime/slo.py's paging
    # calibration (CRITICAL_BURN / WARNING_BURN) without importing it:
    # this module stays pure and dependency-free for property tests.
    burn_critical: float = 14.4
    burn_warning: float = 6.0

    def __post_init__(self):
        if self.min_replicas < 0:
            raise ValueError("min_replicas must be >= 0")
        if self.max_replicas < max(1, self.min_replicas):
            raise ValueError(
                "max_replicas must be >= max(1, min_replicas); got "
                f"min={self.min_replicas} max={self.max_replicas}")


@dataclass(frozen=True)
class Signals:
    """Observed load, as stamped on the CR by the gateway/load driver."""

    rate: float = 0.0              # requests/sec (EWMA)
    inflight: float = 0.0          # concurrent requests right now
    last_request_at: float | None = None   # epoch seconds; None = never
    # serving_latency error-budget burn rate from the SLO engine's fast
    # window, or None when KFTPU_SLO is off. None keeps the decision
    # function byte-for-byte the raw rate/concurrency policy.
    burn_rate: float | None = None


@dataclass
class AutoscalerState:
    """Carried across decisions (the controller keeps one per service).
    ``window`` holds (t, raw recommendation) samples inside the
    stabilization window — the scale-down hold is its max."""

    window: list = field(default_factory=list)
    created_at: float = 0.0        # idle floor for never-hit services


@dataclass(frozen=True)
class Decision:
    replicas: int
    raw: int                       # unstabilized demand (diagnostics)
    reason: str


def _demand(cfg: AutoscalerConfig, signals: Signals) -> int:
    by_rate = (signals.rate / cfg.target_rate_per_replica
               if cfg.target_rate_per_replica > 0 else 0.0)
    by_inflight = (signals.inflight / cfg.target_inflight_per_replica
                   if cfg.target_inflight_per_replica > 0 else 0.0)
    need = max(by_rate, by_inflight)
    return int(math.ceil(need - 1e-9)) if need > 0 else 0


def _slo_demand(cfg: AutoscalerConfig, signals: Signals,
                current: int) -> int | None:
    """SLO-driven demand overlay: how many replicas the burn rate says
    we need, or ``None`` when the SLO signal is absent or the budget is
    healthy (burn <= 1 means the objective is being met — the raw
    policy decides alone, including scale-down)."""
    burn = signals.burn_rate
    if burn is None or burn <= 1.0:
        return None
    if burn >= cfg.burn_critical:
        # Paging-grade burn: step up hard (+50%, at least one replica)
        # — waiting for the rate signal to catch up is how p99 SLOs die.
        return current + max(1, math.ceil(current * 0.5))
    if burn >= cfg.burn_warning:
        return current + 1
    # Budget burning but below warning: hold the line — never scale
    # down while the objective is losing ground.
    return current


def desired_replicas(cfg: AutoscalerConfig, signals: Signals,
                     current: int, now: float,
                     state: AutoscalerState | None = None) -> Decision:
    """One autoscaling decision. Pure given (cfg, signals, current, now,
    state); mutates only ``state`` (the trailing window)."""
    state = state if state is not None else AutoscalerState(created_at=now)
    raw = _demand(cfg, signals)
    slo_need = _slo_demand(cfg, signals, current)
    demand = raw if slo_need is None else max(raw, slo_need)
    slo_driven = demand > raw      # the SLO overlay raised the ask
    floor = cfg.min_replicas
    # Any live demand keeps at least one replica even at min_replicas=0
    # — scale-to-zero is the stricter gate below, never a side effect of
    # a rate rounding to zero replicas.
    if demand > 0:
        floor = max(floor, 1)
    bounded = max(floor, min(cfg.max_replicas, max(demand, floor)))

    # Trailing-window stabilization: remember this sample, drop expired
    # ones, and never scale below the window's high-water mark.
    state.window.append((now, bounded))
    cutoff = now - cfg.scale_down_stabilization_seconds
    state.window[:] = [(t, r) for t, r in state.window if t >= cutoff]
    hold = max(r for _, r in state.window)

    if bounded >= current:
        if bounded > current:
            if slo_driven:
                # Stable strings (no live burn number): these land in
                # status under write-elision, same as the hold reasons.
                kind = ("critical"
                        if signals.burn_rate >= cfg.burn_critical
                        else "warning")
                return Decision(bounded, demand, "scale-up: "
                                f"serving_latency burn-rate {kind} (SLO)")
            return Decision(bounded, demand, "scale-up: demand "
                            f"{demand} replica(s)")
        if slo_driven and raw < current:
            return Decision(current, demand,
                            "hold: serving_latency burn above budget (SLO)")
        return Decision(current, demand, "steady")

    # Candidate scale-down. Zero is gated separately and harder.
    target = max(bounded, min(hold, current))
    if target == 0:
        last = signals.last_request_at
        idle_since = last if last is not None else state.created_at
        if signals.inflight > 0 or signals.rate > 0:
            return Decision(max(current, 1), demand,
                            "hold: live traffic blocks scale-to-zero")
        if now - idle_since < cfg.scale_to_zero_after_seconds:
            # Reason strings land in status and must stay STABLE while
            # the situation is unchanged — a live seconds counter here
            # would defeat the controller's status write-elision and
            # patch the CR every pass for the whole idle window.
            return Decision(max(current if current > 0 else 1,
                                max(floor, 1)), demand,
                            "hold: inside the scale-to-zero idle window "
                            f"({cfg.scale_to_zero_after_seconds:.0f}s)")
        return Decision(0, demand, "scale-to-zero: idle past the window")
    if target < current:
        return Decision(target, demand,
                        f"scale-down (stabilized over "
                        f"{cfg.scale_down_stabilization_seconds:.0f}s)")
    return Decision(current, demand, "hold: stabilization window")


def config_from_spec(scaling: dict, *,
                     default_target_rate: float = 8.0,
                     default_idle_window: float = 300.0,
                     default_stabilization: float = 60.0,
                     ) -> AutoscalerConfig:
    """spec.scaling → AutoscalerConfig with operator-level defaults for
    the knobs the CR leaves unset (cmd/envconfig.py serving_options)."""

    def _num(key: str, default: float) -> float:
        try:
            value = float(scaling.get(key, default))
        except (TypeError, ValueError):
            return default
        return value if value > 0 else default

    return AutoscalerConfig(
        min_replicas=max(0, int(scaling.get("minReplicas", 0) or 0)),
        max_replicas=max(1, int(scaling.get("maxReplicas", 1) or 1),
                         int(scaling.get("minReplicas", 0) or 0)),
        target_rate_per_replica=_num("targetRequestsPerReplica",
                                     default_target_rate),
        scale_to_zero_after_seconds=_num("scaleToZeroAfterSeconds",
                                         default_idle_window),
        scale_down_stabilization_seconds=_num(
            "scaleDownStabilizationSeconds", default_stabilization),
    )
