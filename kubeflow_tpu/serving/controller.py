"""InferenceService reconciler: CR → per-replica slice StatefulSets + a
Service, scaled by the pure autoscaler against the shared chip ledger.

The serving workload class end to end (ISSUE 11):

- each **replica** is a whole slice gang admitted through the SAME
  :class:`~kubeflow_tpu.scheduler.runtime.TpuFleetScheduler` as every
  notebook (``serving_admission`` — one ledger, one fair order; a
  queued serving replica preempts *idle notebooks* through the existing
  drain protocol via its serving-class priority, default "high");
- replica count follows :mod:`kubeflow_tpu.serving.autoscaler` over the
  observed-rate/inflight/last-request annotations the gateway stamps;
- **scale-to-zero parks, never bare-stops**: the controller requests a
  checkpoint (``park-requested``), the engine acks with the committed
  path/step, and only then do replicas scale to zero — replica 0's
  StatefulSet is kept at 0 replicas as the **parked warm standby**. The
  grace deadline (`park_grace_seconds`) is the ack-less fallback, the
  same chips-never-hostage contract as the PR 6 drain.
- **scale-from-zero warm-restores**: the first burst re-admits replica
  0 through the ledger and scales the parked StatefulSet back up with
  the parked checkpoint stamped into the pod env
  (``KFTPU_RESTORE_*``) — the engine restores weights instead of
  cold-initializing, which is the measured scale-from-zero win
  (``bench.py inference_serving``).
"""

from __future__ import annotations

import logging
import re
import time
from dataclasses import dataclass
from typing import Callable

from kubeflow_tpu.api import keys
from kubeflow_tpu.api import inferenceservice as isvcapi
from kubeflow_tpu.migration import protocol as migration
from kubeflow_tpu.runtime import slo
from kubeflow_tpu.runtime.apply import ApplyCache, informer_reader, reconcile_child
from kubeflow_tpu.runtime.errors import ApiError, Invalid, NotFound
from kubeflow_tpu.runtime.events import EventRecorder
from kubeflow_tpu.runtime.informer import OWNER_INDEX
from kubeflow_tpu.runtime.manager import Controller, Manager, Result, soonest
from kubeflow_tpu.runtime.metrics import Registry, global_registry
from kubeflow_tpu.runtime.objects import (
    annotations_of,
    deep_get,
    fmt_iso,
    get_meta,
    name_of,
    namespace_of,
    now_iso,
    parse_iso,
    set_controller_owner,
    uid_of,
)
from kubeflow_tpu.runtime.tracing import span
from kubeflow_tpu.serving.autoscaler import (
    AutoscalerState,
    Signals,
    config_from_spec,
    desired_replicas,
)

log = logging.getLogger(__name__)

STS_LABEL = keys.SERVING_REPLICA_STS_LABEL
WORKERS_SERVICE_SUFFIX = "-workers"

# Replica index from a replica StatefulSet name (`<svc>-r<i>[-s<j>]`).
_REPLICA_STS_RE = re.compile(r"-r(\d+)(?:-s\d+)?$")


@dataclass
class ServingOptions:
    """Env contract (cmd/envconfig.py serving_options). The DATACLASS
    default is off — bare construction keeps the PR 5–8 notebook-only
    control plane byte-for-byte; production gets ``enabled`` from
    ``KFTPU_SERVING`` (default on)."""

    enabled: bool = False
    cluster_domain: str = "cluster.local"
    controller_namespace: str = "kubeflow-tpu"
    serving_port: int = isvcapi.DEFAULT_CONTAINER_PORT
    # Serving-class fleet priority (overridable per CR via the
    # serving.kubeflow.org/priority annotation): "high" — an always-on
    # service outranks interactive notebooks, so a serving burst drains
    # idle notebooks through the existing preemption path.
    priority: int = 100
    # Autoscale cadence: the safety-net requeue; load-annotation watch
    # events drive reconciles sooner.
    autoscale_period_seconds: float = 5.0
    # Park drain grace: how long scale-to-zero waits for the engine's
    # checkpoint ack before parking without a fresh checkpoint.
    park_grace_seconds: float = migration.DEFAULT_DRAIN_GRACE_SECONDS
    # Operator-level defaults for spec.scaling knobs the CR leaves unset.
    default_target_rate: float = 8.0
    default_idle_window: float = 300.0
    default_stabilization: float = 60.0
    # SLO-driven autoscaling (ISSUE 19): feed the serving_latency
    # burn rate into the autoscaler when the SLO engine is installed
    # and enabled. Kill switch: off restores the raw rate/concurrency
    # policy byte-for-byte (KFTPU_SERVING_SLO_AUTOSCALE).
    slo_autoscale: bool = True


class InferenceServiceReconciler:
    def __init__(
        self,
        kube,
        options: ServingOptions | None = None,
        *,
        registry: Registry | None = None,
        clock: Callable[[], float] = time.time,
    ):
        self.kube = kube
        self.opts = options or ServingOptions()
        self.clock = clock
        self.recorder = EventRecorder(kube, "inferenceservice-controller",
                                      registry=registry)
        # The shared fleet scheduler (set by setup_serving_controller).
        # None — bare-reconciler tests, KFTPU_SCHEDULER=off, or no fleet
        # — means every replica admits unconditionally.
        self._scheduler = None
        self._sts_informer = None
        self._child_informers: dict[str, object] = {}
        self._reader = informer_reader(self._child_informers)
        self._apply_cache = ApplyCache()
        # key → AutoscalerState (the scale-down stabilization window).
        self._states: dict[tuple, AutoscalerState] = {}
        # key → last status dict we wrote (write elision; conditions
        # excluded — see _update_status).
        self._last_status: dict[tuple, dict] = {}
        # key → highest replica count ever materialised (scale-down GC
        # + delete-time release walk this).
        self._high_water: dict[tuple, int] = {}
        registry = registry or global_registry
        self.m_desired = registry.gauge(
            "inference_replicas_desired",
            "Replicas the autoscaler wants per InferenceService",
            ["service"])
        self.m_admitted = registry.gauge(
            "inference_replicas_admitted",
            "Replicas holding fleet admission per InferenceService",
            ["service"])
        self.m_scale_events = registry.counter(
            "inference_scale_events_total",
            "Autoscaler scale events", ["direction"])  # up|down|zero
        self.m_parks = registry.counter(
            "inference_parks_total",
            "Scale-to-zero parks (warm standby retained)")
        self.m_warm_restores = registry.counter(
            "inference_warm_restores_total",
            "Scale-from-zero restores from a parked warm standby")
        self.m_scale_from_zero = registry.histogram(
            "inference_scale_from_zero_seconds",
            "Park → first replica admitted again")

    # ---- reconcile --------------------------------------------------------------

    async def reconcile(self, key) -> Result | None:
        ns, name = key
        with span("cache_read"):
            isvc = await self.kube.get_or_none("InferenceService", name, ns)
        if isvc is None or get_meta(isvc).get("deletionTimestamp"):
            await self._release_all(key)
            self._states.pop(tuple(key), None)
            self._high_water.pop(tuple(key), None)
            self._last_status.pop(tuple(key), None)
            self.m_desired.labels(service=f"{ns}/{name}").set(0)
            self.m_admitted.labels(service=f"{ns}/{name}").set(0)
            return None  # children die by ownerReference cascade
        try:
            ms = isvcapi.multi_slice_of(isvc)
        except Invalid as e:
            await self._event(isvc, "Warning", "InvalidSpec", str(e))
            return None

        now = self.clock()
        annotations = annotations_of(isvc)
        skey = (ns, name)
        cfg = config_from_spec(
            isvcapi.scaling_of(isvc),
            default_target_rate=self.opts.default_target_rate,
            default_idle_window=self.opts.default_idle_window,
            default_stabilization=self.opts.default_stabilization)
        rate = _safe_float(annotations.get(
            isvcapi.OBSERVED_RATE_ANNOTATION))
        per_model = isvcapi.model_rates(annotations)
        if per_model:
            # The multiplexing breakdown is also a load signal: a
            # gateway that only stamps per-model rates still scales the
            # service (and a stale aggregate never UNDER-counts it).
            rate = max(rate, sum(per_model.values()))
        signals = Signals(
            rate=rate,
            inflight=_safe_float(annotations.get(
                isvcapi.OBSERVED_INFLIGHT_ANNOTATION)),
            last_request_at=parse_iso(annotations.get(
                isvcapi.LAST_REQUEST_AT_ANNOTATION) or ""),
            burn_rate=self._serving_burn_rate())
        state = self._states.get(skey)
        if state is None:
            created = parse_iso(
                get_meta(isvc).get("creationTimestamp") or "")
            state = self._states[skey] = AutoscalerState(
                created_at=created if created is not None else now)

        current = self._current_replicas(isvc)
        parked = isvcapi.PARKED_AT_ANNOTATION in annotations
        with span("autoscale", service=f"{ns}/{name}", current=current,
                  rate=signals.rate, inflight=signals.inflight):
            decision = desired_replicas(cfg, signals, current, now, state)
        desired = decision.replicas
        self.m_desired.labels(service=f"{ns}/{name}").set(desired)

        requeue = Result(requeue_after=self.opts.autoscale_period_seconds)
        park_requeue: Result | None = None
        admitted = queued = 0
        if desired == 0:
            if current > 0 or isvcapi.PARK_REQUESTED_ANNOTATION \
                    in annotations:
                # Scale-to-zero NEVER bare-stops: the park drain asks
                # the engine for a committed checkpoint first; the
                # grace deadline is the ack-less fallback.
                park_requeue = await self._drain_to_park(
                    isvc, ms, now, annotations)
            elif not parked and (self._high_water.get(skey, 0) > 0
                                 or self._booked_high(skey) > 0):
                # Already at zero without a park mark (e.g. restart):
                # make sure nothing still holds chips.
                await self._release_from(skey, 0)
        else:
            if parked or isvcapi.PARK_REQUESTED_ANNOTATION in annotations:
                await self._cancel_park(isvc, ns, name, parked=parked,
                                        now=now)
            admitted, queued = await self._scale_to(
                isvc, ms, desired, now, parked=parked)
        self.m_admitted.labels(service=f"{ns}/{name}").set(admitted)

        with span("apply_stage", stage="services"):
            await self._ensure(isvc, self._generate_service(isvc))
            if ms is not None and (ms.slice.multi_host or ms.multi):
                await self._ensure(
                    isvc, self._generate_headless_service(isvc))

        with span("status"):
            await self._update_status(
                isvc, ms, desired=desired, admitted=admitted,
                queued=queued, decision=decision, parked=parked)
        return soonest(requeue, park_requeue)

    def _serving_burn_rate(self) -> float | None:
        """The serving_latency error-budget burn rate from the process
        SLO engine's fast window, or None when SLO-driven autoscaling
        is off (kill switch) or no enabled engine is installed — None
        keeps the autoscaler byte-for-byte the raw-signal policy."""
        if not self.opts.slo_autoscale:
            return None
        engine = slo.current()
        if engine is None or not engine.enabled:
            return None
        try:
            # The engine's own clock, not ours: observations were
            # stamped on it, and the two can differ under test clocks.
            return engine.burn_rate("serving_latency", "5m")
        except KeyError:
            return None

    # ---- scale up / steady -------------------------------------------------------

    async def _scale_to(self, isvc: dict, ms, desired: int,
                        now: float, *, parked: bool = False,
                        ) -> tuple[int, int]:
        """Bid ``desired`` replicas against the chip ledger and
        materialise the admitted ones. Returns (admitted, queued)."""
        ns, name = namespace_of(isvc), name_of(isvc)
        skey = (ns, name)
        annotations = annotations_of(isvc)
        priority = self.opts.priority
        raw = annotations.get(isvcapi.PRIORITY_ANNOTATION)
        if raw:
            from kubeflow_tpu.scheduler import parse_priority

            priority = parse_priority(raw)
        restore = isvcapi.parked_checkpoint(annotations)
        admitted = queued = 0
        for i in range(desired):
            rkey = isvcapi.replica_key(ns, name, i)
            running = self._replica_running(isvc, ms, i)
            admission = None
            if self._scheduler is not None and ms is not None:
                admission = await self._scheduler.serving_admission(
                    rkey, ms, namespace=ns, priority=priority,
                    running=running,
                    flex_pool=annotations.get(
                        f"{isvcapi.FLEX_POOL_ANNOTATION_PREFIX}{i}"))
            if admission is None or admission.admitted:
                admitted += 1
                # ``parked`` is the CR state THIS reconcile read — not a
                # live StatefulSet count, which lags the informer and
                # would double-fire the warm branch (and its metric) on
                # the follow-up reconcile _cancel_park's patch triggers.
                warm = parked and i == 0 and restore is not None
                if warm:
                    # Scale-from-zero through the parked standby: the
                    # kept StatefulSet scales back up with the parked
                    # checkpoint as its restore hint — a weight restore,
                    # not a cold model init.
                    with span("warm_restore", service=f"{ns}/{name}",
                              checkpoint=restore[0]):
                        self.m_warm_restores.inc()
                        parked_at = parse_iso(annotations.get(
                            isvcapi.PARKED_AT_ANNOTATION) or "")
                        if parked_at is not None:
                            self.m_scale_from_zero.observe(
                                max(0.0, now - parked_at))
                        await self._apply_replica(isvc, ms, i,
                                                  restore=restore)
                        # The park is consumed NOW — clearing parked-at
                        # any earlier (e.g. while the replica still
                        # queues for chips) would skip this branch and
                        # its metrics on the follow-up reconcile.
                        try:
                            await self.kube.patch(
                                "InferenceService", name,
                                {"metadata": {"annotations": {
                                    isvcapi.PARKED_AT_ANNOTATION: None,
                                }}}, ns)
                        except ApiError as exc:
                            log.debug("parked-at clear for %s/%s after "
                                      "warm restore failed (re-cleared "
                                      "next pass): %s", ns, name, exc)
                        await self._event(
                            isvc, "Normal", "WarmRestored",
                            f"Scale-from-zero: replica 0 restoring from "
                            f"parked checkpoint {restore[0]}"
                            + (f" @ step {restore[1]}"
                               if restore[1] is not None else ""))
                else:
                    await self._apply_replica(isvc, ms, i, restore=restore)
            else:
                queued += 1
                if not running:
                    await self._park_replica_sts(isvc, ms, i,
                                                 delete=False)
        if parked and admitted > 0 and restore is None:
            # A checkpoint-less park (grace fallback) coming back up:
            # there is no warm branch to consume the parked-at mark, so
            # clear it here — a stale mark would skew the NEXT cycle's
            # scale-from-zero histogram.
            try:
                await self.kube.patch(
                    "InferenceService", name,
                    {"metadata": {"annotations": {
                        isvcapi.PARKED_AT_ANNOTATION: None}}}, ns)
            except ApiError as exc:
                log.debug("stale parked-at clear for %s/%s failed "
                          "(re-cleared next pass): %s", ns, name, exc)
        await self._sync_flex_markers(isvc, desired)
        recorded = self._high_water.get(skey, 0)
        if desired > recorded and recorded:
            self.m_scale_events.labels(direction="up").inc()
        elif desired < recorded:
            self.m_scale_events.labels(direction="down").inc()
        # GC/release against CLUSTER truth, not just the in-memory
        # high-water: a controller restart forgets the old replica
        # count, and replicas above the first post-restart desired would
        # otherwise keep their StatefulSets (and pods) forever while the
        # fresh ledger resells their chips.
        prev_high = max(recorded, self._observed_high(isvc))
        if desired < prev_high:
            await self._release_from(skey, desired, high=prev_high)
            await self._gc_replicas(isvc, ms, desired, prev_high)
        # kftpu: ignore[await-race] _scale_to runs only from this service's own reconcile (per-key workqueue serialization); skey entries race no one
        self._high_water[skey] = desired
        return admitted, queued

    async def _apply_replica(self, isvc: dict, ms, replica: int,
                             *, restore=None) -> None:
        for slice_id in range(ms.num_slices if ms else 1):
            with span("build_children", kind="StatefulSet",
                      replica=replica, slice=slice_id):
                sts = self.generate_statefulset(
                    isvc, ms, replica, slice_id=slice_id, restore=restore)
            if self._scheduler is not None:
                flex = self._scheduler.flex_node_selectors(
                    isvcapi.replica_key(namespace_of(isvc),
                                        name_of(isvc), replica))
                if flex:
                    sts["spec"]["template"]["spec"].setdefault(
                        "nodeSelector", {}).update(flex)
            await self._ensure(isvc, sts)

    def _replica_running(self, isvc: dict, ms, replica: int) -> bool:
        sts = self._live_sts(isvc, ms, replica)
        return sts is not None and (
            deep_get(sts, "spec", "replicas") or 0) > 0

    def _live_sts(self, isvc: dict, ms, replica: int,
                  slice_id: int = 0) -> dict | None:
        name = isvcapi.replica_sts_name(
            name_of(isvc), replica, slice_id=slice_id,
            num_slices=ms.num_slices if ms else 1)
        if self._sts_informer is not None:
            return self._sts_informer.get(name, namespace_of(isvc))
        return None

    def _current_replicas(self, isvc: dict) -> int:
        """Replicas with a live (replicas > 0) slice-0 StatefulSet —
        derived from the cluster, not in-memory state, so a controller
        restart sees the truth."""
        count = 0
        ms = None
        try:
            ms = isvcapi.multi_slice_of(isvc)
        except Invalid:
            pass
        for i in range(isvcapi.max_replicas(isvc)):
            if self._replica_running(isvc, ms, i):
                count += 1
        return count

    # ---- scale-to-zero: park drain ----------------------------------------------

    async def _drain_to_park(self, isvc: dict, ms, now: float,
                             annotations: dict) -> Result | None:
        """The ONE path that takes a service to zero replicas. Request a
        checkpoint, wait for the engine's ack (the parked-checkpoint
        annotations) bounded by ``park_grace_seconds``, then park: every
        replica StatefulSet scales to 0, replica 0's object is KEPT as
        the warm standby, and the fleet chips release. Never a bare
        stop — ci/check_tracing.py enforces that this path is the only
        way serving replicas reach zero."""
        ns, name = namespace_of(isvc), name_of(isvc)
        requested = parse_iso(
            annotations.get(isvcapi.PARK_REQUESTED_ANNOTATION) or "")
        if requested is None:
            try:
                await self.kube.patch(
                    "InferenceService", name,
                    {"metadata": {"annotations": {
                        isvcapi.PARK_REQUESTED_ANNOTATION: fmt_iso(now)}}},
                    ns)
            except ApiError:
                return Result(
                    requeue_after=self.opts.autoscale_period_seconds)
            await self._event(
                isvc, "Normal", "ParkRequested",
                f"Idle past the scale-to-zero window; checkpointing "
                f"before parking (grace "
                f"{self.opts.park_grace_seconds:.0f}s)")
            return Result(requeue_after=min(
                self.opts.autoscale_period_seconds,
                self.opts.park_grace_seconds + 0.1))
        acked = isvcapi.park_acked(annotations)
        if not acked and now < requested + self.opts.park_grace_seconds:
            return Result(requeue_after=max(
                0.1, requested + self.opts.park_grace_seconds - now + 0.05))
        await self._park_all(isvc, ms, now, acked=acked)
        return None

    async def _park_all(self, isvc: dict, ms, now: float, *,
                        acked: bool) -> None:
        """Execute the park: replicas → 0 (replica 0's StatefulSet kept
        as the warm standby, higher replicas deleted), chips released,
        park stamped durable."""
        ns, name = namespace_of(isvc), name_of(isvc)
        skey = (ns, name)
        high = max(self._high_water.get(skey, 0),
                   isvcapi.max_replicas(isvc),
                   self._observed_high(isvc))
        with span("park", service=f"{ns}/{name}", acked=acked):
            for i in range(high):
                await self._park_replica_sts(isvc, ms, i, delete=(i > 0))
            await self._release_from(skey, 0)
            # Everything is released: the next scale-from-zero is an
            # up-from-nothing, not a scale-down from the old count.
            # kftpu: ignore[await-race] _park_all runs only from this service's own reconcile (per-key workqueue serialization)
            self._high_water[skey] = 0
            self.m_parks.inc()
            self.m_scale_events.labels(direction="zero").inc()
            try:
                await self.kube.patch(
                    "InferenceService", name,
                    {"metadata": {"annotations": {
                        isvcapi.PARK_REQUESTED_ANNOTATION: None,
                        isvcapi.PARKED_AT_ANNOTATION: fmt_iso(now)}}}, ns)
            except ApiError as exc:
                # the replicas are parked; re-stamp next pass
                log.debug("park stamp for %s/%s failed: %s", ns, name,
                          exc)
        step = isvcapi.parked_checkpoint(annotations_of(isvc))
        await self._event(
            isvc, "Normal", "Parked",
            "Scaled to zero; replica 0 kept as a parked warm standby"
            + (f" (checkpoint @ step {step[1]})"
               if acked and step and step[1] is not None
               else ("" if acked else " (no checkpoint ack within grace)")))

    async def _park_replica_sts(self, isvc: dict, ms, replica: int, *,
                                delete: bool) -> None:
        ns = namespace_of(isvc)
        for slice_id in range(ms.num_slices if ms else 1):
            sts_name = isvcapi.replica_sts_name(
                name_of(isvc), replica, slice_id=slice_id,
                num_slices=ms.num_slices if ms else 1)
            try:
                if delete:
                    await self.kube.delete("StatefulSet", sts_name, ns)
                else:
                    live = self._live_sts(isvc, ms, replica, slice_id)
                    if live is not None and (
                            deep_get(live, "spec", "replicas") or 0) > 0:
                        await self.kube.patch(
                            "StatefulSet", sts_name,
                            {"spec": {"replicas": 0}}, ns)
            except (NotFound, ApiError) as exc:
                log.debug("replica park of %s failed (re-parked next "
                          "pass): %s", sts_name, exc)

    async def _cancel_park(self, isvc: dict, ns: str, name: str, *,
                           parked: bool, now: float) -> None:
        """Demand returned: withdraw a pending park REQUEST. The
        parked-at mark (and the checkpoint annotations — the
        warm-restore hint) survive until the warm restore actually
        runs: a scale-from-zero that must first queue for chips would
        otherwise lose its park state before the restore, and the
        warm-restore metrics/event would silently skip in exactly the
        contended case operators watch them for."""
        if isvcapi.PARK_REQUESTED_ANNOTATION not in annotations_of(isvc):
            return
        try:
            await self.kube.patch(
                "InferenceService", name,
                {"metadata": {"annotations": {
                    isvcapi.PARK_REQUESTED_ANNOTATION: None}}}, ns)
        except ApiError as exc:
            log.debug("park-request withdrawal for %s/%s failed "
                      "(re-tried while demand holds): %s", ns, name, exc)

    # ---- releases / GC -----------------------------------------------------------

    def _observed_high(self, isvc: dict) -> int:
        """Highest replica index (+1) with a live StatefulSet or a
        booking in the shared scheduler — the restart-safe floor for
        GC/release decisions (the in-memory high-water dies with the
        process)."""
        high = 0
        if self._sts_informer is not None \
                and self._sts_informer.has_indexer(OWNER_INDEX):
            for sts in self._sts_informer.by_index(OWNER_INDEX,
                                                   uid_of(isvc)):
                m = _REPLICA_STS_RE.search(name_of(sts) or "")
                if m:
                    high = max(high, int(m.group(1)) + 1)
        return max(high, self._booked_high(
            (namespace_of(isvc), name_of(isvc))))

    def _booked_high(self, skey: tuple) -> int:
        """Highest replica index (+1) this service still holds (or
        queues) in the shared scheduler."""
        if self._scheduler is None:
            return 0
        high = 0
        policy = self._scheduler.policy
        for k in [*policy.ledger.allocations, *policy.pending]:
            parsed = isvcapi.parse_replica_key(tuple(k))
            if parsed is not None and k[0] == skey[0] \
                    and parsed[0] == skey[1]:
                high = max(high, parsed[1] + 1)
        return high

    async def _release_from(self, skey: tuple, keep: int, *,
                            high: int | None = None) -> None:
        """Release fleet admission for replicas >= ``keep``."""
        if self._scheduler is None:
            return
        bound = max(self._high_water.get(skey, 0), high or 0,
                    self._booked_high(skey))
        for i in range(keep, bound):
            await self._scheduler.serving_release(
                isvcapi.replica_key(skey[0], skey[1], i))

    async def _release_all(self, key: tuple) -> None:
        skey = tuple(key)
        await self._release_from(skey, 0)

    async def _sync_flex_markers(self, isvc: dict, desired: int) -> None:
        """Persist each replica's borrow pool on the CR (or clear it) so
        a controller restart re-seats flex replicas as BORROWS — the
        serving analogue of the notebook FLEX_POOL_ANNOTATION stamp."""
        if self._scheduler is None:
            return
        ns, name = namespace_of(isvc), name_of(isvc)
        ann = annotations_of(isvc)
        patch: dict = {}
        for i in range(max(desired, self._observed_high(isvc))):
            key = f"{isvcapi.FLEX_POOL_ANNOTATION_PREFIX}{i}"
            alloc = self._scheduler.policy.ledger.allocations.get(
                isvcapi.replica_key(ns, name, i))
            pool = (next(iter(alloc.borrow))
                    if alloc is not None and alloc.borrowed else None)
            if ann.get(key) != pool:
                patch[key] = pool
        if patch:
            try:
                await self.kube.patch(
                    "InferenceService", name,
                    {"metadata": {"annotations": patch}}, ns)
            except ApiError as exc:
                # best-effort durable marker; re-synced next pass
                log.debug("flex-marker sync for %s failed: %s", name,
                          exc)

    async def _gc_replicas(self, isvc: dict, ms, desired: int,
                           prev_high: int) -> None:
        """Delete StatefulSets of replicas above the new desired count
        (scale-down above zero; the park path owns the zero case)."""
        for i in range(max(desired, 1), prev_high):
            await self._park_replica_sts(isvc, ms, i, delete=True)

    # ---- object generation -------------------------------------------------------

    def generate_statefulset(self, isvc: dict, ms, replica: int, *,
                             slice_id: int = 0, restore=None) -> dict:
        """One replica-slice StatefulSet. Mirrors the notebook slice
        generator's TPU wiring (selectors, chip requests, slice-static
        env, webhook annotations) with serving labels and the parked
        checkpoint (or spec.model.checkpointPath) as the restore env."""
        name, ns = name_of(isvc), namespace_of(isvc)
        num_slices = ms.num_slices if ms else 1
        sts_name = isvcapi.replica_sts_name(
            name, replica, slice_id=slice_id, num_slices=num_slices)
        tpu = ms.slice if ms else None
        replicas = tpu.num_hosts if tpu else 1

        pod_spec = {**isvcapi.pod_spec_of(isvc)}
        containers = [dict(c) for c in pod_spec.get("containers", [])]
        if not containers:
            containers = [{"name": name,
                           "image": "kubeflow-tpu/jax-serve:latest"}]
        main = containers[0]
        main.setdefault("name", name)
        main.setdefault("ports", [
            {"containerPort": self.opts.serving_port, "name": "serve",
             "protocol": "TCP"}])

        template_annotations: dict = {}
        template_labels: dict = {
            STS_LABEL: sts_name,
            isvcapi.SERVICE_LABEL: name,
            isvcapi.WORKLOAD_CLASS_LABEL: isvcapi.SERVING_CLASS,
            "app": name,
        }
        if tpu:
            self._apply_tpu(main, pod_spec, template_annotations,
                            template_labels, isvc, ms, slice_id)
        self._set_restore_env(main, isvc, restore)
        containers[0] = main
        pod_spec["containers"] = containers

        return {
            "apiVersion": "apps/v1",
            "kind": "StatefulSet",
            "metadata": {
                "name": sts_name, "namespace": ns,
                "labels": {
                    isvcapi.SERVICE_LABEL: name,
                    isvcapi.WORKLOAD_CLASS_LABEL: isvcapi.SERVING_CLASS,
                },
            },
            "spec": {
                "replicas": replicas,
                "serviceName": name + WORKERS_SERVICE_SUFFIX,
                "selector": {"matchLabels": {STS_LABEL: sts_name}},
                # Slice workers bootstrap their mesh together, exactly
                # like a notebook slice.
                "podManagementPolicy": "Parallel",
                "template": {
                    "metadata": {
                        "labels": template_labels,
                        "annotations": template_annotations,
                    },
                    "spec": pod_spec,
                },
            },
        }

    def _apply_tpu(self, main: dict, pod_spec: dict,
                   template_annotations: dict, template_labels: dict,
                   isvc: dict, ms, slice_id: int) -> None:
        from kubeflow_tpu.api import notebook as nbapi

        name, ns = name_of(isvc), namespace_of(isvc)
        tpu = ms.slice
        selectors = dict(pod_spec.get("nodeSelector") or {})
        selectors.update(tpu.node_selectors())
        pod_spec["nodeSelector"] = selectors
        resources = dict(main.get("resources") or {})
        for kind in ("requests", "limits"):
            bucket = dict(resources.get(kind) or {})
            bucket.update(tpu.resource_requests())
            resources[kind] = bucket
        main["resources"] = resources

        headless = name + WORKERS_SERVICE_SUFFIX
        if ms.multi:
            hostnames = ms.worker_hostnames(
                name, headless, ns, self.opts.cluster_domain)
            static_env = ms.worker_env(slice_id, 0, hostnames)
            template_annotations[nbapi.TPU_SLICE_ID_ANNOTATION] = \
                str(slice_id)
            template_annotations[nbapi.TPU_NUM_SLICES_ANNOTATION] = \
                str(ms.num_slices)
        else:
            hostnames = tpu.worker_hostnames(
                name, headless, ns, self.opts.cluster_domain)
            static_env = tpu.worker_env(0, hostnames)
        for per_worker in ("TPU_WORKER_ID", "JAX_PROCESS_ID"):
            static_env.pop(per_worker, None)
        env = [dict(e) for e in main.get("env", [])]
        have = {e.get("name") for e in env}
        for k, v in static_env.items():
            if k not in have:
                env.append({"name": k, "value": v})
        main["env"] = env
        # Same per-worker env contract as notebook slices: the pod
        # webhook computes TPU_WORKER_ID / JAX_PROCESS_ID at admission,
        # keyed on the slice label + annotations below.
        template_annotations[nbapi.TPU_ACCELERATOR_ANNOTATION] = \
            tpu.accelerator.name
        template_annotations[nbapi.TPU_TOPOLOGY_ANNOTATION] = \
            tpu.topology_str
        template_labels[nbapi.TPU_SLICE_LABEL] = "true"

    def _set_restore_env(self, container: dict, isvc: dict,
                         restore) -> None:
        """Weights source for the engine: the parked warm-standby
        checkpoint when one exists, else the model's declared
        checkpointPath (the cold source of truth)."""
        if restore is None:
            path = deep_get(isvc, "spec", "model", "checkpointPath")
            restore = (path, None) if path else None
        if restore is None:
            return
        path, step = restore
        env = [dict(e) for e in container.get("env", [])]
        have = {e.get("name") for e in env}
        if migration.RESTORE_PATH_ENV not in have:
            env.append({"name": migration.RESTORE_PATH_ENV, "value": path})
        if step is not None and migration.RESTORE_STEP_ENV not in have:
            env.append({"name": migration.RESTORE_STEP_ENV,
                        "value": str(step)})
        container["env"] = env

    def _generate_service(self, isvc: dict) -> dict:
        name, ns = name_of(isvc), namespace_of(isvc)
        return {
            "apiVersion": "v1",
            "kind": "Service",
            "metadata": {
                "name": name, "namespace": ns,
                "labels": {isvcapi.SERVICE_LABEL: name},
            },
            "spec": {
                # All replicas behind one name: the Service load-balances
                # across every replica's workers.
                "selector": {isvcapi.SERVICE_LABEL: name},
                "ports": [{
                    "name": "http", "port": isvcapi.SERVICE_PORT,
                    "targetPort": self.opts.serving_port,
                    "protocol": "TCP",
                }],
            },
        }

    def _generate_headless_service(self, isvc: dict) -> dict:
        name, ns = name_of(isvc), namespace_of(isvc)
        return {
            "apiVersion": "v1",
            "kind": "Service",
            "metadata": {
                "name": name + WORKERS_SERVICE_SUFFIX, "namespace": ns,
                "labels": {isvcapi.SERVICE_LABEL: name},
            },
            "spec": {
                "clusterIP": "None",
                "selector": {isvcapi.SERVICE_LABEL: name},
                "ports": [{"name": "jax", "port": 8471,
                           "protocol": "TCP"}],
            },
        }

    # ---- status ------------------------------------------------------------------

    async def _update_status(self, isvc: dict, ms, *, desired: int,
                             admitted: int, queued: int, decision,
                             parked: bool) -> None:
        ns, name = namespace_of(isvc), name_of(isvc)
        ready = 0
        if self._sts_informer is not None \
                and self._sts_informer.has_indexer(OWNER_INDEX):
            owned = self._sts_informer.by_index(OWNER_INDEX, uid_of(isvc))
            ready = sum(deep_get(s, "status", "readyReplicas", default=0)
                        or 0 for s in owned)
        want_hosts = (ms.slice.num_hosts * ms.num_slices
                      if ms else 1) * max(admitted, 0)
        state = ("Parked" if parked and desired == 0
                 else "Parking" if desired == 0
                 else "Queued" if admitted == 0 and queued > 0
                 else "Scaling" if ready < want_hosts or queued > 0
                 else "Ready")
        status = {
            "replicas": desired,
            "readyReplicas": ready,
            "serving": {
                "state": state,
                "desiredReplicas": desired,
                "admittedReplicas": admitted,
                "queuedReplicas": queued,
                "reason": decision.reason,
            },
        }
        if ms is not None:
            status["tpu"] = {
                "chipsPerReplica": ms.num_chips,
                "hostsPerReplica": ms.slice.num_hosts * ms.num_slices,
                "accelerator": ms.slice.accelerator.name,
                "topology": ms.slice.topology_str,
            }
        ckpt = isvcapi.parked_checkpoint(annotations_of(isvc))
        if ckpt is not None:
            status["serving"]["parkedCheckpoint"] = {
                "path": ckpt[0],
                **({"step": ckpt[1]} if ckpt[1] is not None else {}),
            }
        # Engine-v2 data-plane surfaces (ISSUE 19), folded from the
        # gateway-stamped annotations so the JWA reads one place: the
        # KV-cache shortfall behind the head of the queue, an in-flight
        # model swap (warm standby vs cold load), and the per-model
        # load breakdown of a multiplexing replica.
        ann = annotations_of(isvc)
        short = int(_safe_float(ann.get(
            isvcapi.KV_BLOCKS_SHORT_ANNOTATION)))
        if short > 0:
            status["serving"]["kvPressure"] = {"blocksShort": short}
        swapping = (ann.get(isvcapi.MODEL_SWAP_ANNOTATION) or "").strip()
        if swapping:
            warm_raw = (ann.get(isvcapi.MODEL_SWAP_WARM_ANNOTATION)
                        or "").strip().lower()
            status["serving"]["modelSwap"] = {
                "model": swapping,
                "warm": warm_raw in ("1", "true", "yes", "on"),
            }
        per_model = isvcapi.model_rates(ann)
        if per_model:
            status["serving"]["models"] = {
                m: round(r, 3) for m, r in sorted(per_model.items())}
        # A successful reconcile clears a manager-stamped quarantine
        # verdict (runtime/manager.py Degraded condition) — without the
        # flip, a released quarantine would show "Reconciliation
        # suspended" in the UI forever (the notebook reconciler does
        # the same).
        conditions = deep_get(isvc, "status", "conditions",
                              default=[]) or []
        flipped = None
        for c in conditions:
            if c.get("type") == "Degraded":
                if c.get("status") == "True":
                    flipped = [{**c, "status": "False",
                                "reason": "Recovered",
                                "lastProbeTime": now_iso()}] + [
                        x for x in conditions if x is not c][:7]
                break
        if flipped is not None:
            status["conditions"] = flipped
        # Write elision against what WE last wrote (conditions aside):
        # other writers add fields this controller doesn't compute, so
        # comparing against the whole live status would defeat the
        # no-op guard and PATCH every autoscale pass forever.
        skey = (ns, name)
        if flipped is None and self._last_status.get(skey) == status:
            return
        try:
            await self.kube.patch(
                "InferenceService", name, {"status": status}, ns,
                subresource="status")
            # kftpu: ignore[await-race] per-service dedup cache written only from this key's own reconcile; worst case is one redundant status write
            self._last_status[skey] = {
                k: v for k, v in status.items() if k != "conditions"}
        except (NotFound, ApiError) as exc:
            log.debug("serving status write for %s failed (refreshed "
                      "next reconcile): %s", skey, exc)

    # ---- plumbing ----------------------------------------------------------------

    async def _ensure(self, isvc: dict, desired: dict) -> bool:
        set_controller_owner(desired, isvc)
        _, created = await reconcile_child(
            self.kube, desired,
            cache=self._apply_cache, reader=self._reader)
        return created

    async def _event(self, isvc: dict, type_: str, reason: str,
                     message: str) -> None:
        try:
            await self.recorder.event(isvc, type_, reason, message)
        except Exception:
            # Events are best-effort BY CONTRACT; the recorder only
            # counts API-level swallows, so count this one ourselves.
            self.recorder.count_drop()


def _safe_float(raw) -> float:
    try:
        value = float(raw) if raw is not None else 0.0
    except (TypeError, ValueError):
        return 0.0
    return max(0.0, value)


def setup_serving_controller(
    mgr: Manager, options: ServingOptions | None = None, *,
    scheduler=None,
) -> InferenceServiceReconciler:
    """Wire the serving workload class onto a manager. ``scheduler`` is
    the SHARED TpuFleetScheduler (the one the notebook controller
    consults) — one ledger for both workload classes; None means every
    replica admits unconditionally (KFTPU_SCHEDULER=off / no fleet)."""
    rec = InferenceServiceReconciler(mgr.kube, options,
                                     registry=mgr.registry)
    rec._scheduler = scheduler
    mgr.add_controller(
        Controller(
            name="inferenceservice",
            kind="InferenceService",
            reconcile=rec.reconcile,
            owns=["StatefulSet", "Service"],
        )
    )
    rec._sts_informer = mgr.informer_for("StatefulSet")
    rec._child_informers.update({
        "StatefulSet": mgr.informer_for("StatefulSet"),
        "Service": mgr.informer_for("Service"),
    })
    if scheduler is not None:
        # A replica admitted (or reclaimed) out of band reconciles its
        # service NOW; replica keys map back to the owning CR.
        def _requeue(rkey: tuple) -> None:
            parsed = isvcapi.parse_replica_key(tuple(rkey))
            if parsed is not None:
                mgr.enqueue("inferenceservice", (rkey[0], parsed[0]))

        scheduler.on_serving_admitted(_requeue)
    return rec
