"""Trace-driven open-loop load generator for the serving bench.

Open loop means arrivals are scheduled by the trace alone — a slow
server does not slow the generator down, so overload shows up as
queueing in the latency percentiles instead of silently throttling the
offered load (the closed-loop fallacy). Seeded end to end: the same
seed always produces the same trace, so bench rounds are comparable and
tests are deterministic.

A trace is a list of phases, each an (duration, rate) pair; arrivals
inside a phase are Poisson (exponential gaps) at that rate. The default
``burst_trace`` is the scale-from-zero story: silence → burst → cool —
exactly the shape that exercises park, warm restore, and scale-down.

v2 (ISSUE 19) grows two seeded dimensions so the paged-KV +
prefill/decode + multi-model engine is drive-able under the same open
loop:

- **Prompt lengths**: ``prompt_tokens``/``prompt_jitter`` give every
  request a prompt, and ``long_prompt_frac``/``long_prompt_tokens``
  mix in a heavy tail (the bimodal short/long mixture that exercises
  chunked prefill vs head-of-line).
- **Model ids**: ``models`` is a weighted ``{model_id: weight}``
  distribution stamped per request (what the gateway would route on).

Both default OFF, and the generator draws from the RNG **only when a
dimension is enabled** — so an existing seed produces the exact same
trace it did before this PR (determinism-by-seed is tested both ways).
"""

from __future__ import annotations

import random
from dataclasses import dataclass

from kubeflow_tpu.serving.engine import DEFAULT_MODEL, Request


@dataclass(frozen=True)
class Phase:
    duration: float            # seconds of trace time
    rate: float                # requests/sec (0 = silence)


def generate_trace(phases: list, *, seed: int = 0,
                   tokens_out: int = 8,
                   tokens_jitter: int = 0,
                   prompt_tokens: int = 0,
                   prompt_jitter: int = 0,
                   long_prompt_frac: float = 0.0,
                   long_prompt_tokens: int = 0,
                   models: dict | None = None) -> list:
    """Phases → arrival-sorted ``Request`` list. ``tokens_jitter`` adds
    uniform spread around ``tokens_out`` (continuous batching only pays
    off when request lengths differ — a jitter of 0 degenerates to
    static batching). ``prompt_tokens``/``long_prompt_*`` shape the
    prefill load; ``models`` weights the model-id mix."""
    rng = random.Random(seed)
    model_ids, model_weights = (), ()
    if models:
        model_ids = tuple(sorted(models))
        model_weights = tuple(models[m] for m in model_ids)
    requests: list = []
    t = 0.0
    rid = 0
    for phase in phases:
        end = t + phase.duration
        if phase.rate <= 0:
            t = end
            continue
        while True:
            t += rng.expovariate(phase.rate)
            if t >= end:
                t = end
                break
            toks = tokens_out
            if tokens_jitter:
                toks = max(1, tokens_out + rng.randint(-tokens_jitter,
                                                       tokens_jitter))
            prompt = prompt_tokens
            if long_prompt_frac and rng.random() < long_prompt_frac:
                prompt = long_prompt_tokens
            if prompt and prompt_jitter:
                prompt = max(1, prompt + rng.randint(-prompt_jitter,
                                                     prompt_jitter))
            model = DEFAULT_MODEL
            if model_ids:
                model = rng.choices(model_ids, weights=model_weights)[0]
            requests.append(Request(rid=rid, arrival=t, tokens_out=toks,
                                    prompt_tokens=max(0, prompt),
                                    model=model))
            rid += 1
    return requests


def burst_trace(*, seed: int = 0, warm_rate: float = 2.0,
                burst_rate: float = 20.0, warm_sec: float = 2.0,
                burst_sec: float = 3.0, cool_sec: float = 1.0,
                tokens_out: int = 8, tokens_jitter: int = 4,
                **dims) -> list:
    """The canonical bench trace: a trickle, a burst, a cool-down.
    Extra keyword dimensions (prompt/model mixes) pass through to
    :func:`generate_trace`."""
    return generate_trace(
        [Phase(warm_sec, warm_rate), Phase(burst_sec, burst_rate),
         Phase(cool_sec, warm_rate / 2)],
        seed=seed, tokens_out=tokens_out, tokens_jitter=tokens_jitter,
        **dims)


def observed_rate(requests: list, now: float, *,
                  window: float = 1.0) -> float:
    """Trailing-window request rate at trace time ``now`` — what a
    serving gateway would stamp as the observed-rate annotation."""
    lo = now - window
    n = sum(1 for r in requests if lo < r.arrival <= now)
    return n / window if window > 0 else 0.0


def model_load(requests: list, now: float, *,
               window: float = 1.0) -> dict:
    """Per-model trailing-window rates at trace time ``now`` — what the
    gateway stamps into the per-model load annotations the autoscaler
    and JWA read (the multiplexing signal)."""
    lo = now - window
    counts: dict = {}
    for r in requests:
        if lo < r.arrival <= now:
            model = getattr(r, "model", DEFAULT_MODEL)
            counts[model] = counts.get(model, 0) + 1
    return {m: n / window for m, n in counts.items()} if window > 0 else {}
