"""Trace-driven open-loop load generator for the serving bench.

Open loop means arrivals are scheduled by the trace alone — a slow
server does not slow the generator down, so overload shows up as
queueing in the latency percentiles instead of silently throttling the
offered load (the closed-loop fallacy). Seeded end to end: the same
seed always produces the same trace, so bench rounds are comparable and
tests are deterministic.

A trace is a list of phases, each an (duration, rate) pair; arrivals
inside a phase are Poisson (exponential gaps) at that rate. The default
``burst_trace`` is the scale-from-zero story: silence → burst → cool —
exactly the shape that exercises park, warm restore, and scale-down.
"""

from __future__ import annotations

import random
from dataclasses import dataclass

from kubeflow_tpu.serving.engine import Request


@dataclass(frozen=True)
class Phase:
    duration: float            # seconds of trace time
    rate: float                # requests/sec (0 = silence)


def generate_trace(phases: list, *, seed: int = 0,
                   tokens_out: int = 8,
                   tokens_jitter: int = 0) -> list:
    """Phases → arrival-sorted ``Request`` list. ``tokens_jitter`` adds
    uniform spread around ``tokens_out`` (continuous batching only pays
    off when request lengths differ — a jitter of 0 degenerates to
    static batching)."""
    rng = random.Random(seed)
    requests: list = []
    t = 0.0
    rid = 0
    for phase in phases:
        end = t + phase.duration
        if phase.rate <= 0:
            t = end
            continue
        while True:
            t += rng.expovariate(phase.rate)
            if t >= end:
                t = end
                break
            toks = tokens_out
            if tokens_jitter:
                toks = max(1, tokens_out + rng.randint(-tokens_jitter,
                                                       tokens_jitter))
            requests.append(Request(rid=rid, arrival=t, tokens_out=toks))
            rid += 1
    return requests


def burst_trace(*, seed: int = 0, warm_rate: float = 2.0,
                burst_rate: float = 20.0, warm_sec: float = 2.0,
                burst_sec: float = 3.0, cool_sec: float = 1.0,
                tokens_out: int = 8, tokens_jitter: int = 4) -> list:
    """The canonical bench trace: a trickle, a burst, a cool-down."""
    return generate_trace(
        [Phase(warm_sec, warm_rate), Phase(burst_sec, burst_rate),
         Phase(cool_sec, warm_rate / 2)],
        seed=seed, tokens_out=tokens_out, tokens_jitter=tokens_jitter)


def observed_rate(requests: list, now: float, *,
                  window: float = 1.0) -> float:
    """Trailing-window request rate at trace time ``now`` — what a
    serving gateway would stamp as the observed-rate annotation."""
    lo = now - window
    n = sum(1 for r in requests if lo < r.arrival <= now)
    return n / window if window > 0 else 0.0
