"""Paged KV-cache: a fixed pool of cache blocks behind admission.

The serving engine's memory plane (ISSUE 19). HBM for attention
key/value state is the scarce resource of a serving replica; v1 sized
it implicitly (max_batch × seq_len, allocated up front) which makes
"can this request fit?" undecidable and OOM the failure mode. v2 makes
it a first-class allocator, the vLLM paged-attention idea adapted
TPU-first:

- The cache is a **fixed pool of fixed-size blocks** (``block_size``
  tokens of K/V per block). Pool capacity is chosen once at engine
  bring-up, so device allocation stays static — one shape, one compile.
- A request owns a **block table** (its ordered block list). Tables are
  granted **all-or-nothing at admission** for the request's *worst
  case* need (prompt + max decode tokens). A request that fits never
  OOMs mid-decode; a request that doesn't fit waits in the queue —
  **backpressure is queue wait, never an allocator failure**.
- The pool never oversells: blocks move between exactly one free list
  and exactly one owner table. :meth:`assert_consistent` re-derives the
  invariant from scratch and any breach increments :attr:`violations`
  (the bench's seeded fault storm gates on this staying 0).

Observability: ``tpu_serving_kv_blocks_used`` / ``_total`` gauges and
:meth:`debug_info` (surfaced under ``/debug/`` by the serving engine's
debug payload).
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field

from kubeflow_tpu.runtime.metrics import Registry, global_registry

#: Tokens of K/V state per cache block. 16 is the paged-attention
#: sweet spot: small enough that short prompts don't strand capacity,
#: large enough that block tables stay short.
DEFAULT_BLOCK_SIZE = 16


class KVCacheError(RuntimeError):
    """A caller broke the allocator protocol (double admit, append past
    the reserved worst case). Raised, not swallowed — these are bugs in
    the engine, not load conditions."""


@dataclass
class BlockTable:
    """One admitted request's view of the cache: its ordered block list
    plus the token count appended so far. The table's capacity is the
    worst case reserved at admission — appends can never outgrow it."""

    rid: int
    blocks: list = field(default_factory=list)
    block_size: int = DEFAULT_BLOCK_SIZE
    tokens: int = 0                  # tokens written so far

    @property
    def capacity_tokens(self) -> int:
        return len(self.blocks) * self.block_size

    def append(self, n_tokens: int) -> None:
        """Record ``n_tokens`` of K/V written into this table (a prefill
        chunk or one decode step). The reservation already covers the
        worst case, so overflow is a protocol bug, not cache pressure."""
        if self.tokens + n_tokens > self.capacity_tokens:
            raise KVCacheError(
                f"request {self.rid}: append({n_tokens}) past reserved "
                f"capacity {self.capacity_tokens} (have {self.tokens})")
        self.tokens += n_tokens


class KVBlockPool:
    """The fixed block pool: allocator, per-request tables, gauges.

    Single-threaded by design — the engine's serve loop is the only
    caller, matching the one-engine-per-replica model. All admission
    goes through :meth:`admit` (the ci/analysis serving contract pins
    the engine's lane grants to this choke point).
    """

    def __init__(self, total_blocks: int, *,
                 block_size: int = DEFAULT_BLOCK_SIZE,
                 registry: Registry | None = None):
        if total_blocks <= 0:
            raise ValueError(f"total_blocks must be positive: {total_blocks}")
        if block_size <= 0:
            raise ValueError(f"block_size must be positive: {block_size}")
        self.total_blocks = total_blocks
        self.block_size = block_size
        self._free: list = list(range(total_blocks - 1, -1, -1))
        self._tables: dict = {}      # rid -> BlockTable
        self.rejections = 0          # admissions refused (cache pressure)
        self.violations = 0          # accounting invariant breaches
        reg = registry or global_registry
        self._g_used = reg.gauge(
            "tpu_serving_kv_blocks_used",
            "KV-cache blocks currently owned by admitted requests")
        self._g_total = reg.gauge(
            "tpu_serving_kv_blocks_total",
            "KV-cache block pool capacity")
        self._g_total.set(float(total_blocks))
        self._g_used.set(0.0)

    # ---- sizing --------------------------------------------------------------

    def blocks_needed(self, prompt_tokens: int, tokens_out: int) -> int:
        """Worst-case block need: the whole prompt plus every decode
        token the request may emit, rounded up to whole blocks."""
        tokens = max(0, prompt_tokens) + max(0, tokens_out)
        return max(1, math.ceil(tokens / self.block_size))

    @property
    def used_blocks(self) -> int:
        return self.total_blocks - len(self._free)

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    @property
    def pressure(self) -> float:
        """Used fraction of the pool, 0..1."""
        return self.used_blocks / self.total_blocks

    def blocks_short(self, prompt_tokens: int, tokens_out: int) -> int:
        """How many blocks a request is short of admission right now
        (0 = it would fit). This is the k in the JWA's "Queued behind
        KV-cache pressure (k blocks short)" message."""
        return max(0, self.blocks_needed(prompt_tokens, tokens_out)
                   - len(self._free))

    # ---- allocate / free -----------------------------------------------------

    def admit(self, rid: int, prompt_tokens: int, tokens_out: int):
        """All-or-nothing worst-case reservation. Returns the request's
        :class:`BlockTable`, or ``None`` under cache pressure (the
        caller leaves the request queued — backpressure, never OOM)."""
        if rid in self._tables:
            raise KVCacheError(f"request {rid} admitted twice")
        need = self.blocks_needed(prompt_tokens, tokens_out)
        if need > len(self._free):
            self.rejections += 1
            return None
        blocks = [self._free.pop() for _ in range(need)]
        table = BlockTable(rid=rid, blocks=blocks, block_size=self.block_size)
        self._tables[rid] = table
        self._g_used.set(float(self.used_blocks))
        return table

    def release(self, rid: int) -> int:
        """Return a finished (or aborted) request's blocks to the free
        list. Idempotent: releasing an unknown/already-released rid is a
        no-op returning 0, so completion and abort paths can't
        double-free a block between them."""
        table = self._tables.pop(rid, None)
        if table is None:
            return 0
        freed = 0
        free_set = set(self._free)
        for b in table.blocks:
            if b in free_set or b < 0 or b >= self.total_blocks:
                # A block that is already free (or out of range) means
                # the accounting was broken before this call — count it
                # rather than corrupt the free list further.
                self.violations += 1
                continue
            self._free.append(b)
            freed += 1
        table.blocks = []
        self._g_used.set(float(self.used_blocks))
        return freed

    # ---- invariants / debug --------------------------------------------------

    def assert_consistent(self) -> None:
        """Re-derive the no-oversell invariant from scratch: every block
        is on the free list or in exactly one table, never both, and the
        counts add up. Breaches increment :attr:`violations` and raise."""
        problems = []
        free_set = set(self._free)
        if len(free_set) != len(self._free):
            problems.append("duplicate blocks on the free list")
        owned: dict = {}
        for rid, table in self._tables.items():
            for b in table.blocks:
                if b in owned:
                    problems.append(
                        f"block {b} owned by both {owned[b]} and {rid}")
                owned[b] = rid
                if b in free_set:
                    problems.append(f"block {b} owned by {rid} AND free")
        if len(owned) + len(free_set) != self.total_blocks:
            problems.append(
                f"{len(owned)} owned + {len(free_set)} free != "
                f"{self.total_blocks} total")
        if problems:
            self.violations += len(problems)
            raise KVCacheError("; ".join(problems))

    def debug_info(self) -> dict:
        """Pressure snapshot for the engine's ``/debug/`` payload."""
        return {
            "blockSize": self.block_size,
            "totalBlocks": self.total_blocks,
            "usedBlocks": self.used_blocks,
            "freeBlocks": self.free_blocks,
            "pressure": round(self.pressure, 4),
            "admitted": len(self._tables),
            "rejections": self.rejections,
            "violations": self.violations,
        }
