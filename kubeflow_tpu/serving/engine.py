"""JAX serving engine v2: paged KV-cache, prefill/decode lanes,
multi-model multiplexing.

The data-plane half of the serving workload class. One engine is one
replica's model server. v1 (PR 11) was a batched-``jit`` loop; v2 is
the same loop grown into a real engine (ISSUE 19) — the vLLM-style
continuous-batching + paged-KV design adapted TPU-first: static
shapes everywhere, so XLA compiles a *closed set* of programs (one
decode program per model, one prefill-chunk program per model) no
matter what the traffic does.

- **Paged KV-cache** (:mod:`kubeflow_tpu.serving.kvcache`): a fixed
  block pool; a request is admitted to a lane only when its worst-case
  block need fits (:meth:`ServingEngine._admit_next` is the single
  admission choke point — the ci/analysis serving contract pins every
  lane grant to ``KVBlockPool.admit``). Cache pressure surfaces as
  queue wait, never an OOM.
- **Decoupled prefill and decode lanes**: long prompts prefill in
  fixed-size chunks on their own lane, interleaved chunk-by-chunk with
  decode steps (``chunked_prefill=True``), so a long prompt never
  stalls decode head-of-line. ``chunked_prefill=False`` keeps the v1
  run-prefill-to-completion behavior as the measured baseline.
- **Multi-model multiplexing** (:class:`ModelRegistry`): many small
  models time-share the replica's chips. Warm standbys keep weights
  host-resident and compiled fns cached (PR 14's warm-pool idiom at
  the model level), so a model swap is a device transfer — not an
  init + compile. :meth:`ModelRegistry.activate` is the single swap
  door (also contract-pinned).
- **Park / warm restore** (the scale-to-zero substrate): ``park()``
  moves every resident model's weights to host memory and keeps the
  compiled fns; ``warm_restore()`` is a device transfer. Requests may
  keep arriving while parked (:meth:`ServingEngine.submit`): they
  queue in the engine and complete after restore, their ``queue_wait``
  spanning the park — the scale-to-zero × continuous-batching
  interaction the serving tests pin.
"""

from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass, field

from kubeflow_tpu.runtime import slo
from kubeflow_tpu.runtime.metrics import Registry, global_registry
from kubeflow_tpu.runtime.tracing import span
from kubeflow_tpu.serving.kvcache import (
    DEFAULT_BLOCK_SIZE,
    KVBlockPool,
    KVCacheError,
)

#: The model id requests carry when they don't ask for one — and the
#: model every engine registers at construction from its own ``cfg``.
DEFAULT_MODEL = "default"


@dataclass(frozen=True)
class EngineOptions:
    """Data-plane tuning knobs (``KFTPU_SERVING_*`` via
    :func:`kubeflow_tpu.cmd.envconfig.serving_engine_options`)."""

    kv_blocks: int | None = None   # None → sized from max_batch × seq_len
    kv_block_size: int = DEFAULT_BLOCK_SIZE
    prefill_chunk: int = 32        # tokens per prefill chunk (static shape)
    chunked_prefill: bool = True   # False = run-to-completion baseline
    max_resident_models: int = 2   # models with weights on device at once


@dataclass(frozen=True)
class Request:
    """One inference request of the open-loop trace."""

    rid: int
    arrival: float             # seconds from trace start
    tokens_out: int = 8        # decode steps this request needs
    prompt_tokens: int = 0     # prompt length (0 = decode-only, v1 shape)
    model: str = DEFAULT_MODEL


@dataclass
class Completion:
    rid: int
    arrival: float
    started: float             # when it got a lane (prefill or decode)
    finished: float
    tokens: int
    prompt_tokens: int = 0
    model: str = DEFAULT_MODEL

    @property
    def latency(self) -> float:
        return self.finished - self.arrival

    @property
    def queue_wait(self) -> float:
        return self.started - self.arrival


@dataclass
class ServeReport:
    completions: list = field(default_factory=list)
    wall_sec: float = 0.0
    steps: int = 0
    batch_occupancy: float = 0.0   # mean filled decode slots per step
    prefill_chunks: int = 0
    prefill_tokens: int = 0
    model_swaps: int = 0
    kv_rejections: int = 0         # admissions deferred by cache pressure
    kv_peak_pressure: float = 0.0  # max used-fraction of the block pool

    @property
    def tokens(self) -> int:
        return sum(c.tokens for c in self.completions)

    @property
    def tokens_per_sec(self) -> float:
        return self.tokens / self.wall_sec if self.wall_sec > 0 else 0.0

    def latency_percentile(self, q: float) -> float:
        return self._percentile([c.latency for c in self.completions], q)

    def decode_latency_percentile(self, q: float) -> float:
        """Percentile over decode-only requests (no prompt) — the
        latency chunked prefill protects while long prompts land."""
        return self._percentile(
            [c.latency for c in self.completions if not c.prompt_tokens], q)

    def decode_service_percentile(self, q: float) -> float:
        """Like :meth:`decode_latency_percentile` but over service time
        (started → finished), excluding queue wait. Queue wait is
        admission-order fate shared by any prefill policy; the service
        time of an already-admitted decode is exactly what a
        head-of-line prefill stalls and chunked prefill protects."""
        return self._percentile(
            [c.finished - c.started for c in self.completions
             if not c.prompt_tokens], q)

    @staticmethod
    def _percentile(lats: list, q: float) -> float:
        lats = sorted(lats)
        if not lats:
            return 0.0
        idx = min(len(lats) - 1, max(0, int(round(q * (len(lats) - 1)))))
        return lats[idx]


@dataclass
class _ModelEntry:
    """One registered model's standby state. Warmth is a spectrum:
    device-resident (serving) → host-resident + compiled fns (warm
    standby: swap is a device transfer) → registered only (cold: swap
    is init + compile)."""

    model: str
    cfg: object
    device_params: object = None
    host_params: object = None
    decode_fn: object = None       # compiled, survives eviction AND park
    prefill_fn: object = None
    cold_init_sec: float | None = None
    warm_swap_sec: float | None = None
    last_used: int = 0

    @property
    def warm(self) -> bool:
        return self.host_params is not None and self.decode_fn is not None


class ModelRegistry:
    """Per-replica model registry with LRU warm standbys.

    PR 14 kept warm *pods* (claim, don't create); this keeps warm
    *models*: weights host-resident and the jitted fns cached, so
    :meth:`activate` of a warm standby is ``device_put`` + a warmup
    step — no init, no compile. At most ``max_resident`` models keep
    weights on device; beyond that the least-recently-used model is
    demoted to host (it stays warm). All swaps go through
    :meth:`activate` — the ci/analysis serving contract pins the
    engine to that single door.
    """

    def __init__(self, *, max_batch: int, seq_len_by_model=None,
                 prefill_chunk: int = 32, use_mesh: bool = True,
                 max_resident: int = 2,
                 registry: Registry | None = None):
        self.max_batch = max_batch
        self.prefill_chunk = prefill_chunk
        self.use_mesh = use_mesh
        self.max_resident = max(1, max_resident)
        self._entries: dict = {}       # model -> _ModelEntry
        self._mesh = None
        self._tick = 0
        self.swaps_cold = 0
        self.swaps_warm = 0
        reg = registry or global_registry
        self._c_swaps = reg.counter(
            "tpu_serving_model_swaps_total",
            "Model activations by kind (cold = init+compile, warm = "
            "device transfer from a warm standby)", ["kind"])
        self._g_resident = reg.gauge(
            "tpu_serving_models_resident",
            "Models with weights currently on device")

    def register(self, model: str, cfg) -> None:
        """Declare a model. No weights move until :meth:`activate`."""
        if model not in self._entries:
            self._entries[model] = _ModelEntry(model=model, cfg=cfg)

    def entry(self, model: str):
        """The registered entry (standby state, swap timings) or None."""
        return self._entries.get(model)

    def __contains__(self, model: str) -> bool:
        return model in self._entries

    def models(self) -> list:
        return sorted(self._entries)

    @property
    def mesh(self):
        return self._mesh

    def _resident(self) -> list:
        return [e for e in self._entries.values()
                if e.device_params is not None]

    def _to_device(self, params, cfg):
        import jax

        if self.use_mesh and len(jax.devices()) > 1:
            from kubeflow_tpu.models.burnin import shard_params
            from kubeflow_tpu.parallel.mesh import make_mesh

            if self._mesh is None:
                self._mesh = make_mesh()
            return shard_params(params, self._mesh, cfg)
        return jax.device_put(params)

    def _build_fns(self, cfg):
        import jax
        import jax.numpy as jnp

        from kubeflow_tpu.models.burnin import forward

        def score(params, tokens):
            # One decode step: score the batch, return each sequence's
            # next-token argmax (the cheapest useful output — the bench
            # measures throughput, not sampling quality).
            logits = forward(params, tokens, cfg)
            return jnp.argmax(logits[:, -1, :], axis=-1)

        # Same program, two static shapes: [max_batch, seq_len] for
        # decode, [1, prefill_chunk] for a prefill chunk. Together with
        # one entry per registered model that is the engine's entire
        # closed set of XLA programs.
        return jax.jit(score), jax.jit(score)

    def _warmup(self, entry) -> None:
        import numpy as np

        tokens = np.zeros((self.max_batch, entry.cfg.seq_len), np.int32)
        np.asarray(entry.decode_fn(entry.device_params, tokens))
        chunk = np.zeros((1, self.prefill_chunk), np.int32)
        np.asarray(entry.prefill_fn(entry.device_params, chunk))

    def _load_cold(self, entry, seed: int) -> None:
        import jax

        from kubeflow_tpu.models.burnin import init_params

        params = init_params(jax.random.key(seed), entry.cfg)
        entry.device_params = self._to_device(params, entry.cfg)
        entry.decode_fn, entry.prefill_fn = self._build_fns(entry.cfg)
        self._warmup(entry)

    def activate(self, model: str, *, seed: int = 0):
        """The single swap door: make ``model`` device-resident and
        return its entry. Cold (registered only) = init + compile;
        warm (standby) = device transfer through the retained compiled
        fns. Evicts the LRU resident past ``max_resident`` — demoted to
        a warm standby, not dropped."""
        entry = self._entries.get(model)
        if entry is None:
            raise KeyError(f"model {model!r} not registered")
        self._tick += 1
        entry.last_used = self._tick
        if entry.device_params is not None:
            return entry
        t0 = time.perf_counter()
        if entry.warm:
            entry.device_params = self._to_device(entry.host_params,
                                                  entry.cfg)
            entry.host_params = None
            self._warmup(entry)
            entry.warm_swap_sec = time.perf_counter() - t0
            self.swaps_warm += 1
            self._c_swaps.labels(kind="warm").inc()
        else:
            self._load_cold(entry, seed)
            entry.cold_init_sec = time.perf_counter() - t0
            self.swaps_cold += 1
            self._c_swaps.labels(kind="cold").inc()
        self._evict_over_budget(keep=model)
        self._g_resident.set(float(len(self._resident())))
        return entry

    def _evict_over_budget(self, *, keep: str) -> None:
        import jax

        resident = self._resident()
        while len(resident) > self.max_resident:
            victim = min((e for e in resident if e.model != keep),
                         key=lambda e: e.last_used, default=None)
            if victim is None:
                return
            victim.host_params = jax.device_get(victim.device_params)
            victim.device_params = None
            resident = self._resident()

    def park_all(self) -> None:
        """Scale-to-zero: every resident model's weights to host. The
        compiled fns stay cached — restore is a device transfer."""
        import jax

        for entry in self._resident():
            entry.host_params = jax.device_get(entry.device_params)
            entry.device_params = None
        self._g_resident.set(0.0)

    def debug_info(self) -> dict:
        return {
            "maxResident": self.max_resident,
            "resident": sorted(e.model for e in self._resident()),
            "warmStandbys": sorted(e.model for e in self._entries.values()
                                   if e.warm),
            "registered": self.models(),
            "swaps": {"cold": self.swaps_cold, "warm": self.swaps_warm},
        }


@dataclass
class _Prefill:
    """The prefill lane's single in-flight prompt."""

    req: Request
    table: object
    arrival: float
    started: float
    done: int = 0
    ready: bool = False        # prefilled, waiting for a decode slot


class ServingEngine:
    """One replica's model server over the burn-in transformer."""

    def __init__(self, cfg=None, *, max_batch: int = 8,
                 use_mesh: bool = True,
                 options: EngineOptions | None = None):
        from kubeflow_tpu.models.burnin import BurninConfig

        self.cfg = cfg or BurninConfig()
        self.max_batch = max_batch
        self.use_mesh = use_mesh
        self.options = options or EngineOptions()
        self._params = None          # active model's device weights
        self._host_params = None     # host weights while parked
        self._step_fn = None         # active model's compiled decode fn
        self._prefill_fn = None      # active model's compiled prefill fn
        self._mesh = None
        self.parked = False
        self.cold_start_sec: float | None = None
        self.warm_restore_sec: float | None = None
        self.park_step = 0           # monotonically counts decode steps
        self._active_model = DEFAULT_MODEL
        self.models = ModelRegistry(
            max_batch=max_batch,
            prefill_chunk=self.options.prefill_chunk,
            use_mesh=use_mesh,
            max_resident=self.options.max_resident_models)
        self.models.register(DEFAULT_MODEL, self.cfg)
        self.kv = KVBlockPool(
            self.options.kv_blocks or self._default_kv_blocks(),
            block_size=self.options.kv_block_size)
        self._waiting: deque = deque()   # (Request, arrival_abs) admitted-not-yet
        self._prefill: _Prefill | None = None
        self._blocks_short = 0       # head-of-queue KV shortfall right now
        self._per_model_done: dict = {}
        self._born = time.perf_counter()

    def _default_kv_blocks(self) -> int:
        # Roomy default: every slot can hold a full-context request
        # twice over — the pool only bites when configured tighter.
        import math

        per_req = math.ceil(2 * self.cfg.seq_len / self.options.kv_block_size)
        return self.max_batch * per_req

    def now(self) -> float:
        """Seconds on the engine's own monotonic clock (born at
        construction — it keeps ticking across park/restore, which is
        what lets ``queue_wait`` span a park)."""
        return time.perf_counter() - self._born

    # ---- model registration / swap -------------------------------------------

    def register_model(self, model: str, cfg=None) -> None:
        """Declare a model this replica can serve (weights move only on
        first use / explicit warmup via the registry)."""
        self.models.register(model, cfg or self.cfg)

    def _activate_model(self, model: str, *, seed: int = 0) -> None:
        """The engine's single model-swap path: route through the
        warm-standby registry and mirror the active entry into the v1
        attribute surface (``_params`` / ``_step_fn``)."""
        if model not in self.models:
            self.models.register(model, self.cfg)
        entry = self.models.activate(model, seed=seed)
        self._params = entry.device_params
        self._step_fn = entry.decode_fn
        self._prefill_fn = entry.prefill_fn
        self._mesh = self.models.mesh
        self._active_model = model

    def use_model(self, model: str, *, seed: int = 0) -> None:
        """Public swap entry (gateway / bench / warmup): make ``model``
        the active model through the registry's single door."""
        if self.parked:
            raise RuntimeError("cannot swap models while parked")
        self._activate_model(model, seed=seed)

    # ---- lifecycle -----------------------------------------------------------

    def cold_start(self, seed: int = 0) -> float:
        """Full cold bring-up of the default model: init weights,
        (optionally) shard them over the device mesh, compile the
        decode + prefill programs, run warm-up steps. Returns (and
        records) the wall seconds — the number warm restore and warm
        model swaps are measured against."""
        t0 = time.perf_counter()
        self._activate_model(DEFAULT_MODEL, seed=seed)
        self.parked = False
        self.cold_start_sec = time.perf_counter() - t0
        return self.cold_start_sec

    def park(self) -> dict:
        """Scale-to-zero park: every resident model's weights off the
        device into host memory, compiled fns retained. Returns the
        checkpoint descriptor the controller's park protocol records
        (path is symbolic here — a real deployment points it at the
        Orbax directory the engine's CheckpointManager commits to).
        Requests may still :meth:`submit` while parked; they queue."""
        if self._params is None:
            raise RuntimeError("cannot park an engine that never started")
        self.models.park_all()
        entry = self.models._entries[self._active_model]
        self._host_params = entry.host_params
        self._params = None
        self.parked = True
        return {"path": f"mem://parked/{id(self):x}", "step": self.park_step}

    def warm_restore(self) -> float:
        """Scale-from-zero restore of a parked standby: device-put the
        active model's host weights back and warm up through the
        RETAINED compiled fns. No init, no compile — the measured delta
        vs :meth:`cold_start` is the warm-standby win."""
        if not self.parked or self._host_params is None:
            raise RuntimeError("warm_restore() needs a parked engine")
        t0 = time.perf_counter()
        self._activate_model(self._active_model)
        self._host_params = None
        self.parked = False
        self.warm_restore_sec = time.perf_counter() - t0
        return self.warm_restore_sec

    # ---- submission ----------------------------------------------------------

    def submit(self, request: Request) -> None:
        """Enqueue a request on the engine's persistent queue — legal
        while parked (that IS the scale-from-zero story: the queue
        accumulates, the controller restores, the next :meth:`serve`
        drains it; ``queue_wait`` spans the park)."""
        self._waiting.append((request, self.now()))

    # ---- serving loop --------------------------------------------------------

    def _ensure_serve_state(self) -> None:
        # serve() must also run on a bare engine (tests build one via
        # __new__ with just _params/_step_fn) — default every v2 field.
        if getattr(self, "options", None) is None:
            self.options = EngineOptions()
        if getattr(self, "kv", None) is None:
            self.kv = KVBlockPool(
                self.options.kv_blocks or self._default_kv_blocks(),
                block_size=self.options.kv_block_size)
        if getattr(self, "_waiting", None) is None:
            self._waiting = deque()
        if getattr(self, "models", None) is None:
            self.models = None
        for name, default in (("_prefill", None), ("_prefill_fn", None),
                              ("_active_model", DEFAULT_MODEL),
                              ("_blocks_short", 0), ("_per_model_done", {}),
                              ("_born", time.perf_counter())):
            if not hasattr(self, name):
                setattr(self, name, default)

    def _admit_next(self, clock: float, slots: list, remaining: list,
                    started: list, arrivals: list) -> None:
        """The single admission choke point: strict-FIFO grants from
        the waiting queue into the prefill or decode lane, each gated
        by a worst-case KV block reservation (``KVBlockPool.admit``).
        Stops at the first request that can't be placed — cache
        pressure and lane pressure surface as queue wait."""
        while self._waiting:
            req, arrival_abs = self._waiting[0]
            model = getattr(req, "model", DEFAULT_MODEL)
            if model != self._active_model:
                # Drain-then-swap: let the current model's in-flight
                # work finish, then the registry makes the swap a
                # device transfer (warm) or an init+compile (cold).
                busy = self._prefill is not None or any(
                    s is not None for s in slots)
                if busy or self.models is None:
                    break
                self._activate_model(model)
            prompt = getattr(req, "prompt_tokens", 0)
            needs_prefill = prompt > 0 and self._prefill_fn is not None
            if needs_prefill and self._prefill is not None:
                break                      # prefill lane busy
            free = None
            if not needs_prefill:
                try:
                    free = slots.index(None)
                except ValueError:
                    break                  # decode lane full
            if self.kv.blocks_needed(prompt, req.tokens_out) \
                    > self.kv.total_blocks:
                raise KVCacheError(
                    f"request {req.rid} can never fit: needs "
                    f"{self.kv.blocks_needed(prompt, req.tokens_out)} "
                    f"blocks, pool holds {self.kv.total_blocks}")
            table = self.kv.admit(req.rid, prompt, req.tokens_out)
            if table is None:
                # Cache pressure: leave it queued (backpressure, never
                # OOM) and remember the shortfall for status surfaces.
                self._blocks_short = self.kv.blocks_short(
                    prompt, req.tokens_out)
                break
            self._blocks_short = 0
            self._waiting.popleft()
            if needs_prefill:
                self._prefill = _Prefill(req=req, table=table,
                                         arrival=arrival_abs, started=clock)
            else:
                slots[free] = (req, table)
                remaining[free] = req.tokens_out
                started[free] = clock
                arrivals[free] = arrival_abs

    def serve(self, requests: list, *, time_scale: float = 1.0) -> ServeReport:
        """Run one open-loop trace to completion with continuous
        batching. ``requests`` arrive at ``arrival * time_scale`` on
        the engine's own clock whether or not lanes are free (open loop
        — the backlog shows up as queue wait in the latency
        percentiles). The trace clock never waits for the model: if the
        model is the bottleneck, arrivals pile up, exactly like
        production. Requests :meth:`submit`-ted earlier (including
        while parked) drain first."""
        import numpy as np

        if self._params is None or self._step_fn is None:
            raise RuntimeError("engine not started (cold_start/warm_restore)")
        self._ensure_serve_state()
        opts = self.options
        t0_abs = self.now()
        pending = [(r, t0_abs + r.arrival * time_scale)
                   for r in sorted(requests, key=lambda r: (r.arrival, r.rid))]
        slots: list = [None] * self.max_batch      # (Request, BlockTable)
        remaining = [0] * self.max_batch
        started = [0.0] * self.max_batch
        arrivals = [0.0] * self.max_batch
        tokens = np.zeros((self.max_batch, self.cfg.seq_len), np.int32)
        chunk_buf = np.zeros((1, opts.prefill_chunk), np.int32)
        report = ServeReport()
        occupancy = 0
        kv_rej0 = self.kv.rejections
        swaps0 = ((self.models.swaps_cold + self.models.swaps_warm)
                  if self.models is not None else 0)

        def finish(i: int, clock: float) -> None:
            req, table = slots[i]
            done = Completion(
                rid=req.rid, arrival=arrivals[i], started=started[i],
                finished=clock, tokens=req.tokens_out,
                prompt_tokens=getattr(req, "prompt_tokens", 0),
                model=getattr(req, "model", DEFAULT_MODEL))
            report.completions.append(done)
            self._per_model_done[done.model] = \
                self._per_model_done.get(done.model, 0) + 1
            # Serving-latency SLI (runtime/slo.py): arrival →
            # completion, queue wait included — the p99 promise covers
            # the backlog, not just compute.
            slo.observe("serving_latency", done.latency,
                        key=("serving", f"req-{req.rid}"))
            self.kv.release(req.rid)
            slots[i] = None

        with span("serve", requests=len(pending), max_batch=self.max_batch):
            while (pending or self._waiting or self._prefill is not None
                   or any(s is not None for s in slots)):
                clock = self.now()
                while pending and pending[0][1] <= clock:
                    self._waiting.append(pending.pop(0))
                self._admit_next(clock, slots, remaining, started, arrivals)

                # Prefill lane: one fixed-shape chunk per iteration.
                pf = self._prefill
                if pf is not None and not pf.ready:
                    n = min(opts.prefill_chunk,
                            pf.req.prompt_tokens - pf.done)
                    np.asarray(self._prefill_fn(self._params, chunk_buf))
                    pf.table.append(n)
                    pf.done += n
                    report.prefill_chunks += 1
                    report.prefill_tokens += n
                    if pf.done >= pf.req.prompt_tokens:
                        pf.ready = True
                if pf is not None and pf.ready:
                    # Hand the prefilled prompt to the decode lane the
                    # moment a slot frees (the lane handoff).
                    try:
                        free = slots.index(None)
                    except ValueError:
                        free = None
                    if free is not None:
                        slots[free] = (pf.req, pf.table)
                        remaining[free] = pf.req.tokens_out
                        started[free] = pf.started
                        arrivals[free] = pf.arrival
                        self._prefill = None
                if (self._prefill is not None and not opts.chunked_prefill
                        and not self._prefill.ready):
                    # Head-of-line baseline: an in-flight prefill runs
                    # to completion before any decode step (what v1
                    # did, and what the bench's paired trials compare
                    # chunked prefill against).
                    continue

                active = [i for i, s in enumerate(slots) if s is not None]
                if not active:
                    if self._prefill is not None:
                        continue           # prefill still progressing
                    # Idle until the next arrival (scaled trace time).
                    if pending and not self._waiting:
                        wait = pending[0][1] - self.now()
                        if wait > 0:
                            # kftpu: ignore[no-blocking-in-async] serve() runs off-loop — bench.py / a dedicated serving worker thread drives it; the sleep paces the open-loop trace clock
                            time.sleep(min(wait, 0.05))
                    continue
                # One decode step for the whole batch (static shape).
                np.asarray(self._step_fn(self._params, tokens))
                self.park_step += 1
                report.steps += 1
                occupancy += len(active)
                report.kv_peak_pressure = max(report.kv_peak_pressure,
                                              self.kv.pressure)
                clock = self.now()
                for i in active:
                    remaining[i] -= 1
                    slots[i][1].append(1)  # one decode token of KV
                    if remaining[i] <= 0:
                        finish(i, clock)
        report.wall_sec = self.now() - t0_abs
        report.batch_occupancy = (occupancy / report.steps
                                  if report.steps else 0.0)
        report.kv_rejections = self.kv.rejections - kv_rej0
        if self.models is not None:
            report.model_swaps = (self.models.swaps_cold
                                  + self.models.swaps_warm) - swaps0
        return report

    # ---- observability -------------------------------------------------------

    def debug_info(self) -> dict:
        """The engine's ``/debug/`` payload: KV pressure, lane state,
        model registry — what an operator checks when p99 climbs."""
        self._ensure_serve_state()
        pf = self._prefill
        return {
            "parked": self.parked,
            "activeModel": self._active_model,
            "queued": len(self._waiting),
            "blocksShort": self._blocks_short,
            "kv": self.kv.debug_info(),
            "lanes": {
                "decodeSlots": self.max_batch,
                "prefill": None if pf is None else {
                    "rid": pf.req.rid, "done": pf.done,
                    "promptTokens": pf.req.prompt_tokens,
                    "ready": pf.ready,
                },
                "chunkedPrefill": self.options.chunked_prefill,
                "prefillChunk": self.options.prefill_chunk,
            },
            "perModelCompleted": dict(self._per_model_done),
            "models": (self.models.debug_info()
                       if self.models is not None else None),
        }
