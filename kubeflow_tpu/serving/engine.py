"""JAX serving loop: batched ``jit`` forward with continuous batching.

The data-plane half of the serving workload class. One engine is one
replica's model server:

- **Batched forward**: requests are packed into a fixed ``[max_batch,
  seq_len]`` token buffer and scored by ONE jitted forward per decode
  step — static shapes, so XLA compiles exactly once (the burn-in
  transformer from ``models/burnin.py``, sharded over a
  ``parallel/mesh.py`` mesh when more than one device is attached).
- **Continuous batching**: a request occupies a batch slot only for its
  own ``tokens_out`` decode steps; the moment it finishes, the next
  queued request takes the slot mid-flight — no head-of-line blocking
  on the longest request in a static batch.
- **Park / warm restore** (the scale-to-zero substrate): ``park()``
  moves the weights to host memory and keeps the compiled step — the
  checkpoint the controller's park protocol records. ``warm_restore()``
  is then a device transfer, not an init + compile: that delta is
  exactly why a parked warm standby restores measurably faster than a
  cold replica create (``bench.py inference_serving`` gates on it).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

from kubeflow_tpu.runtime import slo
from kubeflow_tpu.runtime.tracing import span


@dataclass(frozen=True)
class Request:
    """One inference request of the open-loop trace."""

    rid: int
    arrival: float             # seconds from trace start
    tokens_out: int = 8        # decode steps this request needs


@dataclass
class Completion:
    rid: int
    arrival: float
    started: float             # when it got a batch slot
    finished: float
    tokens: int

    @property
    def latency(self) -> float:
        return self.finished - self.arrival

    @property
    def queue_wait(self) -> float:
        return self.started - self.arrival


@dataclass
class ServeReport:
    completions: list = field(default_factory=list)
    wall_sec: float = 0.0
    steps: int = 0
    batch_occupancy: float = 0.0   # mean filled slots per step

    @property
    def tokens(self) -> int:
        return sum(c.tokens for c in self.completions)

    @property
    def tokens_per_sec(self) -> float:
        return self.tokens / self.wall_sec if self.wall_sec > 0 else 0.0

    def latency_percentile(self, q: float) -> float:
        lats = sorted(c.latency for c in self.completions)
        if not lats:
            return 0.0
        idx = min(len(lats) - 1, max(0, int(round(q * (len(lats) - 1)))))
        return lats[idx]


class ServingEngine:
    """One replica's model server over the burn-in transformer."""

    def __init__(self, cfg=None, *, max_batch: int = 8, use_mesh: bool = True):
        from kubeflow_tpu.models.burnin import BurninConfig

        self.cfg = cfg or BurninConfig()
        self.max_batch = max_batch
        self.use_mesh = use_mesh
        self._params = None          # device weights while serving
        self._host_params = None     # host weights while parked
        self._step_fn = None         # compiled forward (survives a park)
        self._mesh = None
        self.parked = False
        self.cold_start_sec: float | None = None
        self.warm_restore_sec: float | None = None
        self.park_step = 0           # monotonically counts decode steps

    # ---- lifecycle -----------------------------------------------------------

    def _build_step(self):
        import jax
        import jax.numpy as jnp

        from kubeflow_tpu.models.burnin import forward

        cfg = self.cfg

        def score(params, tokens):
            # One decode step: score the batch, return each sequence's
            # next-token logits argmax (the cheapest useful output — the
            # bench measures throughput, not sampling quality).
            logits = forward(params, tokens, cfg)
            return jnp.argmax(logits[:, -1, :], axis=-1)

        return jax.jit(score)

    def cold_start(self, seed: int = 0) -> float:
        """Full cold bring-up: init weights, (optionally) shard them
        over the device mesh, compile the batched forward, run one
        warm-up step. Returns (and records) the wall seconds — the
        number the warm restore is measured against."""
        import jax
        import numpy as np

        from kubeflow_tpu.models.burnin import init_params, shard_params

        t0 = time.perf_counter()
        params = init_params(jax.random.key(seed), self.cfg)
        if self.use_mesh and len(jax.devices()) > 1:
            from kubeflow_tpu.parallel.mesh import make_mesh

            self._mesh = make_mesh()
            params = shard_params(params, self._mesh, self.cfg)
        self._params = params
        self._step_fn = self._build_step()
        tokens = np.zeros((self.max_batch, self.cfg.seq_len), np.int32)
        np.asarray(self._step_fn(self._params, tokens))  # compile + sync
        self.parked = False
        self.cold_start_sec = time.perf_counter() - t0
        return self.cold_start_sec

    def park(self) -> dict:
        """Scale-to-zero park: weights off the device into host memory,
        compiled step retained. Returns the checkpoint descriptor the
        controller stamps onto the CR (path is symbolic here — a real
        deployment points it at the Orbax directory the engine's
        CheckpointManager commits to)."""
        import jax

        if self._params is None:
            raise RuntimeError("cannot park an engine that never started")
        self._host_params = jax.device_get(self._params)
        self._params = None
        self.parked = True
        return {"path": f"mem://parked/{id(self):x}", "step": self.park_step}

    def warm_restore(self) -> float:
        """Scale-from-zero restore of a parked standby: device-put the
        host weights back and run one warm-up step through the RETAINED
        compiled fn. No init, no compile — the measured delta vs
        :meth:`cold_start` is the warm-standby win."""
        import jax
        import numpy as np

        if not self.parked or self._host_params is None:
            raise RuntimeError("warm_restore() needs a parked engine")
        t0 = time.perf_counter()
        if self._mesh is not None:
            from kubeflow_tpu.models.burnin import shard_params

            self._params = shard_params(self._host_params, self._mesh,
                                        self.cfg)
        else:
            self._params = jax.device_put(self._host_params)
        self._host_params = None
        tokens = np.zeros((self.max_batch, self.cfg.seq_len), np.int32)
        np.asarray(self._step_fn(self._params, tokens))
        self.parked = False
        self.warm_restore_sec = time.perf_counter() - t0
        return self.warm_restore_sec

    # ---- serving loop --------------------------------------------------------

    def serve(self, requests: list, *, time_scale: float = 1.0) -> ServeReport:
        """Run one open-loop trace to completion with continuous
        batching. ``requests`` arrive at ``arrival * time_scale`` on the
        engine's own clock whether or not slots are free (open loop —
        the backlog shows up as queue wait in the latency percentiles).
        The trace clock never waits for the model: if the model is the
        bottleneck, arrivals pile up, exactly like production."""
        import numpy as np

        if self._params is None or self._step_fn is None:
            raise RuntimeError("engine not started (cold_start/warm_restore)")
        queue = sorted(requests, key=lambda r: (r.arrival, r.rid))
        pending = list(queue)
        slots: list = [None] * self.max_batch      # Request | None
        remaining = [0] * self.max_batch
        started = [0.0] * self.max_batch
        tokens = np.zeros((self.max_batch, self.cfg.seq_len), np.int32)
        report = ServeReport()
        occupancy = 0
        t0 = time.perf_counter()

        def now() -> float:
            return time.perf_counter() - t0

        with span("serve", requests=len(queue), max_batch=self.max_batch):
            while pending or any(s is not None for s in slots):
                clock = now()
                # Admit arrivals into free slots, earliest arrival first.
                while pending and pending[0].arrival * time_scale <= clock:
                    try:
                        free = slots.index(None)
                    except ValueError:
                        break  # batch full; the backlog queues (open loop)
                    req = pending.pop(0)
                    slots[free] = req
                    remaining[free] = req.tokens_out
                    started[free] = clock
                active = [i for i, s in enumerate(slots) if s is not None]
                if not active:
                    # Idle until the next arrival (scaled trace time).
                    if pending:
                        wait = pending[0].arrival * time_scale - now()
                        if wait > 0:
                            # kftpu: ignore[no-blocking-in-async] serve() runs off-loop — bench.py / a dedicated serving worker thread drives it; the sleep paces the open-loop trace clock
                            time.sleep(min(wait, 0.05))
                    continue
                # One decode step for the whole batch (static shape).
                np.asarray(self._step_fn(self._params, tokens))
                self.park_step += 1
                report.steps += 1
                occupancy += len(active)
                clock = now()
                for i in active:
                    remaining[i] -= 1
                    if remaining[i] <= 0:
                        req = slots[i]
                        done = Completion(
                            rid=req.rid, arrival=req.arrival * time_scale,
                            started=started[i], finished=clock,
                            tokens=req.tokens_out)
                        report.completions.append(done)
                        # Serving-latency SLI (runtime/slo.py): arrival
                        # → completion, queue wait included — the p99
                        # promise covers the backlog, not just compute.
                        slo.observe("serving_latency", done.latency,
                                    key=("serving", f"req-{req.rid}"))
                        slots[i] = None
        report.wall_sec = now()
        report.batch_occupancy = (occupancy / report.steps
                                  if report.steps else 0.0)
        return report
