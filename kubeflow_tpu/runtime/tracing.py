"""Lightweight tracing for reconcile loops.

The reference has no distributed tracing (SURVEY.md §5: "No OpenTelemetry
anywhere"); the rebuild adds optional spans: when the ``opentelemetry`` SDK
is importable AND tracing is enabled, real OTel spans are emitted; otherwise
spans degrade to structured debug logs + a per-controller latency histogram
(always on — this is where reconcile-duration metrics come from).
"""

from __future__ import annotations

import contextlib
import logging
import os
import time

from kubeflow_tpu.runtime.metrics import Registry, global_registry

log = logging.getLogger("kubeflow_tpu.trace")

_otel_tracer = None
if os.environ.get("ENABLE_TRACING") == "true":  # pragma: no cover
    try:
        from opentelemetry import trace as _otel_trace

        _otel_tracer = _otel_trace.get_tracer("kubeflow_tpu")
    except ImportError:
        _otel_tracer = None


class Tracer:
    def __init__(self, registry: Registry | None = None):
        registry = registry or global_registry
        self.h_duration = registry.histogram(
            "controller_reconcile_duration_seconds",
            "Reconcile latency per controller",
            ["controller"],
        )

    @contextlib.contextmanager
    def span(self, name: str, **attrs):
        start = time.perf_counter()
        otel_cm = (
            _otel_tracer.start_as_current_span(name)
            if _otel_tracer is not None
            else contextlib.nullcontext()
        )
        with otel_cm as otel_span:
            if otel_span is not None and hasattr(otel_span, "set_attribute"):
                for key, value in attrs.items():
                    otel_span.set_attribute(key, str(value))
            try:
                yield
            finally:
                elapsed = time.perf_counter() - start
                controller = attrs.get("controller", name)
                self.h_duration.observe(elapsed, controller=str(controller))
                log.debug("span %s %s took %.4fs", name, attrs, elapsed)
