"""End-to-end reconcile tracing: span trees, correlation IDs, flight recorder.

The reference stack has no distributed tracing at all (SURVEY.md §5: "No
OpenTelemetry anywhere") — when a Notebook sticks in ``Waiting`` there is
no way to see which phase (queue wait, cache read, child apply, status
patch, admission) ate the time or which API call failed. This module is
the rebuilt answer, always on and cheap enough to stay on:

- **Span trees.** :func:`span` opens a named span as a child of whatever
  span the current ``contextvars`` context carries; ``trace_id`` is shared
  down the tree, every span gets its own ``span_id``. Spans record wall
  duration, attributes, and ok/error status. When the ``opentelemetry``
  SDK is importable AND ``ENABLE_TRACING=true``, real OTel spans mirror
  the tree; otherwise the tree itself is the trace (plus the existing
  per-controller latency histogram, which stays — dashboards carry over).

- **Correlation IDs.** :func:`current_trace_id` exposes the active trace
  id so API clients stamp it onto every request (``X-Request-Id`` header:
  ``runtime/httpclient.py`` on the wire, ``testing/fakekube.py`` in its
  request log) and the web apps' request-ID middleware joins the same
  header space — one id follows a reconcile from queue pop to apiserver
  audit log.

- **Flight recorder.** A bounded per-object ring buffer of the last N
  completed reconcile traces per key — outcome, duration, span tree, API
  verbs issued, events emitted, error — retained *after* the reconcile
  ends, so ``GET /debug/traces`` on the manager answers "what did the
  last reconcile of team/nb actually do" hours later. controller-runtime's
  pprof/zpages idiom rebuilt for this stack.

Overhead is bench-gated: ``bench.py tracing_overhead`` proves the
always-on path costs <5% of reconcile throughput (acceptance criterion);
:func:`set_enabled` is the kill switch the probe flips to measure it.
"""

from __future__ import annotations

import contextlib
import contextvars
import itertools
import logging
import os
import random
import threading
import time
from collections import OrderedDict, deque

from kubeflow_tpu.runtime.metrics import Registry, global_registry
from kubeflow_tpu.runtime.objects import fmt_iso

log = logging.getLogger("kubeflow_tpu.trace")

_otel_tracer = None
if os.environ.get("ENABLE_TRACING") == "true":  # pragma: no cover
    try:
        from opentelemetry import trace as _otel_trace

        _otel_tracer = _otel_trace.get_tracer("kubeflow_tpu")
    except ImportError:
        _otel_tracer = None

# Process-wide kill switch (the tracing_overhead bench probe measures the
# difference; operators never need it — that's the point of the bench gate).
_enabled = True


def set_enabled(on: bool) -> None:
    global _enabled
    _enabled = bool(on)


def is_enabled() -> bool:
    return _enabled


# Correlation ids need uniqueness, not cryptographic randomness — and the
# hot path opens several spans per reconcile. A process-local PRNG (seeded
# from the OS once) is ~100× cheaper than uuid4, whose per-call
# os.urandom syscall alone costs ~0.1 ms on sandboxed kernels.
_rand = random.Random(int.from_bytes(os.urandom(16), "big"))


def new_trace_id() -> str:
    return f"{_rand.getrandbits(128):032x}"


def new_span_id() -> str:
    return f"{_rand.getrandbits(64):016x}"


class Span:
    """One node of a trace tree. Cheap by construction — the reconcile
    hot path opens ~a dozen of these, so ids are generated lazily (only
    the root's trace id is eager: API clients stamp it on every request)
    and nothing is serialized until the flight recorder or a /debug
    handler asks."""

    __slots__ = (
        "name", "_trace_id", "_span_id", "parent", "attrs", "status",
        "error", "children", "root", "api_calls", "events",
        "_start", "duration", "_token", "_otel",
    )

    def __init__(self, name: str, *, trace_id: str | None = None,
                 parent: "Span | None" = None, attrs: dict | None = None):
        self.name = name
        self.parent = parent
        self._span_id: str | None = None
        self.attrs = attrs or {}
        self.status = "ok"
        self.error: str | None = None
        self.children: list[Span] = []
        self._token = None
        if parent is None:
            self._trace_id = trace_id or new_trace_id()
            self.root = self
            # Root-only bookkeeping: API verbs and emitted events
            # aggregate here so the flight-recorder entry answers "what
            # did this reconcile DO" without walking the tree.
            self.api_calls: dict[tuple[str, str], int] = {}
            self.events: list[str] = []
        else:
            self._trace_id = None
            self.root = parent.root
            self.api_calls = self.root.api_calls
            self.events = self.root.events
        self._start = time.perf_counter()
        self.duration: float | None = None

    # Span doubles as its own context manager — the reconcile hot path
    # opens ~a dozen spans, and a separate contextmanager object (let
    # alone contextlib's generator machinery) costs real throughput.
    def __enter__(self) -> "Span":
        self._token = _current.set(self)
        if _otel_tracer is not None:  # pragma: no cover - needs the SDK
            self._otel = _otel_tracer.start_as_current_span(self.name)
            otel_span = self._otel.__enter__()
            if hasattr(otel_span, "set_attribute"):
                for key, value in self.attrs.items():
                    otel_span.set_attribute(key, str(value))
        self._start = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        if exc_type is not None:
            self.finish("error", repr(exc))
        elif self.status == "error":
            # fail() was called inside the block (handled error — e.g. an
            # admission deny whose exception never escapes): keep it.
            self.finish("error", self.error)
        else:
            self.finish()
        if self._token is not None:
            _current.reset(self._token)
            self._token = None
        if _otel_tracer is not None and getattr(self, "_otel", None) is not None:
            self._otel.__exit__(exc_type, exc, tb)  # pragma: no cover
        return False

    @property
    def trace_id(self) -> str:
        return self.root._trace_id  # the root always generated one

    @property
    def span_id(self) -> str:
        if self._span_id is None:
            self._span_id = new_span_id()
        return self._span_id

    @property
    def parent_id(self) -> str | None:
        return self.parent.span_id if self.parent is not None else None

    def set_attribute(self, key: str, value) -> None:
        self.attrs[key] = value

    def child(self, name: str, /, **attrs) -> "Span":
        s = Span(name, parent=self, attrs=attrs)
        self.children.append(s)
        return s

    def add_synthetic(self, name: str, duration: float, /, **attrs) -> "Span":
        """A pre-measured child (e.g. queue wait — the time was spent
        before any span context existed, so the duration is injected)."""
        s = self.child(name, **attrs)
        s.duration = max(0.0, float(duration))
        return s

    def finish(self, status: str = "ok", error: str | None = None) -> None:
        if self.duration is None:
            self.duration = time.perf_counter() - self._start
        self.status = status
        self.error = error

    def fail(self, error: str) -> None:
        """Mark the span failed WITHOUT ending it — for handled errors
        that never escape the ``with`` block (a webhook deny response, a
        swallowed ApiError). __exit__ preserves the status."""
        self.status = "error"
        self.error = error

    def note_api_call(self, verb: str, kind: str | None) -> None:
        # api_calls is shared with the root — one dict per tree.
        key = (verb, kind or "")
        self.api_calls[key] = self.api_calls.get(key, 0) + 1

    def span_names(self) -> list[str]:
        """Every descendant span name, depth-first (test/debug helper)."""
        out = []
        for c in self.children:
            out.append(c.name)
            out.extend(c.span_names())
        return out

    def to_dict(self) -> dict:
        d: dict = {
            "name": self.name,
            "span_id": self.span_id,
            "duration_sec": round(self.duration, 6) if self.duration is not None else None,
            "status": self.status,
        }
        if self.error:
            d["error"] = self.error
        if self.attrs:
            d["attrs"] = {k: str(v) for k, v in self.attrs.items()}
        if self.children:
            d["spans"] = [c.to_dict() for c in self.children]
        return d


class _NoopSpan:
    """What span() yields when tracing is disabled — every method is a
    no-op so call sites never branch on the kill switch."""

    name = trace_id = span_id = parent_id = parent = None
    status, error, duration = "ok", None, None
    attrs: dict = {}
    children: list = []
    api_calls: dict = {}
    events: list = []

    def __enter__(self):
        return self

    def __exit__(self, *exc) -> bool:
        return False

    def set_attribute(self, key, value):  # noqa: D102
        pass

    def child(self, name, **attrs):
        return self

    def add_synthetic(self, name, duration, **attrs):
        return self

    def finish(self, status="ok", error=None):
        pass

    def fail(self, error):
        pass

    def note_api_call(self, verb, kind):
        pass

    def span_names(self):
        return []

    def to_dict(self):
        return {}


NOOP_SPAN = _NoopSpan()
_NoopSpan.root = NOOP_SPAN

_current: contextvars.ContextVar[Span | None] = contextvars.ContextVar(
    "kubeflow_tpu_span", default=None
)


def current_span() -> Span | None:
    return _current.get()


def current_trace_id() -> str | None:
    s = _current.get()
    return s.trace_id if s is not None else None


def note_api_call(verb: str, kind: str | None = None) -> None:
    """Tag the active trace with an API call (kube clients call this on
    every request). No active trace → no-op."""
    s = _current.get()
    if s is not None:
        s.note_api_call(verb, kind)


def note_event(reason: str) -> None:
    """Tag the active trace with an emitted Kubernetes Event reason."""
    s = _current.get()
    if s is not None:
        s.root.events.append(reason)


def span(name: str, /, *, trace_id: str | None = None, **attrs):
    """Open a span as a child of the context's current span (or a new
    root). Works across ``await`` — contextvars follow the task.

    ``trace_id`` seeds a ROOT span's trace id (request-ID middleware
    reuses an incoming ``X-Request-Id``); ignored when a parent exists —
    a child can't change the tree it's in. With tracing disabled
    (:func:`set_enabled`), returns the shared no-op span.
    """
    if not _enabled:
        return NOOP_SPAN
    parent = _current.get()
    s = Span(name, trace_id=trace_id, parent=parent, attrs=attrs)
    if parent is not None:
        parent.children.append(s)
    return s


def format_key(key) -> str:
    """Normalize a reconcile key — (namespace, name) tuple, string,
    whatever — to the flight recorder's ``ns/name`` string form."""
    if isinstance(key, (tuple, list)):
        return "/".join("-" if part is None else str(part) for part in key)
    return str(key)


class FlightRecorder:
    """Bounded per-key ring buffer of completed trace entries.

    ``per_key`` entries are retained per object key (a deque, oldest
    evicted first) and at most ``max_keys`` keys total (LRU on record —
    deleted objects age out instead of leaking). Thread-safe: the web
    /debug handlers read while reconcile workers write.
    """

    def __init__(self, per_key: int = 8, max_keys: int = 1024):
        self.per_key = per_key
        self.max_keys = max_keys
        self._buffers: "OrderedDict[str, deque]" = OrderedDict()
        self._lock = threading.Lock()
        self._seq = itertools.count()

    def record(self, entry: dict) -> None:
        """File one completed trace. The hot path stores the live span
        tree (``_root``) plus flat metadata; serialization to JSON shape
        happens lazily in :meth:`entries` — /debug reads pay it, not
        every reconcile."""
        key = entry.get("key") or "-"
        with self._lock:
            entry["seq"] = next(self._seq)
            buf = self._buffers.get(key)
            if buf is None:
                buf = self._buffers[key] = deque(maxlen=self.per_key)
            buf.append(entry)
            self._buffers.move_to_end(key)
            while len(self._buffers) > self.max_keys:
                self._buffers.popitem(last=False)

    @staticmethod
    def _expand(entry: dict) -> dict:
        root: Span | None = entry.get("_root")
        out = {k: v for k, v in entry.items() if not k.startswith("_")}
        if "_wall" in entry:
            out["time"] = fmt_iso(entry["_wall"])
        if root is not None:
            out["trace_id"] = root.trace_id
            out["api_calls"] = [
                {"verb": verb, "kind": kind, "count": count}
                for (verb, kind), count in sorted(root.api_calls.items())
            ]
            out["events"] = list(root.events)
            out["spans"] = [c.to_dict() for c in root.children]
        return out

    def entries(self, key=None, limit: int = 50) -> list[dict]:
        """Most-recent-first entries (JSON-shaped), optionally for one key."""
        with self._lock:
            if key is not None:
                rows = list(self._buffers.get(format_key(key), ()))
            else:
                rows = [e for buf in self._buffers.values() for e in buf]
        rows.sort(key=lambda e: e.get("seq", 0), reverse=True)
        return [self._expand(e) for e in rows[: max(0, limit)]]

    def __len__(self) -> int:
        with self._lock:
            return sum(len(b) for b in self._buffers.values())


class Tracer:
    """Root-trace factory: opens the root span, keeps the per-controller
    latency histogram (pre-existing metric name — dashboards carry over),
    and files every completed root into the flight recorder."""

    def __init__(self, registry: Registry | None = None,
                 recorder: FlightRecorder | None = None):
        registry = registry or global_registry
        self.h_duration = registry.histogram(
            "controller_reconcile_duration_seconds",
            "Reconcile latency per controller",
            ["controller"],
        )
        self.m_traces = registry.counter(
            "tracing_traces_total",
            "Completed root traces by outcome",
            ["root", "outcome"],
        )
        self.recorder = recorder or FlightRecorder()

    @contextlib.contextmanager
    def trace(self, name: str, /, *, key=None, controller: str | None = None,
              trace_id: str | None = None, **attrs):
        """Open a ROOT span; on exit (success or exception) observe the
        latency histogram and record the flight-recorder entry. Exceptions
        propagate — error handling stays the caller's business."""
        if not _enabled:
            yield NOOP_SPAN
            return
        key_str = format_key(key) if key is not None else None
        all_attrs = dict(attrs)
        if controller:
            all_attrs["controller"] = controller
        if key_str:
            all_attrs["key"] = key_str
        start_wall = time.time()
        error: str | None = None
        root: Span | None = None
        try:
            with span(name, trace_id=trace_id, **all_attrs) as root:
                yield root
        except BaseException as e:
            error = repr(e)
            raise
        finally:
            if root is not None and root is not NOOP_SPAN:
                # An escaped exception OR an in-block fail() (handled
                # error, e.g. an admission deny) both count as error.
                error = error or (root.error if root.status == "error" else None)
                outcome = "error" if error else "ok"
                self.h_duration.labels(
                    controller=str(controller or name)
                ).observe(root.duration or 0.0)
                self.m_traces.labels(root=name, outcome=outcome).inc()
                self.recorder.record({
                    "root": name,
                    "key": key_str or "-",
                    "controller": controller,
                    "outcome": outcome,
                    "error": error,
                    "duration_sec": round(root.duration or 0.0, 6),
                    "_wall": start_wall,
                    "_root": root,
                })
