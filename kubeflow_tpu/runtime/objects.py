"""Helpers over dict-shaped Kubernetes objects.

Objects are plain dicts everywhere (the Go stack's ``unstructured``), which
keeps the reference's central contract — a Notebook's ``spec.template.spec``
is a *literal PodSpec* (``notebook-controller/api/v1/notebook_types.go:27-34``)
— structurally true: every layer composes by editing the same dict.
"""

from __future__ import annotations

import calendar
import copy
import time
from typing import Any

ISO_FORMAT = "%Y-%m-%dT%H:%M:%SZ"  # k8s RFC3339 second precision


def fmt_iso(ts: float) -> str:
    return time.strftime(ISO_FORMAT, time.gmtime(ts))


def fmt_iso_micro(ts: float) -> str:
    """metav1.MicroTime — microsecond RFC3339, the real precision of the
    Lease ``renewTime`` field. Leader election MUST use this: rounding a
    renew stamp down a whole second makes a fresh sub-second lease read
    as already expired, and mutual exclusion collapses (every candidate
    acquires)."""
    micros = int(round(ts * 1_000_000))
    secs, frac = divmod(micros, 1_000_000)
    return time.strftime("%Y-%m-%dT%H:%M:%S", time.gmtime(secs)) \
        + f".{frac:06d}Z"


def now_iso() -> str:
    return fmt_iso(time.time())


def parse_iso(value: str) -> float | None:
    # Fractional seconds are split off and re-added: strptime's %f parses
    # them but struct_time cannot carry them, so the old %f formats were
    # silently truncating MicroTime stamps to whole seconds.
    frac = 0.0
    if "." in value:
        head, _, tail = value.partition(".")
        digits = tail.rstrip("Zz")
        if digits.isdigit():
            frac = float(f"0.{digits}")
            value = head + "Z"
    try:
        return calendar.timegm(time.strptime(value, ISO_FORMAT)) + frac
    except ValueError:
        return None


def new_object(
    kind: str,
    name: str,
    namespace: str | None = None,
    *,
    api_version: str | None = None,
    labels: dict[str, str] | None = None,
    annotations: dict[str, str] | None = None,
    spec: Any = None,
) -> dict:
    from kubeflow_tpu.runtime.scheme import DEFAULT_SCHEME

    meta: dict[str, Any] = {"name": name}
    if namespace is not None:
        meta["namespace"] = namespace
    if labels:
        meta["labels"] = dict(labels)
    if annotations:
        meta["annotations"] = dict(annotations)
    obj: dict[str, Any] = {
        "apiVersion": api_version or DEFAULT_SCHEME.by_kind(kind).api_version,
        "kind": kind,
        "metadata": meta,
    }
    if spec is not None:
        obj["spec"] = spec
    return obj


def get_meta(obj: dict) -> dict:
    return obj.setdefault("metadata", {})


def name_of(obj: dict) -> str:
    return get_meta(obj).get("name", "")


def namespace_of(obj: dict) -> str | None:
    return get_meta(obj).get("namespace")


def uid_of(obj: dict) -> str | None:
    return get_meta(obj).get("uid")


def labels_of(obj: dict) -> dict:
    return get_meta(obj).setdefault("labels", {})


def annotations_of(obj: dict) -> dict:
    return get_meta(obj).setdefault("annotations", {})


def key_of(obj: dict) -> tuple[str | None, str]:
    return namespace_of(obj), name_of(obj)


def deep_get(obj: dict, *path: str, default: Any = None) -> Any:
    cur: Any = obj
    for part in path:
        if not isinstance(cur, dict) or part not in cur:
            return default
        cur = cur[part]
    return cur


def deep_set(obj: dict, *path_and_value: Any) -> None:
    *path, value = path_and_value
    cur = obj
    for part in path[:-1]:
        cur = cur.setdefault(part, {})
    cur[path[-1]] = value


def deepcopy(obj):
    """Deep copy for JSON-shaped trees (dict/list/scalars).

    K8s wire objects are acyclic and contain only these types, so the
    specialized walk skips copy.deepcopy's memo table and per-type
    dispatch — the fakekube read path (every get/list/watch hands out a
    copy) measured ~4× faster, which directly bounds control-plane
    reconcile throughput in the bench. Unexpected types (a test sticking a
    tuple or custom object into a spec) fall back to copy.deepcopy.
    """
    t = type(obj)
    if t is dict:
        return {k: deepcopy(v) for k, v in obj.items()}
    if t is list:
        return [deepcopy(v) for v in obj]
    if t is str or t is int or t is float or t is bool or obj is None:
        return obj
    return copy.deepcopy(obj)


# ---- owner references ---------------------------------------------------------------


def controller_owner(owner: dict) -> dict:
    """Build a controller ownerReference (blockOwnerDeletion like kubebuilder)."""
    return {
        "apiVersion": owner["apiVersion"],
        "kind": owner["kind"],
        "name": name_of(owner),
        "uid": uid_of(owner),
        "controller": True,
        "blockOwnerDeletion": True,
    }


def set_controller_owner(obj: dict, owner: dict) -> dict:
    refs = get_meta(obj).setdefault("ownerReferences", [])
    ref = controller_owner(owner)
    for existing in refs:
        if existing.get("uid") == ref["uid"]:
            existing.update(ref)
            return obj
    refs.append(ref)
    return obj


def owned_by(obj: dict, owner: dict) -> bool:
    uid = uid_of(owner)
    return any(r.get("uid") == uid for r in get_meta(obj).get("ownerReferences", []))


def controller_of(obj: dict) -> dict | None:
    for r in get_meta(obj).get("ownerReferences", []):
        if r.get("controller"):
            return r
    return None


# ---- label selectors ----------------------------------------------------------------


def matches_selector(labels: dict[str, str] | None, selector: dict | None) -> bool:
    """Evaluate a LabelSelector dict (matchLabels + matchExpressions).

    Mirrors the semantics the PodDefault webhook relies on
    (``admission-webhook/main.go:72-97`` label-selector filtering).
    """
    if not selector:
        return True  # empty selector matches everything
    labels = labels or {}
    for k, v in (selector.get("matchLabels") or {}).items():
        if labels.get(k) != v:
            return False
    for expr in selector.get("matchExpressions") or []:
        key, op = expr.get("key"), expr.get("operator")
        values = expr.get("values") or []
        if op == "In":
            if labels.get(key) not in values:
                return False
        elif op == "NotIn":
            if labels.get(key) in values:
                return False
        elif op == "Exists":
            if key not in labels:
                return False
        elif op == "DoesNotExist":
            if key in labels:
                return False
        else:
            return False
    return True


def parse_label_selector(selector: str | None) -> dict | None:
    """Parse a string selector ("a=b,c!=d,e") into LabelSelector dict form."""
    if not selector:
        return None
    match_labels: dict[str, str] = {}
    exprs: list[dict] = []
    for part in selector.split(","):
        part = part.strip()
        if not part:
            continue
        if "!=" in part:
            k, v = part.split("!=", 1)
            exprs.append({"key": k.strip(), "operator": "NotIn", "values": [v.strip()]})
        elif "==" in part:
            k, v = part.split("==", 1)
            match_labels[k.strip()] = v.strip()
        elif "=" in part:
            k, v = part.split("=", 1)
            match_labels[k.strip()] = v.strip()
        else:
            exprs.append({"key": part, "operator": "Exists"})
    out: dict = {}
    if match_labels:
        out["matchLabels"] = match_labels
    if exprs:
        out["matchExpressions"] = exprs
    return out or None


def selector_to_string(selector: str | dict | None) -> str | None:
    """Serialize a LabelSelector for the real apiserver's ?labelSelector=."""
    if selector is None or isinstance(selector, str):
        return selector
    parts: list[str] = []
    for k, v in (selector.get("matchLabels") or {}).items():
        parts.append(f"{k}={v}")
    for expr in selector.get("matchExpressions") or []:
        key, op = expr.get("key"), expr.get("operator")
        values = ",".join(expr.get("values") or [])
        if op == "In":
            parts.append(f"{key} in ({values})")
        elif op == "NotIn":
            parts.append(f"{key} notin ({values})")
        elif op == "Exists":
            parts.append(key)
        elif op == "DoesNotExist":
            parts.append(f"!{key}")
    return ",".join(parts) or None
