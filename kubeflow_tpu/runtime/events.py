"""Event recorder: create/aggregate v1 Events on objects.

The reference re-emits pod/STS events onto Notebook CRs so the UI can surface
them (``notebook_controller.go:94-123``); this recorder provides the emit
side, with count aggregation like client-go's EventRecorder.
"""

from __future__ import annotations

import hashlib

from kubeflow_tpu.runtime import tracing
from kubeflow_tpu.runtime.errors import ApiError, NotFound
from kubeflow_tpu.runtime.objects import name_of, namespace_of, uid_of
from kubeflow_tpu.runtime.objects import now_iso as _now


class EventRecorder:
    def __init__(self, kube, component: str):
        self.kube = kube
        self.component = component

    async def event(
        self, obj: dict, event_type: str, reason: str, message: str
    ) -> None:
        # The flight-recorder entry lists the reasons a reconcile emitted,
        # next to the API verbs it issued.
        tracing.note_event(reason)
        namespace = namespace_of(obj) or "default"
        ref = {
            "apiVersion": obj.get("apiVersion"),
            "kind": obj.get("kind"),
            "name": name_of(obj),
            "namespace": namespace_of(obj),
            "uid": uid_of(obj),
        }
        digest = hashlib.sha1(
            f"{ref['kind']}/{ref['namespace']}/{ref['name']}/{reason}/{message}".encode()
        ).hexdigest()[:10]
        name = f"{name_of(obj)}.{digest}"
        try:
            existing = await self.kube.get("Event", name, namespace)
        except NotFound:
            existing = None
        if existing:
            try:
                await self.kube.patch(
                    "Event",
                    name,
                    {"count": existing.get("count", 1) + 1, "lastTimestamp": _now()},
                    namespace,
                )
                return
            except ApiError:
                return
        event = {
            "apiVersion": "v1",
            "kind": "Event",
            "metadata": {"name": name, "namespace": namespace},
            "involvedObject": ref,
            "reason": reason,
            "message": message,
            "type": event_type,
            "source": {"component": self.component},
            "firstTimestamp": _now(),
            "lastTimestamp": _now(),
            "count": 1,
        }
        try:
            await self.kube.create("Event", event)
        except ApiError:
            pass  # events are best-effort
