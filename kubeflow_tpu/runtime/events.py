"""Event recorder: create/aggregate v1 Events on objects.

The reference re-emits pod/STS events onto Notebook CRs so the UI can surface
them (``notebook_controller.go:94-123``); this recorder provides the emit
side, with count aggregation like client-go's EventRecorder.
"""

from __future__ import annotations

import hashlib
from collections import OrderedDict

from kubeflow_tpu.runtime import tracing
from kubeflow_tpu.runtime.errors import AlreadyExists, ApiError, NotFound
from kubeflow_tpu.runtime.metrics import Registry, global_registry
from kubeflow_tpu.runtime.objects import name_of, namespace_of, uid_of
from kubeflow_tpu.runtime.objects import now_iso as _now


class EventRecorder:
    # Known-digest LRU bound: enough for every hot event series of a busy
    # controller; an evicted digest costs one GET on its next emit.
    CACHE_SIZE = 512

    def __init__(self, kube, component: str,
                 registry: Registry | None = None):
        self.kube = kube
        self.component = component
        # Events are best-effort BY CONTRACT: a failed create/patch (an
        # injected 500, a saturated apiserver) must never fail the
        # reconcile that emitted it — swallowed here, visible there.
        self._emit_failures = (registry or global_registry).counter(
            "events_emit_failures_total",
            "Event create/patch attempts swallowed as best-effort",
            ["component"],
        )
        # (namespace, event-name) → last-written count. Steady-state
        # aggregation (the overwhelmingly common case: the same reason
        # re-emitted every reconcile) patches the count directly instead
        # of paying a GET round trip per emit just to decide
        # create-vs-patch. NotFound on the patch (event TTL'd/GC'd under
        # us) invalidates the entry and falls back to create.
        self._known: OrderedDict[tuple, int] = OrderedDict()

    def count_drop(self) -> None:
        """Count an emission dropped OUTSIDE the recorder (a caller's
        best-effort guard around :meth:`event` — non-API failures the
        recorder itself can't see). Same ``events_emit_failures_total``
        series as the recorder's own swallows, so 'events stopped
        appearing' always has one metric to alert on (the
        ``exception-swallow`` pass rejects uncounted drops)."""
        self._emit_failures.labels(component=self.component).inc()

    def _remember(self, key: tuple, count: int) -> None:
        self._known[key] = count
        self._known.move_to_end(key)
        while len(self._known) > self.CACHE_SIZE:
            self._known.popitem(last=False)

    async def event(
        self, obj: dict, event_type: str, reason: str, message: str
    ) -> None:
        # The flight-recorder entry lists the reasons a reconcile emitted,
        # next to the API verbs it issued.
        tracing.note_event(reason)
        namespace = namespace_of(obj) or "default"
        ref = {
            "apiVersion": obj.get("apiVersion"),
            "kind": obj.get("kind"),
            "name": name_of(obj),
            "namespace": namespace_of(obj),
            "uid": uid_of(obj),
        }
        digest = hashlib.sha1(
            f"{ref['kind']}/{ref['namespace']}/{ref['name']}/{reason}/{message}".encode()
        ).hexdigest()[:10]
        name = f"{name_of(obj)}.{digest}"
        key = (namespace, name)
        count = self._known.get(key)
        if count is not None:
            try:
                await self.kube.patch(
                    "Event",
                    name,
                    {"count": count + 1, "lastTimestamp": _now()},
                    namespace,
                )
                self._remember(key, count + 1)
                return
            except NotFound:
                # The event expired between emits; create it fresh below.
                self._known.pop(key, None)
            except ApiError:
                self._emit_failures.labels(component=self.component).inc()
                return
        # Cold miss: optimistic create — a brand-new event (the common
        # cold case) costs ONE round trip instead of GET + create; an
        # AlreadyExists (recorder restart over a live event, or a racing
        # writer) falls back to read-and-aggregate.
        event = {
            "apiVersion": "v1",
            "kind": "Event",
            "metadata": {"name": name, "namespace": namespace},
            "involvedObject": ref,
            "reason": reason,
            "message": message,
            "type": event_type,
            "source": {"component": self.component},
            "firstTimestamp": _now(),
            "lastTimestamp": _now(),
            "count": 1,
        }
        try:
            await self.kube.create("Event", event)
            self._remember(key, 1)
            return
        except AlreadyExists:
            pass
        except ApiError:
            self._emit_failures.labels(component=self.component).inc()
            return  # events are best-effort
        try:
            existing = await self.kube.get("Event", name, namespace)
            await self.kube.patch(
                "Event",
                existing["metadata"]["name"],
                {"count": existing.get("count", 1) + 1,
                 "lastTimestamp": _now()},
                namespace,
            )
            self._remember(key, existing.get("count", 1) + 1)
        except ApiError:
            self._emit_failures.labels(component=self.component).inc()
            self._known.pop(key, None)
