"""Lease-based leader election.

controller-runtime equivalent (the reference managers pass
``--leader-elect``; e.g. ``notebook-controller/main.go``): one replica holds
a ``coordination.k8s.io/v1`` Lease and runs the controllers; standbys renew-
watch and take over when the lease expires. The same object/protocol as
client-go's leaderelection package, asyncio-native.
"""

from __future__ import annotations

import asyncio
import logging
import uuid

from kubeflow_tpu.runtime.aiotasks import reap
from kubeflow_tpu.runtime.errors import ApiError, NotFound
from kubeflow_tpu.runtime.objects import deep_get, fmt_iso, parse_iso

log = logging.getLogger(__name__)


class LeaderElector:
    def __init__(
        self,
        kube,
        *,
        lease_name: str = "kubeflow-tpu-controller-manager",
        namespace: str = "kubeflow-tpu",
        identity: str | None = None,
        lease_seconds: float = 15.0,
        renew_seconds: float = 5.0,
        retry_seconds: float = 2.0,
        clock=None,
    ):
        self.kube = kube
        self.lease_name = lease_name
        self.namespace = namespace
        self.identity = identity or f"manager-{uuid.uuid4().hex[:8]}"
        self.lease_seconds = lease_seconds
        self.renew_seconds = renew_seconds
        self.retry_seconds = retry_seconds
        import time as _time

        self.clock = clock or _time.time
        self.is_leader = False
        self._renew_task: asyncio.Task | None = None

    def _lease_body(self) -> dict:
        return {
            "apiVersion": "coordination.k8s.io/v1",
            "kind": "Lease",
            "metadata": {"name": self.lease_name, "namespace": self.namespace},
            "spec": {
                "holderIdentity": self.identity,
                "leaseDurationSeconds": int(self.lease_seconds),
                "renewTime": fmt_iso(self.clock()),
            },
        }

    def _expired(self, lease: dict) -> bool:
        renew = parse_iso(deep_get(lease, "spec", "renewTime", default="") or "")
        duration = deep_get(
            lease, "spec", "leaseDurationSeconds", default=self.lease_seconds
        )
        if renew is None:
            return True
        return self.clock() - renew > duration

    async def try_acquire(self) -> bool:
        """One acquisition attempt; True when this identity holds the lease.
        Any apiserver error is a failed attempt, never an exception — a
        transient blip must not crash acquire() nor kill the renew loop."""
        try:
            lease = await self.kube.get("Lease", self.lease_name, self.namespace)
        except NotFound:
            try:
                await self.kube.create("Lease", self._lease_body())
                return True
            except ApiError:
                return False
        except ApiError:
            return False
        holder = deep_get(lease, "spec", "holderIdentity")
        if holder == self.identity or self._expired(lease):
            lease["spec"] = self._lease_body()["spec"]
            try:
                await self.kube.update("Lease", lease)
                return True
            except ApiError:
                return False
        return False

    async def acquire(self) -> None:
        """Block until leadership is held, then keep renewing in background."""
        while not await self.try_acquire():
            await asyncio.sleep(self.retry_seconds)
        self.is_leader = True
        log.info("leader election: %s acquired %s", self.identity, self.lease_name)
        self._renew_task = asyncio.create_task(self._renew_loop())

    async def _renew_loop(self) -> None:
        try:
            failures = 0
            while True:
                await asyncio.sleep(self.renew_seconds)
                if await self.try_acquire():
                    failures = 0
                    continue
                # Tolerate transient renew failures while the lease we hold
                # is still fresh; give up once it could have expired.
                failures += 1
                if failures * self.renew_seconds >= self.lease_seconds:
                    break
        except asyncio.CancelledError:
            raise
        except Exception:
            log.exception("leader election: renew loop crashed")
        # Lost (or possibly lost) the lease: a split-brain manager must
        # stop reconciling immediately.
        self.is_leader = False
        log.error("leader election: %s LOST %s", self.identity, self.lease_name)

    async def release(self) -> None:
        if self._renew_task:
            self._renew_task.cancel()
            await reap(self._renew_task)
        if self.is_leader:
            try:
                lease = await self.kube.get(
                    "Lease", self.lease_name, self.namespace
                )
                if deep_get(lease, "spec", "holderIdentity") == self.identity:
                    lease["spec"]["holderIdentity"] = ""
                    lease["spec"]["renewTime"] = None
                    await self.kube.update("Lease", lease)
            except ApiError:
                pass
        self.is_leader = False
