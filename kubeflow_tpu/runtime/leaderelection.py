"""Lease-based leader election.

controller-runtime equivalent (the reference managers pass
``--leader-elect``; e.g. ``notebook-controller/main.go``): one replica holds
a ``coordination.k8s.io/v1`` Lease and runs the controllers; standbys renew-
watch and take over when the lease expires. The same object/protocol as
client-go's leaderelection package, asyncio-native.

``ShardRing`` (runtime/sharding.py) composes N of these — one Lease per
keyspace shard — into an active-active membership ring.
"""

from __future__ import annotations

import asyncio
import logging
import time
import uuid

from kubeflow_tpu.runtime.aiotasks import reap
from kubeflow_tpu.runtime.errors import ApiError, NotFound
from kubeflow_tpu.runtime.metrics import global_registry
from kubeflow_tpu.runtime.objects import deep_get, fmt_iso_micro, parse_iso

log = logging.getLogger(__name__)


class LeaderElector:
    def __init__(
        self,
        kube,
        *,
        lease_name: str = "kubeflow-tpu-controller-manager",
        namespace: str = "kubeflow-tpu",
        identity: str | None = None,
        lease_seconds: float = 15.0,
        renew_seconds: float = 5.0,
        retry_seconds: float = 2.0,
        clock=None,
        registry=None,
        on_lost=None,
    ):
        self.kube = kube
        self.lease_name = lease_name
        self.namespace = namespace
        self.identity = identity or f"manager-{uuid.uuid4().hex[:8]}"
        self.lease_seconds = lease_seconds
        self.renew_seconds = renew_seconds
        self.retry_seconds = retry_seconds
        self.clock = clock or time.time
        self.is_leader = False
        self.transitions = 0
        # Sync callback fired from the renew loop the moment leadership is
        # (possibly) lost — split-brain fencing must not wait for a poll.
        self._on_lost = on_lost
        self._renew_task: asyncio.Task | None = None
        registry = registry or global_registry
        self._m_held = registry.gauge(
            "leader_election_is_leader",
            "1 while this process holds the named lease",
            ["lease"])
        self._m_transitions = registry.counter(
            "leader_election_transitions_total",
            "Leadership acquisitions and losses observed by this process",
            ["lease", "event"])  # acquired | lost

    def _set_leader(self, held: bool) -> None:
        if held == self.is_leader:
            return
        self.is_leader = held
        self.transitions += 1
        self._m_held.labels(lease=self.lease_name).set(1.0 if held else 0.0)
        self._m_transitions.labels(
            lease=self.lease_name,
            event="acquired" if held else "lost").inc()
        if not held and self._on_lost is not None:
            try:
                self._on_lost(self)
            except Exception:
                log.exception("leader election: on_lost callback failed")

    def _lease_body(self) -> dict:
        # The apiserver's field is int32 seconds; int() would truncate a
        # sub-second test lease to 0 — instantly expired for EVERY reader,
        # which collapses mutual exclusion (all candidates acquire). Keep
        # the float for fractional durations (FakeKube soak clocks only;
        # production configs are whole seconds).
        duration = (int(self.lease_seconds) if self.lease_seconds >= 1
                    else self.lease_seconds)
        return {
            "apiVersion": "coordination.k8s.io/v1",
            "kind": "Lease",
            "metadata": {"name": self.lease_name, "namespace": self.namespace},
            "spec": {
                "holderIdentity": self.identity,
                "leaseDurationSeconds": duration,
                "renewTime": fmt_iso_micro(self.clock()),
            },
        }

    def _expired(self, lease: dict) -> bool:
        renew = parse_iso(deep_get(lease, "spec", "renewTime", default="") or "")
        duration = deep_get(
            lease, "spec", "leaseDurationSeconds", default=self.lease_seconds
        )
        if renew is None:
            return True
        return self.clock() - renew > duration

    async def current_holder(self) -> str | None:
        """Read the lease's holder (None when absent/unset/expired) —
        observability only, never part of the acquisition protocol."""
        try:
            lease = await self.kube.get("Lease", self.lease_name, self.namespace)
        except ApiError:
            return None
        if self._expired(lease):
            return None
        return deep_get(lease, "spec", "holderIdentity") or None

    async def try_acquire(self) -> bool:
        """One acquisition attempt; True when this identity holds the lease.
        Any apiserver error is a failed attempt, never an exception — a
        transient blip must not crash acquire() nor kill the renew loop."""
        try:
            lease = await self.kube.get("Lease", self.lease_name, self.namespace)
        except NotFound:
            try:
                await self.kube.create("Lease", self._lease_body())
                return True
            except ApiError:
                return False
        except ApiError:
            return False
        holder = deep_get(lease, "spec", "holderIdentity")
        if holder == self.identity or self._expired(lease):
            lease["spec"] = self._lease_body()["spec"]
            try:
                # kftpu: ignore[await-race] the update IS the CAS: it carries the resourceVersion read above, and the apiserver rejects a racing writer with Conflict — re-validation is server-side
                await self.kube.update("Lease", lease)
                return True
            except ApiError:
                return False
        return False

    async def acquire(self) -> None:
        """Block until leadership is held, then keep renewing in background."""
        while not await self.try_acquire():
            await asyncio.sleep(self.retry_seconds)
        self._set_leader(True)
        log.info("leader election: %s acquired %s", self.identity, self.lease_name)
        self._renew_task = asyncio.create_task(self._renew_loop())

    async def _renew_loop(self) -> None:
        try:
            failures = 0
            while True:
                await asyncio.sleep(self.renew_seconds)
                if await self.try_acquire():
                    failures = 0
                    continue
                # Tolerate transient renew failures while the lease we hold
                # is still fresh; give up once it could have expired.
                failures += 1
                if failures * self.renew_seconds >= self.lease_seconds:
                    break
        except asyncio.CancelledError:
            raise
        except Exception:
            log.exception("leader election: renew loop crashed")
        # Lost (or possibly lost) the lease: a split-brain manager must
        # stop reconciling immediately.
        self._set_leader(False)
        log.error("leader election: %s LOST %s", self.identity, self.lease_name)

    async def release(self) -> None:
        if self._renew_task:
            self._renew_task.cancel()
            await reap(self._renew_task)
            # kftpu: ignore[await-race] the cancel above stopped the only other writer of _renew_task; release() itself is not re-entered (callers serialize shutdown)
            self._renew_task = None
        # Unconditionally offer the lease back when the API says we hold
        # it — callers that drive try_acquire() directly (ShardRing) never
        # set is_leader, and a graceful departure must not leave survivors
        # waiting out the full lease expiry.
        try:
            lease = await self.kube.get(
                "Lease", self.lease_name, self.namespace
            )
            if deep_get(lease, "spec", "holderIdentity") == self.identity:
                lease["spec"]["holderIdentity"] = ""
                lease["spec"]["renewTime"] = None
                # kftpu: ignore[await-race] CAS again: the update carries the freshly-read resourceVersion, so clearing a lease stolen mid-flight fails with Conflict instead of clobbering
                await self.kube.update("Lease", lease)
        except ApiError:
            pass
        self._set_leader(False)
