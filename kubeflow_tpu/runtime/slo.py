"""Fleet SLO engine: declarative SLIs, sliding windows, burn rates.

An operator serving millions of users needs one page that answers "are
we meeting our latency promises?" — not a wall of raw histograms. This
module is that layer, in the SRE multi-window multi-burn-rate idiom
(Google SRE workbook ch. 5; the Prometheus/OpenTelemetry ecosystem the
reference stack assumes):

- **Declarative SLI registry** (:data:`SLI_SPECS`): each SLI is a named
  latency promise — notebook time-to-ready, scheduler time-to-admission,
  drain roundtrip, serving request latency, reconcile latency — fed from
  the instrumentation that already exists (the scheduler's wait
  histogram, the drain timer, the manager's reconcile clock, the serving
  engine's completions). Zero new measurement points; the SLO layer is a
  second consumer of the same numbers.
- **Objectives from env** (``KFTPU_SLO_<SLI>``): ``"30"`` (seconds) or
  ``"30:0.995"`` (seconds:target). The default target is 0.99 — "99% of
  events under the threshold".
- **Sliding windows + burn rates**: per SLI, good/bad counters in
  10-second buckets retained for 6 h; burn rate over 5m/1h/6h windows is
  ``bad_fraction / error_budget`` — burn 1.0 spends the budget exactly
  at the objective's rate, 14.4 spends 2% of a 30-day budget per hour
  (the classic page threshold). Surfaced as
  ``tpu_slo_burn_rate{sli,window}`` / ``tpu_slo_budget_remaining{sli}``
  gauges and the ``/debug/slo`` page (worst offenders with exemplar
  trace ids linked from the flight recorder).

Overhead is bench-gated (``bench.py slo_overhead``, <5% of
control-plane throughput — the same protocol as the PR 3 tracing gate);
:func:`set_enabled` is the A/B switch the bench flips.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque

from kubeflow_tpu.runtime.metrics import Registry, global_registry

# Master switch (docs/operations.md "SLOs & burn-rate alerting").
SLO_ENABLED_ENV = "KFTPU_SLO"

# The SLI registry: (name, objective env knob, default threshold seconds,
# default target, description). A PURE LITERAL on purpose — the
# ``slo-registry`` analysis pass (ci/analysis/passes/sloreg.py) reads it
# from the AST and fails CI when an SLI's knob or name is missing from
# docs/operations.md, so the registry and the runbook cannot drift.
SLI_SPECS = (
    ("notebook_time_to_ready", "KFTPU_SLO_NOTEBOOK_TIME_TO_READY",
     30.0, 0.99,
     "start of a notebook's startup episode (create / re-queue / "
     "restore) to every TPU worker Ready, from the lifecycle timeline"),
    ("scheduler_time_to_admission", "KFTPU_SLO_TIME_TO_ADMISSION",
     60.0, 0.99,
     "gang submission to fleet-scheduler admission (the scheduler's "
     "admission-wait histogram, per admitted gang)"),
    ("drain_roundtrip", "KFTPU_SLO_DRAIN_ROUNDTRIP",
     60.0, 0.99,
     "drain request to checkpoint-ack park (grace-deadline hard stops "
     "count as bad events at the full elapsed time)"),
    ("serving_latency", "KFTPU_SLO_SERVING_LATENCY",
     2.0, 0.99,
     "per-request serving latency (arrival to completion) from the "
     "JAX serving engine's continuous-batching loop"),
    ("reconcile_latency", "KFTPU_SLO_RECONCILE_LATENCY",
     1.0, 0.999,
     "reconcile wall time per workqueue key across every controller"),
    ("checkpoint_commit", "KFTPU_SLO_CHECKPOINT_COMMIT",
     60.0, 0.99,
     "checkpoint snapshot-ack to durable commit (the background upload "
     "the drain SLI deliberately excludes; commit-grace timeouts count "
     "as bad events even when the grace is below the objective)"),
    ("restore", "KFTPU_SLO_RESTORE",
     30.0, 0.99,
     "checkpoint restore wall time through the tier fallthrough "
     "(staging or object store), including integrity-fallback reads"),
    ("training_step", "KFTPU_SLO_TRAINING_STEP",
     1.0, 0.99,
     "rolling-window p50 training-step wall time from the telemetry "
     "annotation, fed once per new publish seq by the notebook "
     "controller's status fold"),
)

# Multi-window set: the short window catches a fast burn the moment it
# starts, the long ones keep a slow leak visible. Fixed — alerting math
# (the 14.4/6 thresholds below) is calibrated to these widths.
WINDOWS = (("5m", 300.0), ("1h", 3600.0), ("6h", 21600.0))
LONGEST_WINDOW_SECONDS = 21600.0
BUCKET_SECONDS = 10.0

# Multi-window multi-burn-rate alerting thresholds (SRE workbook): page
# when BOTH the 5m and 1h burn exceed 14.4 (2% of a 30-day budget per
# hour, still burning), warn when both 1h and 6h exceed 6.
CRITICAL_BURN = 14.4
WARNING_BURN = 6.0

_enabled = True  # process-wide A/B switch for the overhead bench


def set_enabled(on: bool) -> None:
    global _enabled
    _enabled = bool(on)


def is_enabled() -> bool:
    return _enabled


def slo_enabled(environ=os.environ) -> bool:
    """``KFTPU_SLO`` master switch — anything but off/false/0/no keeps
    the engine on."""
    return environ.get(SLO_ENABLED_ENV, "on").strip().lower() not in (
        "off", "false", "0", "no", "disabled",
    )


def objective_for(name: str, environ=os.environ) -> tuple[float, float]:
    """(threshold seconds, target fraction) for one SLI — the pure
    env-reading half, importable by the web backend (the JWA
    waiting-longer-than-expected message) without an engine. Accepts
    ``"30"`` or ``"30:0.995"``; malformed values fall back to the
    spec default."""
    for sli, env, threshold, target, _desc in SLI_SPECS:
        if sli != name:
            continue
        raw = environ.get(env)
        if raw:
            head, _, tail = raw.strip().partition(":")
            try:
                threshold = float(head)
                if tail:
                    t = float(tail)
                    if 0.0 < t < 1.0:
                        target = t
            except ValueError:
                pass
        return threshold, target
    raise KeyError(f"unknown SLI {name!r} (registry: "
                   f"{[s[0] for s in SLI_SPECS]})")


class _Sli:
    """One SLI's counters: good/bad in time buckets + worst offenders."""

    def __init__(self, name: str, threshold: float, target: float,
                 description: str, env: str):
        self.name = name
        self.threshold = threshold
        self.target = target
        self.description = description
        self.env = env
        # deque of [bucket_index, good, bad]; bucket_index = now // 10s.
        self.buckets: deque = deque()
        # Worst offenders: recent bad observations with exemplar trace
        # ids (the /debug/slo → /debug/traces?key= join).
        self.offenders: deque = deque(maxlen=8)
        self.total_good = 0
        self.total_bad = 0

    @property
    def error_budget(self) -> float:
        return max(1e-9, 1.0 - self.target)

    def observe(self, seconds: float, *, now: float, key=None,
                trace_id: str | None = None) -> bool:
        good = seconds <= self.threshold
        idx = int(now // BUCKET_SECONDS)
        if self.buckets and self.buckets[-1][0] == idx:
            bucket = self.buckets[-1]
        elif self.buckets and self.buckets[-1][0] > idx:
            bucket = self.buckets[-1]  # clock went backwards; keep order
        else:
            self.buckets.append([idx, 0, 0])
            bucket = self.buckets[-1]
        bucket[1 if good else 2] += 1
        if good:
            self.total_good += 1
        else:
            self.total_bad += 1
            self.offenders.append({
                "key": ("/".join(str(p) for p in key)
                        if isinstance(key, (tuple, list)) else key),
                "seconds": round(float(seconds), 4),
                "trace_id": trace_id,
                "at": now,
            })
        horizon = idx - int(LONGEST_WINDOW_SECONDS // BUCKET_SECONDS) - 1
        while self.buckets and self.buckets[0][0] < horizon:
            self.buckets.popleft()
        return good

    def counts(self, window_seconds: float, now: float) -> tuple[int, int]:
        """(good, bad) inside the trailing window."""
        cutoff = int((now - window_seconds) // BUCKET_SECONDS)
        good = bad = 0
        for idx, g, b in reversed(self.buckets):
            if idx <= cutoff:
                break
            good += g
            bad += b
        return good, bad

    def burn_rate(self, window_seconds: float, now: float) -> float:
        good, bad = self.counts(window_seconds, now)
        total = good + bad
        if total == 0:
            return 0.0
        return (bad / total) / self.error_budget

    def budget_remaining(self, now: float) -> float:
        """Fraction of the error budget left over the LONGEST window,
        floored at 0 (a blown budget reads 0, never negative)."""
        good, bad = self.counts(LONGEST_WINDOW_SECONDS, now)
        total = good + bad
        if total == 0:
            return 1.0
        return max(0.0, 1.0 - (bad / total) / self.error_budget)

    def health(self, now: float) -> str:
        b5 = self.burn_rate(WINDOWS[0][1], now)
        b1 = self.burn_rate(WINDOWS[1][1], now)
        b6 = self.burn_rate(WINDOWS[2][1], now)
        if b5 >= CRITICAL_BURN and b1 >= CRITICAL_BURN:
            return "critical"
        if b1 >= WARNING_BURN and b6 >= WARNING_BURN:
            return "warning"
        return "ok"


class SloEngine:
    """The manager-owned engine: observes, computes burn rates, exposes
    the gauges and the ``/debug/slo`` payload. Thread-safe — the serving
    engine's worker thread observes while the event loop reads."""

    def __init__(self, registry: Registry | None = None, *,
                 environ=os.environ, now=time.time):
        self.enabled = slo_enabled(environ)
        self._now = now
        self._lock = threading.Lock()
        self.slis: dict[str, _Sli] = {}
        for name, env, _thr, _tgt, desc in SLI_SPECS:
            # objective_for is the ONE reader of the objective (spec
            # default + env override); the spec's literal defaults are
            # deliberately unused here.
            thr, tgt = objective_for(name, environ)
            self.slis[name] = _Sli(name, thr, tgt, desc, env)
        registry = registry or global_registry
        self.g_burn = registry.gauge(
            "tpu_slo_burn_rate",
            "Error-budget burn rate per SLI and window (1.0 = spending "
            "exactly at the objective's rate)", ["sli", "window"])
        self.g_budget = registry.gauge(
            "tpu_slo_budget_remaining",
            "Fraction of the 6h error budget remaining per SLI (never "
            "negative)", ["sli"])
        self.c_events = registry.counter(
            "tpu_slo_events_total",
            "SLI events by outcome vs the objective threshold",
            ["sli", "outcome"])

    def observe(self, sli: str, seconds: float, *, key=None,
                trace_id: str | None = None, now: float | None = None,
                ) -> None:
        """Feed one measurement. Unknown SLI names raise — a typo'd feed
        silently counting nowhere is exactly the drift class the
        registry exists to kill."""
        if not (_enabled and self.enabled):
            return
        entry = self.slis.get(sli)
        if entry is None:
            raise KeyError(f"unknown SLI {sli!r}")
        t = self._now() if now is None else now
        with self._lock:
            good = entry.observe(float(seconds), now=t, key=key,
                                 trace_id=trace_id)
        self.c_events.labels(sli=sli,
                             outcome="good" if good else "bad").inc()

    def refresh(self, now: float | None = None) -> None:
        """Recompute the burn/budget gauges (called by /metrics and
        /debug/slo — scrape-time, not per-observation)."""
        t = self._now() if now is None else now
        with self._lock:
            for name, entry in self.slis.items():
                for wname, wsec in WINDOWS:
                    self.g_burn.labels(sli=name, window=wname).set(
                        round(entry.burn_rate(wsec, t), 4))
                self.g_budget.labels(sli=name).set(
                    round(entry.budget_remaining(t), 4))

    def burn_rate(self, sli: str, window: str,
                  now: float | None = None) -> float:
        t = self._now() if now is None else now
        wsec = dict(WINDOWS)[window]
        with self._lock:
            return self.slis[sli].burn_rate(wsec, t)

    def counts(self, sli: str, window: str,
               now: float | None = None) -> tuple[int, int]:
        t = self._now() if now is None else now
        wsec = dict(WINDOWS)[window]
        with self._lock:
            return self.slis[sli].counts(wsec, t)

    def budget_remaining(self, sli: str, now: float | None = None) -> float:
        t = self._now() if now is None else now
        with self._lock:
            return self.slis[sli].budget_remaining(t)

    def debug_info(self, now: float | None = None) -> dict:
        """The ``/debug/slo`` payload: per-SLI objective, window counts,
        burn rates, budget, health, and the worst offenders with their
        exemplar trace ids."""
        t = self._now() if now is None else now
        out: dict = {"enabled": self.enabled and _enabled, "slis": []}
        worst_health = "ok"
        rank = {"ok": 0, "warning": 1, "critical": 2}
        with self._lock:
            for name, e in self.slis.items():
                health = e.health(t)
                if rank[health] > rank[worst_health]:
                    worst_health = health
                windows = {}
                for wname, wsec in WINDOWS:
                    good, bad = e.counts(wsec, t)
                    windows[wname] = {
                        "good": good, "bad": bad,
                        "burn_rate": round(e.burn_rate(wsec, t), 4),
                    }
                out["slis"].append({
                    "sli": name,
                    "description": e.description,
                    "objective": {
                        "threshold_seconds": e.threshold,
                        "target": e.target,
                        "env": e.env,
                    },
                    "windows": windows,
                    "budget_remaining": round(e.budget_remaining(t), 4),
                    "health": health,
                    "events": {"good": e.total_good, "bad": e.total_bad},
                    "worst_offenders": sorted(
                        ({**o, "at_ago_sec": round(t - o["at"], 1)}
                         for o in e.offenders),
                        key=lambda o: -o["seconds"]),
                })
        out["health"] = worst_health
        out["alerting"] = {
            "critical": f"burn_rate(5m) >= {CRITICAL_BURN} AND "
                        f"burn_rate(1h) >= {CRITICAL_BURN}",
            "warning": f"burn_rate(1h) >= {WARNING_BURN} AND "
                       f"burn_rate(6h) >= {WARNING_BURN}",
        }
        return out


# ---- process-wide current engine -----------------------------------------------
# Producers scattered across layers (scheduler admission, drain finalize,
# serving engine completions) feed the module-level observe(): the
# manager installs its engine at construction, so no constructor
# threading is needed — exactly the "zero new instrumentation points"
# contract. No engine installed (bare unit tests) → feeds are no-ops.

_current: SloEngine | None = None


def install(engine: SloEngine | None) -> SloEngine | None:
    global _current
    _current = engine
    return engine


def current() -> SloEngine | None:
    return _current


def observe(sli: str, seconds: float, *, key=None,
            trace_id: str | None = None) -> None:
    engine = _current
    if engine is not None:
        engine.observe(sli, seconds, key=key, trace_id=trace_id)
