"""Rate-limited workqueue (client-go semantics, asyncio-native).

Deduplicates keys while queued, tracks in-flight keys so a key re-added during
processing is re-queued afterwards, and applies per-item exponential backoff —
the behaviors the reference's hot loop depends on (every pod event maps back
to a Notebook reconcile, SURVEY.md §3.1).

``coalesce_window`` adds per-key event coalescing: an immediate add
(delay 0) is held for the window so a burst of child events for one owner
— a slice's worth of pod status flaps, say — collapses into ONE reconcile
at window close instead of one per event. Explicit delays (backoff,
requeue_after) are never stretched by the window.

``quarantine_after`` adds poison-pill quarantine (dead-lettering): a key
whose reconcile fails that many times IN A ROW is parked in a quarantine
set instead of retrying at max backoff forever — a permanently-broken
object must not eat a worker slot and a log line every ``max_delay``
until the end of time. A quarantined key is released (failure budget
reset, re-queued immediately) when its object actually CHANGES — add()
carries an opaque change token (the manager derives it from metadata +
spec, NOT resourceVersion: the manager's own Degraded status write bumps
rv and must not free the pill it just parked), and a differing token is
the release signal — or via the manual escape hatch
(``release_quarantined``, surfaced as POST /debug/queue/requeue).
Same-token re-deliveries (relists, status-only writes) do not release:
the user-editable half of the object is unchanged, so the reconcile
would only fail the same way again.
"""

from __future__ import annotations

import asyncio
import heapq
import time
from typing import Hashable


class RateLimitedQueue:
    def __init__(
        self,
        base_delay: float = 0.005,
        max_delay: float = 60.0,
        coalesce_window: float = 0.0,
        quarantine_after: int = 0,
    ):
        self.base_delay = base_delay
        self.max_delay = max_delay
        self.coalesce_window = coalesce_window
        # Consecutive failures before a key is dead-lettered; 0 disables.
        self.quarantine_after = quarantine_after
        self.peak_depth = 0  # high-water mark of queued keys (bench telemetry)
        self._queue: list[tuple[float, int, Hashable]] = []  # (ready_at, seq, key)
        self._seq = 0
        self._queued: set[Hashable] = set()
        self._earliest: dict[Hashable, float] = {}  # earliest ready_at per key
        self._in_flight: set[Hashable] = set()
        self._dirty: set[Hashable] = set()  # re-added while in flight
        self._failures: dict[Hashable, int] = {}
        # Consecutive POISONOUS failures (the quarantine budget). Tracked
        # apart from _failures: a 409 Conflict backs off like any error
        # but is optimistic-concurrency noise, not poison — it must
        # neither advance this streak nor (being neutral evidence) reset
        # it, or a conflict storm plus one trailing 5xx would dead-letter
        # a healthy key.
        self._poison_streak: dict[Hashable, int] = {}
        # key → (change token at quarantine time | None, monotonic
        # quarantined-at). Keys here are parked: add() drops them unless
        # the delta's token proves the object changed.
        self._quarantined: dict[Hashable, tuple[str | None, float]] = {}
        # Queue-wait telemetry: after get(), how long the popped key sat
        # READY (past its ready_at) before a worker picked it up — pure
        # contention signal; intentional backoff/requeue_after delay is
        # excluded. The manager turns this into the ``queue_wait`` span.
        self._last_wait: dict[Hashable, float] = {}
        self._event = asyncio.Event()
        self._closed = False

    def __len__(self) -> int:
        return len(self._queued)

    def ready_count(self) -> int:
        """Keys ready to be processed now (excludes future-delayed entries —
        a controller that perpetually requeues itself would otherwise never
        look 'idle' to Manager.wait_idle)."""
        now = time.monotonic()
        return sum(1 for t in self._earliest.values() if t <= now)

    def add(self, key: Hashable, delay: float = 0.0, *,
            token: str | None = None) -> bool:
        """Queue a key. ``token`` is the object's opaque change token
        (metadata+spec signature), when the caller has one: it is ONLY
        consulted for quarantined keys, where a changed token is the
        release signal. Returns True iff this add released the key from
        quarantine."""
        if self._closed:
            return False
        released = False
        if key in self._quarantined:
            held_token, _since = self._quarantined[key]
            if token is None or token == held_token:
                return False  # unchanged object: stay parked
            self._quarantined.pop(key)
            self._failures.pop(key, None)  # fresh budget for the new spec
            self._poison_streak.pop(key, None)
            released = True
        if key in self._in_flight:
            self._dirty.add(key)
            return released
        if delay == 0.0 and self.coalesce_window:
            # Event-driven adds ride the coalescing window; because an add
            # may only move a key EARLIER (below), every event inside the
            # window lands on the first event's deadline — one reconcile
            # per burst. Explicit delays (backoff/requeue_after) pass
            # through untouched.
            delay = self.coalesce_window
        ready_at = time.monotonic() + delay
        if key in self._queued:
            # Already queued: a NEW add may only move the key *earlier*
            # (client-go semantics — an immediate change event must not wait
            # behind a long requeue_after/backoff entry). Push a second heap
            # entry; get() takes the earliest and drops stale duplicates.
            if ready_at >= self._earliest.get(key, float("inf")):
                return released
        else:
            self._queued.add(key)
            self.peak_depth = max(self.peak_depth, len(self._queued))
        self._earliest[key] = min(ready_at, self._earliest.get(key, float("inf")))
        self._seq += 1
        heapq.heappush(self._queue, (ready_at, self._seq, key))
        self._event.set()
        return released

    def note_failure(self, key: Hashable, *, poisonous: bool = True) -> None:
        """Record a failed reconcile. ``poisonous=False`` (409 Conflicts)
        still grows the backoff but never the quarantine streak."""
        self._failures[key] = self._failures.get(key, 0) + 1
        if poisonous:
            self._poison_streak[key] = self._poison_streak.get(key, 0) + 1

    def backoff_delay(self, key: Hashable) -> float:
        failures = self._failures.get(key, 0)
        if failures == 0:
            return 0.0
        return min(self.base_delay * (2 ** (failures - 1)), self.max_delay)

    def add_rate_limited(self, key: Hashable) -> None:
        """Re-queue after a failure with exponential backoff."""
        self.note_failure(key)
        self.add(key, self.backoff_delay(key))

    def forget(self, key: Hashable) -> None:
        """Drop the key's failure state — called on success AND on object
        deletion (informer DELETED), so the failure map and quarantine set
        cannot leak one entry per ever-failed key forever."""
        self._failures.pop(key, None)
        self._poison_streak.pop(key, None)
        self._quarantined.pop(key, None)
        self._last_wait.pop(key, None)

    # ---- poison-pill quarantine ------------------------------------------------

    def poison_streak(self, key: Hashable) -> int:
        """Consecutive poisonous failures recorded for the key — the
        number the quarantine budget compares against (callers must not
        reach into the internal maps)."""
        return self._poison_streak.get(key, 0)

    def should_quarantine(self, key: Hashable) -> bool:
        """Has the key exhausted its consecutive-failure budget?"""
        return (self.quarantine_after > 0
                and self._poison_streak.get(key, 0) >= self.quarantine_after)

    def quarantine(self, key: Hashable, token: str | None = None) -> None:
        """Dead-letter the key: it leaves the queue entirely (any pending
        heap entries go stale) and no add() re-queues it until its object
        changes (token differs) or release_quarantined() is called.
        ``token`` is the object's change token as of quarantine time."""
        if key in self._quarantined:
            return
        self._quarantined[key] = (token, time.monotonic())
        self._queued.discard(key)
        self._earliest.pop(key, None)
        self._dirty.discard(key)

    def release_quarantined(self, key: Hashable) -> bool:
        """Manual escape hatch (POST /debug/queue/requeue): un-park the
        key with a fresh failure budget and queue it immediately."""
        if key not in self._quarantined:
            return False
        self._quarantined.pop(key)
        self._failures.pop(key, None)
        self._poison_streak.pop(key, None)
        self.add(key)
        return True

    def quarantined_keys(self) -> list[Hashable]:
        return list(self._quarantined)

    def purge(self, predicate) -> int:
        """Drop every queued/backoff/quarantine record whose key matches
        ``predicate`` — the shard-rebalance eviction: when this replica
        loses a shard, that shard's keys must leave the queue NOW (the
        new owner re-discovers them via its refill; a worker here
        dequeuing one later would race the new owner's reconcile).
        In-flight keys are not touched — the worker's dequeue fence
        drops them on done(). Returns the number of queued keys purged;
        stale heap entries are left to get()'s staleness check."""
        purged = 0
        for key in [k for k in self._queued if predicate(k)]:
            self._queued.discard(key)
            self._earliest.pop(key, None)
            purged += 1
        for key in [k for k in list(self._failures) if predicate(k)]:
            self._failures.pop(key, None)
            self._poison_streak.pop(key, None)
        for key in [k for k in list(self._quarantined) if predicate(k)]:
            self._quarantined.pop(key, None)
        for key in [k for k in self._dirty if predicate(k)]:
            self._dirty.discard(key)
        return purged

    def is_quarantined(self, key: Hashable) -> bool:
        return key in self._quarantined

    async def get(self) -> Hashable | None:
        """Next ready key, or None when the queue is shut down."""
        while True:
            now = time.monotonic()
            if self._closed and not (
                self._queue and self._queue[0][0] <= now
            ):
                # Shut down: drain entries that are ready NOW, but never
                # wait out future-delayed ones (a 300 s capacity-retry
                # entry would otherwise pin a worker — and its cancelled
                # shutdown — for the full delay; shutdown() already woke
                # us via the event precisely so this check runs).
                return None
            if self._queue and self._queue[0][0] <= now:
                ready_at, _, key = heapq.heappop(self._queue)
                # Drop stale entries: from a previous queued lifetime of the
                # key (not queued now, or queued again with a DIFFERENT
                # ready_at — honoring backoff set after the stale push).
                if key not in self._queued or ready_at != self._earliest.get(key):
                    continue
                # kftpu: ignore[await-race] no suspension between the fresh heap pop, the staleness re-check and this discard — racing workers pop distinct entries
                self._queued.discard(key)
                # kftpu: ignore[await-race] same atomic pop-to-mutate window as the discard above
                self._earliest.pop(key, None)
                # Time past eligibility only — ready_at already folds in
                # any intentional delay (coalesce/backoff/requeue_after).
                self._last_wait[key] = max(0.0, now - ready_at)
                self._in_flight.add(key)
                return key
            timeout = (self._queue[0][0] - now) if self._queue else None
            # kftpu: ignore[await-race] no suspension between the queue-state read and this clear — add()'s set() can only interleave inside the awaited wait
            self._event.clear()
            try:
                await asyncio.wait_for(self._event.wait(), timeout)
            except asyncio.TimeoutError:
                pass

    def done(self, key: Hashable) -> bool:
        """Finish processing a key. Returns True iff the key had gone
        dirty in flight (and was re-queued) — new information arrived
        DURING the reconcile, which the manager's quarantine gate must
        honor: dead-lettering on the stale attempt would capture the
        already-changed object's token and park the user's fix forever."""
        self._in_flight.discard(key)
        if key in self._dirty:
            self._dirty.discard(key)
            if key in self._quarantined:
                return False  # parked: the dirty re-add must not resurrect it
            # A dirty key that has recorded failures re-queues with its
            # backoff, not immediately — otherwise a failing reconciler that
            # touches its own children retries in a hot loop.
            self.add(key, self.backoff_delay(key))
            return True
        return False

    def take_wait(self, key: Hashable) -> float:
        """Queue wait of the most recent get() of ``key`` — time the key
        sat ready past its eligibility, consumed once (the manager
        attaches it to the reconcile trace as the ``queue_wait`` span)."""
        return self._last_wait.pop(key, 0.0)

    def debug_info(self) -> dict:
        """JSON-shaped snapshot for the /debug/queue endpoint: depth,
        backoff keys, oldest wait — the "why is nothing happening"
        questions answered without a debugger."""
        now = time.monotonic()
        return {
            "depth": len(self._queued),
            "ready": self.ready_count(),
            "in_flight": sorted(str(k) for k in self._in_flight),
            "dirty": len(self._dirty),
            "peak_depth": self.peak_depth,
            "coalesce_window_sec": self.coalesce_window,
            "backoff_keys": {
                str(k): {
                    "failures": n,
                    "next_delay_sec": round(self.backoff_delay(k), 4),
                }
                for k, n in sorted(self._failures.items(), key=lambda kv: str(kv[0]))
                if k not in self._quarantined
            },
            # Dead-lettered keys: reconcile suspended until the object
            # changes or an operator hits /debug/queue/requeue.
            "quarantined": {
                str(k): {
                    "failures": self._failures.get(k, 0),
                    "since_sec": round(now - since, 3),
                }
                for k, (_token, since) in sorted(
                    self._quarantined.items(), key=lambda kv: str(kv[0]))
            },
            # Longest a currently-READY key has been waiting for a worker
            # (keys still inside an intentional delay don't count — their
            # "wait" is a timer, not contention).
            "oldest_wait_sec": round(
                max((now - t for t in self._earliest.values() if t <= now),
                    default=0.0), 4
            ),
        }

    def shutdown(self) -> None:
        self._closed = True
        self._event.set()
