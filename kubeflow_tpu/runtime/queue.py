"""Rate-limited workqueue (client-go semantics, asyncio-native).

Deduplicates keys while queued, tracks in-flight keys so a key re-added during
processing is re-queued afterwards, and applies per-item exponential backoff —
the behaviors the reference's hot loop depends on (every pod event maps back
to a Notebook reconcile, SURVEY.md §3.1).

``coalesce_window`` adds per-key event coalescing: an immediate add
(delay 0) is held for the window so a burst of child events for one owner
— a slice's worth of pod status flaps, say — collapses into ONE reconcile
at window close instead of one per event. Explicit delays (backoff,
requeue_after) are never stretched by the window.
"""

from __future__ import annotations

import asyncio
import heapq
import time
from typing import Hashable


class RateLimitedQueue:
    def __init__(
        self,
        base_delay: float = 0.005,
        max_delay: float = 60.0,
        coalesce_window: float = 0.0,
    ):
        self.base_delay = base_delay
        self.max_delay = max_delay
        self.coalesce_window = coalesce_window
        self.peak_depth = 0  # high-water mark of queued keys (bench telemetry)
        self._queue: list[tuple[float, int, Hashable]] = []  # (ready_at, seq, key)
        self._seq = 0
        self._queued: set[Hashable] = set()
        self._earliest: dict[Hashable, float] = {}  # earliest ready_at per key
        self._in_flight: set[Hashable] = set()
        self._dirty: set[Hashable] = set()  # re-added while in flight
        self._failures: dict[Hashable, int] = {}
        # Queue-wait telemetry: after get(), how long the popped key sat
        # READY (past its ready_at) before a worker picked it up — pure
        # contention signal; intentional backoff/requeue_after delay is
        # excluded. The manager turns this into the ``queue_wait`` span.
        self._last_wait: dict[Hashable, float] = {}
        self._event = asyncio.Event()
        self._closed = False

    def __len__(self) -> int:
        return len(self._queued)

    def ready_count(self) -> int:
        """Keys ready to be processed now (excludes future-delayed entries —
        a controller that perpetually requeues itself would otherwise never
        look 'idle' to Manager.wait_idle)."""
        now = time.monotonic()
        return sum(1 for t in self._earliest.values() if t <= now)

    def add(self, key: Hashable, delay: float = 0.0) -> None:
        if self._closed:
            return
        if key in self._in_flight:
            self._dirty.add(key)
            return
        if delay == 0.0 and self.coalesce_window:
            # Event-driven adds ride the coalescing window; because an add
            # may only move a key EARLIER (below), every event inside the
            # window lands on the first event's deadline — one reconcile
            # per burst. Explicit delays (backoff/requeue_after) pass
            # through untouched.
            delay = self.coalesce_window
        ready_at = time.monotonic() + delay
        if key in self._queued:
            # Already queued: a NEW add may only move the key *earlier*
            # (client-go semantics — an immediate change event must not wait
            # behind a long requeue_after/backoff entry). Push a second heap
            # entry; get() takes the earliest and drops stale duplicates.
            if ready_at >= self._earliest.get(key, float("inf")):
                return
        else:
            self._queued.add(key)
            self.peak_depth = max(self.peak_depth, len(self._queued))
        self._earliest[key] = min(ready_at, self._earliest.get(key, float("inf")))
        self._seq += 1
        heapq.heappush(self._queue, (ready_at, self._seq, key))
        self._event.set()

    def note_failure(self, key: Hashable) -> None:
        self._failures[key] = self._failures.get(key, 0) + 1

    def backoff_delay(self, key: Hashable) -> float:
        failures = self._failures.get(key, 0)
        if failures == 0:
            return 0.0
        return min(self.base_delay * (2 ** (failures - 1)), self.max_delay)

    def add_rate_limited(self, key: Hashable) -> None:
        """Re-queue after a failure with exponential backoff."""
        self.note_failure(key)
        self.add(key, self.backoff_delay(key))

    def forget(self, key: Hashable) -> None:
        self._failures.pop(key, None)

    async def get(self) -> Hashable | None:
        """Next ready key, or None when the queue is shut down."""
        while True:
            now = time.monotonic()
            if self._closed and not (
                self._queue and self._queue[0][0] <= now
            ):
                # Shut down: drain entries that are ready NOW, but never
                # wait out future-delayed ones (a 300 s capacity-retry
                # entry would otherwise pin a worker — and its cancelled
                # shutdown — for the full delay; shutdown() already woke
                # us via the event precisely so this check runs).
                return None
            if self._queue and self._queue[0][0] <= now:
                ready_at, _, key = heapq.heappop(self._queue)
                # Drop stale entries: from a previous queued lifetime of the
                # key (not queued now, or queued again with a DIFFERENT
                # ready_at — honoring backoff set after the stale push).
                if key not in self._queued or ready_at != self._earliest.get(key):
                    continue
                self._queued.discard(key)
                self._earliest.pop(key, None)
                # Time past eligibility only — ready_at already folds in
                # any intentional delay (coalesce/backoff/requeue_after).
                self._last_wait[key] = max(0.0, now - ready_at)
                self._in_flight.add(key)
                return key
            timeout = (self._queue[0][0] - now) if self._queue else None
            self._event.clear()
            try:
                await asyncio.wait_for(self._event.wait(), timeout)
            except asyncio.TimeoutError:
                pass

    def done(self, key: Hashable) -> None:
        self._in_flight.discard(key)
        if key in self._dirty:
            self._dirty.discard(key)
            # A dirty key that has recorded failures re-queues with its
            # backoff, not immediately — otherwise a failing reconciler that
            # touches its own children retries in a hot loop.
            self.add(key, self.backoff_delay(key))

    def take_wait(self, key: Hashable) -> float:
        """Queue wait of the most recent get() of ``key`` — time the key
        sat ready past its eligibility, consumed once (the manager
        attaches it to the reconcile trace as the ``queue_wait`` span)."""
        return self._last_wait.pop(key, 0.0)

    def debug_info(self) -> dict:
        """JSON-shaped snapshot for the /debug/queue endpoint: depth,
        backoff keys, oldest wait — the "why is nothing happening"
        questions answered without a debugger."""
        now = time.monotonic()
        return {
            "depth": len(self._queued),
            "ready": self.ready_count(),
            "in_flight": sorted(str(k) for k in self._in_flight),
            "dirty": len(self._dirty),
            "peak_depth": self.peak_depth,
            "coalesce_window_sec": self.coalesce_window,
            "backoff_keys": {
                str(k): {
                    "failures": n,
                    "next_delay_sec": round(self.backoff_delay(k), 4),
                }
                for k, n in sorted(self._failures.items(), key=lambda kv: str(kv[0]))
            },
            # Longest a currently-READY key has been waiting for a worker
            # (keys still inside an intentional delay don't count — their
            # "wait" is a timer, not contention).
            "oldest_wait_sec": round(
                max((now - t for t in self._earliest.values() if t <= now),
                    default=0.0), 4
            ),
        }

    def shutdown(self) -> None:
        self._closed = True
        self._event.set()
