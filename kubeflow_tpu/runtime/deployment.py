"""Deployment-identity facts shared across layers.

The one value every layer needs to agree on regardless of which process it
runs in (controller manager, webhook server, web apps): the namespace this
stack is installed in, from the downward-API ``POD_NAMESPACE``. Lives in
runtime/ so web/ and webhooks/ never import the cmd wiring layer.
"""

from __future__ import annotations

import os


def controller_namespace() -> str:
    return os.environ.get("POD_NAMESPACE", "kubeflow-tpu")
