"""Durable per-object lifecycle timelines.

The flight recorder (runtime/tracing.py) answers "what did the last
reconcile DO" — but it is an in-memory ring that dies with every manager
restart, and the chaos soak restarts managers on purpose. This module is
the durable complement: an append-only journal of LIFECYCLE transitions
(Queued → Admitted → Ready → Draining → Parked → Restoring → Ready,
Preempted, Reclaimed, …) per object, each entry carrying a timestamp,
reason, exemplar trace id, and the gang's chip shape.

Durability: the journal is persisted as ONE compact capped annotation on
the object itself (``notebooks.kubeflow.org/timeline``) — the same
substrate that already makes the drain protocol restart-safe. A rebuilt
manager decodes the annotation and appends from the durable sequence
number, so the chaos soak's kill/rebuild cycles replay into an unbroken
timeline: sequence numbers stay consecutive, no transition is recorded
twice (:func:`continuity_problems` is the shared invariant checker the
soak and tier-1 both run).

Writers: the notebook reconciler is the SINGLE writer per key (its
workqueue already serializes reconciles per key) — every layer's state
lands in the one status derivation ``_update_status`` performs, so one
``record()`` call per reconcile captures scheduler, migration, and
readiness transitions alike. Readers: ``/debug/timeline/<ns>/<name>``,
the scheduler-explain endpoint, and the SLO engine (time-to-ready is
measured from the timeline's startup-episode boundary).
"""

from __future__ import annotations

import json
import logging
import os
from collections import OrderedDict

from kubeflow_tpu.api import keys
from kubeflow_tpu.runtime.errors import ApiError
from kubeflow_tpu.runtime.objects import fmt_iso

log = logging.getLogger(__name__)

TIMELINE_ANNOTATION = keys.NOTEBOOK_TIMELINE

# Knobs (docs/operations.md "SLOs & burn-rate alerting"):
TIMELINE_ENABLED_ENV = "KFTPU_TIMELINE"
TIMELINE_MAX_ENTRIES_ENV = "KFTPU_TIMELINE_MAX_ENTRIES"
DEFAULT_MAX_ENTRIES = 24

# Canonical lifecycle states. ``derive_lifecycle`` folds the scheduler
# verdict, the migration protocol state, and pod readiness into one
# chain, so a timeline reads as the object's life story.
CREATING = "Creating"          # no scheduler verdict yet, workers coming up
QUEUED = "Queued"
ADMITTED = "Admitted"          # chips booked, workers not all Ready
READY = "Ready"
DRAINING = "Draining"          # checkpoint requested / in progress
PARKED = "Parked"              # stopped with a committed checkpoint
RESTORING = "Restoring"
PREEMPTED = "Preempted"
RECLAIMED = "Reclaimed"        # re-queued after spot reclaim / defrag
STOPPED = "Stopped"
# Warm pod pools (ISSUE 14): Claimed = this startup adopted a pre-warmed
# pod (the episode's Claimed→Ready gap is the warm path's whole cost);
# Warming = a matching pool existed but was EMPTY, so the cold path ran
# while the pool replenished — the miss that cost this episode the warm
# start. An episode containing either transition attributes its
# time-to-ready to the warm (or missed-warm) path from the journal alone.
CLAIMED = "Claimed"
WARMING = "Warming"

# States that END a startup episode: time-to-ready measures from the
# first entry AFTER the latest of these to the Ready transition.
_EPISODE_BOUNDARIES = frozenset({READY, STOPPED, PARKED, PREEMPTED})

_enabled = True  # process-wide A/B switch for the overhead bench


def set_enabled(on: bool) -> None:
    global _enabled
    _enabled = bool(on)


def is_enabled() -> bool:
    return _enabled


def timeline_enabled(environ=os.environ) -> bool:
    """``KFTPU_TIMELINE`` master switch (default on)."""
    return environ.get(TIMELINE_ENABLED_ENV, "on").strip().lower() not in (
        "off", "false", "0", "no", "disabled",
    )


def max_entries(environ=os.environ) -> int:
    raw = environ.get(TIMELINE_MAX_ENTRIES_ENV)
    try:
        value = int(raw) if raw is not None else DEFAULT_MAX_ENTRIES
    except ValueError:
        return DEFAULT_MAX_ENTRIES
    return value if value >= 2 else DEFAULT_MAX_ENTRIES


# ---- pure core: derive / encode / append / check -------------------------------


def derive_lifecycle(*, sched_state: str | None, mig_state: str | None,
                     stopped: bool, ready: int, want_hosts: int,
                     reclaimed: str = "", warm: str = "") -> str:
    """The object's lifecycle state as a pure function of what
    ``_update_status`` already derived. Priority order mirrors the JWA
    status machine: park/preempt verdicts over queueing over readiness.
    ``warm`` is the warm-pool verdict ("claimed" = a pre-warmed pod was
    adopted this episode, "warming" = a matching pool was empty and the
    cold path ran) — it refines the pre-Ready states only; Ready and
    every park/queue verdict outrank it."""
    if stopped:
        if mig_state == "Parked":
            return PARKED
        if sched_state == "Preempted":
            return PREEMPTED
        return STOPPED
    if sched_state == "Draining" or mig_state in (
            "DrainRequested", "Checkpointing", "Checkpointed"):
        return DRAINING
    if sched_state == "Queued":
        return RECLAIMED if reclaimed else QUEUED
    if sched_state == "Preempted":
        return PREEMPTED
    if ready and want_hosts and ready >= want_hosts:
        return READY
    if warm == "claimed":
        return CLAIMED
    if mig_state == "Restoring":
        return RESTORING
    if warm == "warming":
        return WARMING
    if sched_state == "Admitted":
        return ADMITTED
    return CREATING


def decode(annotations: dict | None) -> list[dict]:
    """Annotation → entry dicts. Tolerant: a corrupt value decodes to an
    empty journal (the next transition rewrites it whole) rather than
    wedging the reconcile."""
    raw = (annotations or {}).get(TIMELINE_ANNOTATION)
    if not raw:
        return []
    try:
        rows = json.loads(raw)
    except (ValueError, TypeError):
        return []
    out: list[dict] = []
    if not isinstance(rows, list):
        return out
    for row in rows:
        if not isinstance(row, list) or len(row) < 3:
            continue
        try:
            out.append({
                "seq": int(row[0]),
                "at": float(row[1]),
                "state": str(row[2]),
                "reason": str(row[3]) if len(row) > 3 else "",
                "trace_id": str(row[4]) if len(row) > 4 and row[4] else "",
                "shape": str(row[5]) if len(row) > 5 else "",
            })
        except (ValueError, TypeError):
            continue
    return out


def encode(entries: list[dict]) -> str:
    """Entry dicts → the compact annotation value (JSON list-of-lists,
    short on purpose: annotations ride every GET of the object)."""
    return json.dumps(
        [[e["seq"], round(e["at"], 3), e["state"], e.get("reason", ""),
          e.get("trace_id", ""), e.get("shape", "")]
         for e in entries],
        separators=(",", ":"))


def append(entries: list[dict], state: str, *, at: float, reason: str = "",
           trace_id: str | None = None, shape: str = "",
           cap: int = DEFAULT_MAX_ENTRIES) -> bool:
    """Append one transition IN PLACE if it is a real change (the last
    recorded state differs); returns whether anything was appended. Seq
    continues from the durable tail, so entries evicted by the cap never
    create a gap inside the retained window."""
    if entries and entries[-1]["state"] == state:
        return False
    seq = entries[-1]["seq"] + 1 if entries else 1
    ts = max(at, entries[-1]["at"]) if entries else at
    entries.append({
        "seq": seq, "at": ts, "state": state, "reason": reason or "",
        "trace_id": trace_id or "", "shape": shape or "",
    })
    while len(entries) > cap:
        entries.pop(0)
    return True


def continuity_problems(entries: list[dict]) -> list[str]:
    """The unbroken-timeline invariant (chaos soak + tier-1): within the
    retained window, sequence numbers are consecutive (no gap, no
    duplicate), no two adjacent entries share a state (no duplicate
    transition), and timestamps never go backwards."""
    problems: list[str] = []
    for i in range(1, len(entries)):
        prev, cur = entries[i - 1], entries[i]
        if cur["seq"] != prev["seq"] + 1:
            problems.append(
                f"seq gap/duplicate: {prev['seq']} -> {cur['seq']} "
                f"({prev['state']} -> {cur['state']})")
        if cur["state"] == prev["state"]:
            problems.append(
                f"duplicate transition to {cur['state']!r} at seq "
                f"{cur['seq']}")
        if cur["at"] < prev["at"]:
            problems.append(
                f"time went backwards at seq {cur['seq']} "
                f"({prev['at']} -> {cur['at']})")
    return problems


def episode_start(entries: list[dict]) -> dict | None:
    """First entry of the CURRENT startup episode: the earliest entry
    after the latest boundary state (Ready/Stopped/Parked/Preempted).
    None when the journal is empty or the latest entry IS a boundary."""
    start = None
    for e in reversed(entries):
        if e["state"] in _EPISODE_BOUNDARIES:
            break
        start = e
    return start


def time_to_ready(entries: list[dict]) -> float | None:
    """Seconds from the current episode's start to its Ready tail —
    meaningful right after a Ready transition was appended."""
    if not entries or entries[-1]["state"] != READY:
        return None
    start = episode_start(entries[:-1])
    if start is None:
        return None
    return max(0.0, entries[-1]["at"] - start["at"])


def render(entries: list[dict]) -> list[dict]:
    """Entries shaped for /debug responses (ISO timestamps)."""
    return [{**e, "time": fmt_iso(e["at"])} for e in entries]


# ---- runtime recorder ----------------------------------------------------------


class TimelineRecorder:
    """Write-through journal store: in-memory cache (bounded, LRU) over
    the durable annotation. ``record()`` is called once per reconcile by
    the single writer; a no-transition call is free. A failed annotation
    patch keeps the journal dirty and re-flushes on the next call (every
    write carries the FULL capped list, so durability self-heals)."""

    def __init__(self, kube, *, kind: str = "Notebook",
                 environ=os.environ, max_keys: int = 4096):
        self.kube = kube
        self.kind = kind
        self.enabled = timeline_enabled(environ)
        self.cap = max_entries(environ)
        self.max_keys = max_keys
        self._entries: "OrderedDict[tuple, list]" = OrderedDict()
        self._dirty: set = set()

    def _load(self, key: tuple, annotations: dict | None) -> list[dict]:
        cached = self._entries.get(key)
        durable = decode(annotations) if annotations else []
        if cached is None:
            entries = durable
        elif durable and (not cached
                          or durable[-1]["seq"] > cached[-1]["seq"]):
            # Another writer (or a previous incarnation) got further
            # than our cache: the durable record wins.
            entries = durable
        else:
            entries = cached
        self._entries[key] = entries
        self._entries.move_to_end(key)
        while len(self._entries) > self.max_keys:
            # Evict clean journals first: a DIRTY one holds transitions
            # the apiserver hasn't accepted yet, and dropping it would
            # silently lose them despite the re-flush self-heal. Only
            # when EVERY cached journal is dirty (total write outage)
            # does the oldest go, loudly — memory stays bounded.
            evicted = next((k for k in self._entries
                            if k not in self._dirty), None)
            if evicted is None:
                evicted, _ = self._entries.popitem(last=False)
                self._dirty.discard(evicted)
                log.warning(
                    "lifecycle timeline for %s evicted with unflushed "
                    "transitions (are apiserver writes failing?)",
                    evicted)
            else:
                self._entries.pop(evicted)
        return entries

    async def record(self, key: tuple, state: str, *, at: float,
                     reason: str = "", trace_id: str | None = None,
                     shape: str = "",
                     annotations: dict | None = None) -> list[dict] | None:
        """Record the object's current lifecycle state. Returns the
        entry list when a NEW transition was appended (the caller feeds
        time-to-ready into the SLO engine off that), else None.
        ``annotations`` is the live object's annotations this reconcile
        already holds — no extra GET."""
        if not (self.enabled and _enabled):
            return None
        key = tuple(key)
        entries = self._load(key, annotations)
        changed = append(entries, state, at=at, reason=reason,
                         trace_id=trace_id, shape=shape, cap=self.cap)
        if changed or key in self._dirty:
            await self._flush(key, entries)
        return entries if changed else None

    async def _flush(self, key: tuple, entries: list[dict]) -> None:
        try:
            await self.kube.patch(
                self.kind, key[1],
                {"metadata": {"annotations": {
                    TIMELINE_ANNOTATION: encode(entries)}}},
                key[0])
            self._dirty.discard(key)
        except ApiError:
            # Best-effort by design: the journal stays cached and the
            # next record() re-writes the full list. Losing the tail to
            # a process death is safe — seq continues from the durable
            # record, so the retained window stays unbroken.
            self._dirty.add(key)

    def entries(self, key: tuple,
                annotations: dict | None = None) -> list[dict]:
        """Read the journal (cache-first, durable fallback) WITHOUT
        recording anything — /debug handlers."""
        key = tuple(key)
        cached = self._entries.get(key)
        durable = decode(annotations) if annotations else []
        if cached is None:
            return durable
        if durable and (not cached or durable[-1]["seq"] > cached[-1]["seq"]):
            return durable
        return cached

    def forget(self, key: tuple) -> None:
        key = tuple(key)
        self._entries.pop(key, None)
        self._dirty.discard(key)
