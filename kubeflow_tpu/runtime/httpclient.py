"""Real-apiserver client over aiohttp.

Same ``KubeApi`` surface as ``FakeKube``, speaking the actual Kubernetes REST
conventions: GVR paths from the scheme, merge-patch content types, watch via
``?watch=true`` chunked JSON lines, in-cluster auth from the mounted
ServiceAccount (token + CA), or kubeconfig-less host/token injection for dev.
"""

from __future__ import annotations

import asyncio
import json
import os
import ssl
from typing import AsyncIterator

import aiohttp

from kubeflow_tpu.runtime import tracing
from kubeflow_tpu.runtime.errors import ServerTimeout, error_for_code
from kubeflow_tpu.runtime.flowcontrol import FlowControl, _env_float
from kubeflow_tpu.runtime.objects import name_of, namespace_of, selector_to_string
from kubeflow_tpu.runtime.scheme import DEFAULT_SCHEME, Scheme

SA_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"

# Client deadlines + connection pool, env-tunable (docs/operations.md).
# A session with NO total timeout lets one hung apiserver socket pin a
# reconcile worker forever; the watch path opts back out explicitly
# (streams are expected to idle).
TIMEOUT_ENV = "KUBE_CLIENT_TIMEOUT"
LIST_TIMEOUT_ENV = "KUBE_CLIENT_LIST_TIMEOUT"
CONNECT_TIMEOUT_ENV = "KUBE_CLIENT_CONNECT_TIMEOUT"
MAX_CONNS_ENV = "KUBE_CLIENT_MAX_CONNS"
RETRY_429_ENV = "KUBE_CLIENT_RETRY_429"
DEFAULT_TIMEOUT_SEC = 30.0
# LISTs are the one legitimately-slow request class (an informer relist
# of a big kind can stream hundreds of MB) — a 30 s blanket deadline
# would fail every attempt and the cache could never sync. Still
# bounded: a truly hung apiserver must not pin the relist loop forever.
DEFAULT_LIST_TIMEOUT_SEC = 300.0
DEFAULT_CONNECT_TIMEOUT_SEC = 5.0
# Must exceed the flow-control lanes' combined concurrency (16 reads +
# 8 writes + 1 event by default) PLUS the long-lived watch streams the
# informers hold on the same connector (~one per watched kind) — an
# undersized pool would queue watch (re)connects behind a reconcile
# burst exactly when the cluster is busiest.
DEFAULT_MAX_CONNS = 64
DEFAULT_RETRY_429 = 2
RETRY_AFTER_CAP_SEC = 30.0


def _parse_retry_after(value: str | None) -> float:
    """Seconds form only (the apiserver sends integral seconds); an
    unparseable or HTTP-date value falls back to 1 s."""
    try:
        return max(0.0, float(value))
    except (TypeError, ValueError):
        return 1.0


class HttpKube:
    def __init__(
        self,
        base_url: str | None = None,
        token: str | None = None,
        ca_file: str | None = None,
        scheme: Scheme | None = None,
        verify_tls: bool = True,
        flow: FlowControl | None = None,
        timeout: float | None = None,
        connect_timeout: float | None = None,
    ):
        self.scheme = scheme or DEFAULT_SCHEME
        # Client-side priority & fairness: every request passes a lane
        # gate (reads / writes / low-priority events) so one traffic
        # class can't monopolize the connection pool.
        self.flow = flow or FlowControl()
        self._timeout_total = (
            timeout if timeout is not None
            else _env_float(TIMEOUT_ENV, DEFAULT_TIMEOUT_SEC))
        self._timeout_list = max(
            _env_float(LIST_TIMEOUT_ENV, DEFAULT_LIST_TIMEOUT_SEC),
            self._timeout_total)
        self._timeout_connect = (
            connect_timeout if connect_timeout is not None
            else _env_float(CONNECT_TIMEOUT_ENV, DEFAULT_CONNECT_TIMEOUT_SEC))
        self._max_conns = int(_env_float(MAX_CONNS_ENV, DEFAULT_MAX_CONNS))
        self._max_429_retries = int(_env_float(RETRY_429_ENV, DEFAULT_RETRY_429))
        host = os.environ.get("KUBERNETES_SERVICE_HOST")
        port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
        self.base_url = base_url or (f"https://{host}:{port}" if host else "http://127.0.0.1:8001")
        if token is None and os.path.exists(f"{SA_DIR}/token"):
            with open(f"{SA_DIR}/token") as f:
                token = f.read().strip()
        self.token = token
        if ca_file is None and os.path.exists(f"{SA_DIR}/ca.crt"):
            ca_file = f"{SA_DIR}/ca.crt"
        self._ssl: ssl.SSLContext | bool | None = None
        if self.base_url.startswith("https"):
            if ca_file:
                self._ssl = ssl.create_default_context(cafile=ca_file)
            elif not verify_tls:
                self._ssl = False
        self._session: aiohttp.ClientSession | None = None

    async def _sess(self) -> aiohttp.ClientSession:
        if self._session is None or self._session.closed:
            headers = {}
            if self.token:
                headers["Authorization"] = f"Bearer {self.token}"
            self._session = aiohttp.ClientSession(
                headers=headers,
                # Default deadline for every request (watch overrides it
                # per-request): a hung apiserver surfaces as a retriable
                # ServerTimeout instead of pinning the worker forever.
                timeout=aiohttp.ClientTimeout(
                    total=self._timeout_total, connect=self._timeout_connect),
                # One shared pool: connection reuse across requests, and
                # a hard cap so a reconcile burst can't exhaust sockets.
                connector=aiohttp.TCPConnector(limit=self._max_conns),
            )
        return self._session

    async def close(self) -> None:
        if self._session and not self._session.closed:
            await self._session.close()

    def _url(self, kind: str, namespace: str | None, name: str | None = None) -> str:
        gvk = self.scheme.by_kind(kind)
        url = self.base_url + gvk.rest_base(namespace)
        if name:
            url += f"/{name}"
        return url

    async def _request(
        self, method: str, url: str, *, verb: str | None = None,
        kind: str | None = None, **kw,
    ) -> dict:
        sess = await self._sess()
        verb = verb or method.lower()
        # Correlate with the active reconcile trace: the trace id travels
        # as X-Request-Id, so the apiserver audit log and this process's
        # flight recorder describe the same request by the same id. The
        # verb/kind tag lands on the trace's root span (api_calls).
        tracing.note_api_call(verb, kind)
        trace_id = tracing.current_trace_id()
        if trace_id:
            headers = dict(kw.pop("headers", None) or {})
            headers.setdefault("X-Request-Id", trace_id)
            kw["headers"] = headers
        for attempt in range(self._max_429_retries + 1):
            try:
                # The lane slot (and the pooled connection) is held only
                # for the request itself — NOT across the Retry-After
                # sleep below, or a 429 storm would park the whole write
                # lane for the server's pacing interval.
                async with self.flow.slot(verb, kind):
                    async with sess.request(
                        method, url, ssl=self._ssl, **kw
                    ) as resp:
                        body = await resp.text()
                        status, headers = resp.status, resp.headers
            except asyncio.TimeoutError:
                raise ServerTimeout(
                    f"{method} {url}: no response within the client "
                    "deadline"
                ) from None
            if status == 429 and attempt < self._max_429_retries:
                # Server-side APF pushed back; honor its pacing (bounded)
                # instead of re-slamming it.
                await asyncio.sleep(min(
                    _parse_retry_after(headers.get("Retry-After")),
                    RETRY_AFTER_CAP_SEC))
                continue
            if status >= 400:
                # The apiserver returns a Status object; its ``reason``
                # is the authoritative error discriminator (409
                # AlreadyExists vs Conflict), not the free-text message.
                reason = None
                try:
                    reason = json.loads(body).get("reason")
                except (ValueError, AttributeError):
                    pass
                raise error_for_code(
                    status, f"{method} {url}: {body[:500]}", reason=reason,
                )
            return json.loads(body) if body else {}
        raise AssertionError("unreachable")  # loop always returns or raises

    # ---- KubeApi surface -----------------------------------------------------

    async def get(self, kind: str, name: str, namespace: str | None = None) -> dict:
        return await self._request(
            "GET", self._url(kind, namespace, name), verb="get", kind=kind
        )

    async def list(
        self,
        kind: str,
        namespace: str | None = None,
        label_selector: str | dict | None = None,
        field_selector=None,
    ) -> list[dict]:
        items, _ = await self.list_with_rv(kind, namespace, label_selector, field_selector)
        return items

    async def list_with_rv(
        self,
        kind: str,
        namespace: str | None = None,
        label_selector: str | dict | None = None,
        field_selector=None,
    ) -> tuple[list[dict], str | None]:
        """List plus the collection resourceVersion, for list→watch continuity."""
        params = {}
        sel = selector_to_string(label_selector)
        if sel:
            params["labelSelector"] = sel
        data = await self._request(
            "GET", self._url(kind, namespace), verb="list", kind=kind,
            params=params,
            # LIST gets its own (longer, still bounded) deadline — see
            # DEFAULT_LIST_TIMEOUT_SEC.
            timeout=aiohttp.ClientTimeout(
                total=self._timeout_list, connect=self._timeout_connect),
        )
        items = data.get("items", [])
        gvk = self.scheme.by_kind(kind)
        for item in items:
            item.setdefault("kind", kind)
            item.setdefault("apiVersion", gvk.api_version)
        if field_selector:
            items = [o for o in items if field_selector(o)]
        return items, (data.get("metadata") or {}).get("resourceVersion")

    async def create(self, kind: str, obj: dict, namespace: str | None = None) -> dict:
        ns = namespace or namespace_of(obj)
        return await self._request(
            "POST", self._url(kind, ns), verb="create", kind=kind, json=obj
        )

    async def update(self, kind: str, obj: dict) -> dict:
        return await self._request(
            "PUT", self._url(kind, namespace_of(obj), name_of(obj)),
            verb="update", kind=kind, json=obj,
        )

    async def update_status(self, kind: str, obj: dict) -> dict:
        url = self._url(kind, namespace_of(obj), name_of(obj)) + "/status"
        return await self._request(
            "PUT", url, verb="update_status", kind=kind, json=obj
        )

    async def patch(
        self,
        kind: str,
        name: str,
        patch: dict,
        namespace: str | None = None,
        subresource: str | None = None,
    ) -> dict:
        url = self._url(kind, namespace, name)
        if subresource:
            url += f"/{subresource}"
        return await self._request(
            "PATCH",
            url,
            verb="patch",
            kind=kind,
            data=json.dumps(patch),
            headers={"Content-Type": "application/merge-patch+json"},
        )

    async def delete(self, kind: str, name: str, namespace: str | None = None) -> None:
        await self._request(
            "DELETE",
            self._url(kind, namespace, name),
            verb="delete",
            kind=kind,
            json={"propagationPolicy": "Background"},
        )

    async def watch(
        self,
        kind: str,
        namespace: str | None = None,
        label_selector: str | dict | None = None,
        *,
        send_initial: bool = True,
        resource_version: str | None = None,
    ) -> AsyncIterator[tuple[str, dict]]:
        if send_initial:
            for obj in await self.list(kind, namespace, label_selector):
                yield ("ADDED", obj)
        params = {"watch": "true"}
        sel = selector_to_string(label_selector)
        if sel:
            params["labelSelector"] = sel
        if resource_version:
            # Continue exactly where the priming list left off; a 410 Gone
            # surfaces as ApiError and the informer relists.
            params["resourceVersion"] = resource_version
        sess = await self._sess()
        gvk = self.scheme.by_kind(kind)
        async with sess.get(
            self._url(kind, namespace),
            params=params,
            ssl=self._ssl,
            # Streams idle by design — no total/read deadline; connect
            # keeps the session default so a dead endpoint still fails fast.
            timeout=aiohttp.ClientTimeout(
                total=None, sock_read=None, connect=self._timeout_connect),
        ) as resp:
            if resp.status >= 400:
                raise error_for_code(resp.status, await resp.text())
            # Manual line buffering: aiohttp's line iterator raises on JSON
            # lines beyond its 64 KiB readline limit, which real objects
            # (managedFields, big ConfigMaps) exceed routinely.
            buf = b""
            async for chunk in resp.content.iter_any():
                buf += chunk
                while b"\n" in buf:
                    line, buf = buf.split(b"\n", 1)
                    line = line.strip()
                    if not line:
                        continue
                    evt = json.loads(line)
                    obj = evt.get("object", {})
                    if evt.get("type") == "ERROR":
                        # e.g. 410 Gone on an expired resourceVersion — the
                        # Status object is not a resource; surface as an
                        # ApiError so the informer relists.
                        raise error_for_code(
                            obj.get("code", 500), obj.get("message", "watch error")
                        )
                    obj.setdefault("kind", kind)
                    obj.setdefault("apiVersion", gvk.api_version)
                    yield (evt.get("type", "MODIFIED"), obj)

    async def pod_logs(
        self, name: str, namespace: str, container: str | None = None,
        tail_lines: int | None = None,
    ) -> str:
        """Text response, so it can't ride _request — but it gets the
        same treatment: read lane, trace header, and the session
        deadline surfacing as a retriable ServerTimeout rather than a
        raw asyncio.TimeoutError no error middleware maps."""
        url = self._url("Pod", namespace, name) + "/log"
        params: dict = {}
        if container:
            params["container"] = container
        if tail_lines is not None:
            params["tailLines"] = str(tail_lines)
        sess = await self._sess()
        tracing.note_api_call("get", "Pod")
        headers = {}
        trace_id = tracing.current_trace_id()
        if trace_id:
            headers["X-Request-Id"] = trace_id
        try:
            async with self.flow.slot("get", "Pod"):
                async with sess.get(
                    url, params=params, ssl=self._ssl, headers=headers
                ) as resp:
                    body = await resp.text()
                    if resp.status >= 400:
                        raise error_for_code(resp.status, body[:500])
                    return body
        except asyncio.TimeoutError:
            raise ServerTimeout(
                f"GET {url}: no response within the client deadline"
            ) from None

    async def get_or_none(self, kind: str, name: str, namespace: str | None = None):
        from kubeflow_tpu.runtime.errors import NotFound

        try:
            return await self.get(kind, name, namespace)
        except NotFound:
            return None
