"""Real-apiserver client over aiohttp.

Same ``KubeApi`` surface as ``FakeKube``, speaking the actual Kubernetes REST
conventions: GVR paths from the scheme, merge-patch content types, watch via
``?watch=true`` chunked JSON lines, in-cluster auth from the mounted
ServiceAccount (token + CA), or kubeconfig-less host/token injection for dev.
"""

from __future__ import annotations

import asyncio
import json
import os
import ssl
from typing import AsyncIterator

import aiohttp

from kubeflow_tpu.runtime import tracing
from kubeflow_tpu.runtime.errors import error_for_code
from kubeflow_tpu.runtime.objects import name_of, namespace_of, selector_to_string
from kubeflow_tpu.runtime.scheme import DEFAULT_SCHEME, Scheme

SA_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"


class HttpKube:
    def __init__(
        self,
        base_url: str | None = None,
        token: str | None = None,
        ca_file: str | None = None,
        scheme: Scheme | None = None,
        verify_tls: bool = True,
    ):
        self.scheme = scheme or DEFAULT_SCHEME
        host = os.environ.get("KUBERNETES_SERVICE_HOST")
        port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
        self.base_url = base_url or (f"https://{host}:{port}" if host else "http://127.0.0.1:8001")
        if token is None and os.path.exists(f"{SA_DIR}/token"):
            with open(f"{SA_DIR}/token") as f:
                token = f.read().strip()
        self.token = token
        if ca_file is None and os.path.exists(f"{SA_DIR}/ca.crt"):
            ca_file = f"{SA_DIR}/ca.crt"
        self._ssl: ssl.SSLContext | bool | None = None
        if self.base_url.startswith("https"):
            if ca_file:
                self._ssl = ssl.create_default_context(cafile=ca_file)
            elif not verify_tls:
                self._ssl = False
        self._session: aiohttp.ClientSession | None = None

    async def _sess(self) -> aiohttp.ClientSession:
        if self._session is None or self._session.closed:
            headers = {}
            if self.token:
                headers["Authorization"] = f"Bearer {self.token}"
            self._session = aiohttp.ClientSession(headers=headers)
        return self._session

    async def close(self) -> None:
        if self._session and not self._session.closed:
            await self._session.close()

    def _url(self, kind: str, namespace: str | None, name: str | None = None) -> str:
        gvk = self.scheme.by_kind(kind)
        url = self.base_url + gvk.rest_base(namespace)
        if name:
            url += f"/{name}"
        return url

    async def _request(
        self, method: str, url: str, *, verb: str | None = None,
        kind: str | None = None, **kw,
    ) -> dict:
        sess = await self._sess()
        # Correlate with the active reconcile trace: the trace id travels
        # as X-Request-Id, so the apiserver audit log and this process's
        # flight recorder describe the same request by the same id. The
        # verb/kind tag lands on the trace's root span (api_calls).
        tracing.note_api_call(verb or method.lower(), kind)
        trace_id = tracing.current_trace_id()
        if trace_id:
            headers = dict(kw.pop("headers", None) or {})
            headers.setdefault("X-Request-Id", trace_id)
            kw["headers"] = headers
        async with sess.request(method, url, ssl=self._ssl, **kw) as resp:
            body = await resp.text()
            if resp.status >= 400:
                # The apiserver returns a Status object; its ``reason`` is
                # the authoritative error discriminator (409 AlreadyExists
                # vs Conflict), not the free-text message.
                reason = None
                try:
                    reason = json.loads(body).get("reason")
                except (ValueError, AttributeError):
                    pass
                raise error_for_code(
                    resp.status, f"{method} {url}: {body[:500]}", reason=reason
                )
            return json.loads(body) if body else {}

    # ---- KubeApi surface -----------------------------------------------------

    async def get(self, kind: str, name: str, namespace: str | None = None) -> dict:
        return await self._request(
            "GET", self._url(kind, namespace, name), verb="get", kind=kind
        )

    async def list(
        self,
        kind: str,
        namespace: str | None = None,
        label_selector: str | dict | None = None,
        field_selector=None,
    ) -> list[dict]:
        items, _ = await self.list_with_rv(kind, namespace, label_selector, field_selector)
        return items

    async def list_with_rv(
        self,
        kind: str,
        namespace: str | None = None,
        label_selector: str | dict | None = None,
        field_selector=None,
    ) -> tuple[list[dict], str | None]:
        """List plus the collection resourceVersion, for list→watch continuity."""
        params = {}
        sel = selector_to_string(label_selector)
        if sel:
            params["labelSelector"] = sel
        data = await self._request(
            "GET", self._url(kind, namespace), verb="list", kind=kind,
            params=params,
        )
        items = data.get("items", [])
        gvk = self.scheme.by_kind(kind)
        for item in items:
            item.setdefault("kind", kind)
            item.setdefault("apiVersion", gvk.api_version)
        if field_selector:
            items = [o for o in items if field_selector(o)]
        return items, (data.get("metadata") or {}).get("resourceVersion")

    async def create(self, kind: str, obj: dict, namespace: str | None = None) -> dict:
        ns = namespace or namespace_of(obj)
        return await self._request(
            "POST", self._url(kind, ns), verb="create", kind=kind, json=obj
        )

    async def update(self, kind: str, obj: dict) -> dict:
        return await self._request(
            "PUT", self._url(kind, namespace_of(obj), name_of(obj)),
            verb="update", kind=kind, json=obj,
        )

    async def update_status(self, kind: str, obj: dict) -> dict:
        url = self._url(kind, namespace_of(obj), name_of(obj)) + "/status"
        return await self._request(
            "PUT", url, verb="update_status", kind=kind, json=obj
        )

    async def patch(
        self,
        kind: str,
        name: str,
        patch: dict,
        namespace: str | None = None,
        subresource: str | None = None,
    ) -> dict:
        url = self._url(kind, namespace, name)
        if subresource:
            url += f"/{subresource}"
        return await self._request(
            "PATCH",
            url,
            verb="patch",
            kind=kind,
            data=json.dumps(patch),
            headers={"Content-Type": "application/merge-patch+json"},
        )

    async def delete(self, kind: str, name: str, namespace: str | None = None) -> None:
        await self._request(
            "DELETE",
            self._url(kind, namespace, name),
            verb="delete",
            kind=kind,
            json={"propagationPolicy": "Background"},
        )

    async def watch(
        self,
        kind: str,
        namespace: str | None = None,
        label_selector: str | dict | None = None,
        *,
        send_initial: bool = True,
        resource_version: str | None = None,
    ) -> AsyncIterator[tuple[str, dict]]:
        if send_initial:
            for obj in await self.list(kind, namespace, label_selector):
                yield ("ADDED", obj)
        params = {"watch": "true"}
        sel = selector_to_string(label_selector)
        if sel:
            params["labelSelector"] = sel
        if resource_version:
            # Continue exactly where the priming list left off; a 410 Gone
            # surfaces as ApiError and the informer relists.
            params["resourceVersion"] = resource_version
        sess = await self._sess()
        gvk = self.scheme.by_kind(kind)
        async with sess.get(
            self._url(kind, namespace),
            params=params,
            ssl=self._ssl,
            timeout=aiohttp.ClientTimeout(total=None, sock_read=None),
        ) as resp:
            if resp.status >= 400:
                raise error_for_code(resp.status, await resp.text())
            # Manual line buffering: aiohttp's line iterator raises on JSON
            # lines beyond its 64 KiB readline limit, which real objects
            # (managedFields, big ConfigMaps) exceed routinely.
            buf = b""
            async for chunk in resp.content.iter_any():
                buf += chunk
                while b"\n" in buf:
                    line, buf = buf.split(b"\n", 1)
                    line = line.strip()
                    if not line:
                        continue
                    evt = json.loads(line)
                    obj = evt.get("object", {})
                    if evt.get("type") == "ERROR":
                        # e.g. 410 Gone on an expired resourceVersion — the
                        # Status object is not a resource; surface as an
                        # ApiError so the informer relists.
                        raise error_for_code(
                            obj.get("code", 500), obj.get("message", "watch error")
                        )
                    obj.setdefault("kind", kind)
                    obj.setdefault("apiVersion", gvk.api_version)
                    yield (evt.get("type", "MODIFIED"), obj)

    async def pod_logs(
        self, name: str, namespace: str, container: str | None = None,
        tail_lines: int | None = None,
    ) -> str:
        url = self._url("Pod", namespace, name) + "/log"
        params: dict = {}
        if container:
            params["container"] = container
        if tail_lines is not None:
            params["tailLines"] = str(tail_lines)
        sess = await self._sess()
        async with sess.get(url, params=params, ssl=self._ssl) as resp:
            body = await resp.text()
            if resp.status >= 400:
                raise error_for_code(resp.status, body[:500])
            return body

    async def get_or_none(self, kind: str, name: str, namespace: str | None = None):
        from kubeflow_tpu.runtime.errors import NotFound

        try:
            return await self.get(kind, name, namespace)
        except NotFound:
            return None
