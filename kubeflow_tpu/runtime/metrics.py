"""Minimal Prometheus client (text exposition format).

The reference registers custom collectors with controller-runtime's registry
(``notebook-controller/pkg/metrics/metrics.go:14-99``). No prometheus client
ships in this image, so this is a from-scratch implementation of the 20% we
use: counters, gauges, histograms, labels, and text-format exposition.
"""

from __future__ import annotations

import threading
import time
from collections import defaultdict


def _escape_label_value(value: str) -> str:
    """Prometheus text exposition escaping for label values: backslash,
    double-quote, and newline (a notebook name containing a quote would
    otherwise corrupt the whole /metrics scrape)."""
    return (
        str(value)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def _fmt_labels(labels: dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(
        f'{k}="{_escape_label_value(v)}"' for k, v in sorted(labels.items())
    )
    return "{" + inner + "}"


class _Child:
    def __init__(self):
        self.value = 0.0
        self._lock = threading.Lock()

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self.value += amount

    def set(self, value: float) -> None:
        with self._lock:
            self.value = float(value)


class _Metric:
    type_name = "untyped"

    def __init__(self, name: str, help_: str, label_names: list[str]):
        self.name = name
        self.help = help_
        self.label_names = label_names
        self._children: dict[tuple, _Child] = defaultdict(_Child)

    def labels(self, **labels: str) -> _Child:
        key = tuple(str(labels.get(n, "")) for n in self.label_names)
        return self._children[key]

    # convenience for label-less metrics
    def inc(self, amount: float = 1.0) -> None:
        self.labels().inc(amount)

    def set(self, value: float) -> None:
        self.labels().set(value)

    def collect(self) -> list[str]:
        lines = [
            f"# HELP {self.name} {self.help}",
            f"# TYPE {self.name} {self.type_name}",
        ]
        children = self._children or {(): _Child()}
        for key, child in sorted(children.items()):
            labels = dict(zip(self.label_names, key))
            lines.append(f"{self.name}{_fmt_labels(labels)} {child.value}")
        return lines


class Counter(_Metric):
    type_name = "counter"


class Gauge(_Metric):
    type_name = "gauge"


class _HistogramChild:
    """A label-bound observer. ``hist.labels(...)`` used to inherit the
    counter/gauge child from ``_Metric`` and silently write into a dead
    ``_children`` map that ``Histogram.collect()`` never read — data was
    dropped. Now labels() routes to observe() and the counter/gauge verbs
    raise instead of lying."""

    def __init__(self, hist: "Histogram", labels: dict):
        self._hist = hist
        self._labels = labels

    def observe(self, value: float) -> None:
        self._hist.observe(value, **self._labels)

    def time(self) -> "_Timer":
        return _Timer(self._hist, self._labels)

    def inc(self, amount: float = 1.0) -> None:
        raise TypeError("histograms have no inc(); use observe()")

    def set(self, value: float) -> None:
        raise TypeError("histograms have no set(); use observe()")


class Histogram(_Metric):
    type_name = "histogram"

    DEFAULT_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1, 2.5, 5, 10, 30, 60)

    def __init__(self, name, help_, label_names, buckets=None):
        super().__init__(name, help_, label_names)
        self.buckets = tuple(buckets or self.DEFAULT_BUCKETS)
        self._data: dict[tuple, dict] = defaultdict(
            lambda: {"counts": [0] * len(self.buckets), "sum": 0.0, "count": 0}
        )
        self._lock = threading.Lock()

    def labels(self, **labels: str) -> _HistogramChild:
        return _HistogramChild(self, labels)

    def inc(self, amount: float = 1.0) -> None:
        raise TypeError("histograms have no inc(); use observe()")

    def set(self, value: float) -> None:
        raise TypeError("histograms have no set(); use observe()")

    def observe(self, value: float, **labels: str) -> None:
        key = tuple(str(labels.get(n, "")) for n in self.label_names)
        with self._lock:
            data = self._data[key]
            data["sum"] += value
            data["count"] += 1
            for i, bound in enumerate(self.buckets):
                if value <= bound:
                    data["counts"][i] += 1
                    break  # collect() cumulates; counting once keeps buckets monotone

    def time(self, **labels: str) -> "_Timer":
        """``with hist.time(controller="notebook"): ...`` observes the
        block's wall duration — the reconcile-latency idiom."""
        return _Timer(self, labels)

    def snapshot(self, **labels: str) -> dict:
        """(count, sum) for one label set — lets the bench report mean
        latency without parsing the exposition text."""
        key = tuple(str(labels.get(n, "")) for n in self.label_names)
        with self._lock:
            data = self._data.get(key)
            return {"count": data["count"], "sum": data["sum"]} if data else \
                {"count": 0, "sum": 0.0}


    def collect(self) -> list[str]:
        lines = [
            f"# HELP {self.name} {self.help}",
            f"# TYPE {self.name} histogram",
        ]
        for key, data in sorted(self._data.items()):
            labels = dict(zip(self.label_names, key))
            cumulative = 0
            for bound, count in zip(self.buckets, data["counts"]):
                cumulative += count
                lines.append(
                    f'{self.name}_bucket{_fmt_labels({**labels, "le": str(bound)})} {cumulative}'
                )
            lines.append(f'{self.name}_bucket{_fmt_labels({**labels, "le": "+Inf"})} {data["count"]}')
            lines.append(f"{self.name}_sum{_fmt_labels(labels)} {data['sum']}")
            lines.append(f"{self.name}_count{_fmt_labels(labels)} {data['count']}")
        return lines


class _Timer:
    def __init__(self, hist: Histogram, labels: dict):
        self._hist = hist
        self._labels = labels

    def __enter__(self) -> "_Timer":
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc) -> None:
        self._hist.observe(time.perf_counter() - self._t0, **self._labels)


class Registry:
    def __init__(self):
        self._metrics: dict[str, _Metric] = {}
        self._lock = threading.Lock()

    def _register(self, cls, name, help_, label_names, **kw):
        with self._lock:
            existing = self._metrics.get(name)
            if existing is not None:
                # Re-registration is idempotent ONLY for an identical
                # schema; silently returning a metric with different label
                # names or type would make writers disagree with collect()
                # about the label tuple and corrupt the series.
                if type(existing) is not cls:
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{type(existing).__name__}, not {cls.__name__}"
                    )
                if existing.label_names != list(label_names or []):
                    raise ValueError(
                        f"metric {name!r} already registered with labels "
                        f"{existing.label_names}, not {list(label_names or [])}"
                    )
                return existing
            metric = cls(name, help_, label_names or [], **kw)
            self._metrics[name] = metric
            return metric

    def counter(self, name: str, help_: str = "", label_names: list[str] | None = None) -> Counter:
        return self._register(Counter, name, help_, label_names)

    def gauge(self, name: str, help_: str = "", label_names: list[str] | None = None) -> Gauge:
        return self._register(Gauge, name, help_, label_names)

    def histogram(
        self, name: str, help_: str = "", label_names: list[str] | None = None, buckets=None
    ) -> Histogram:
        return self._register(Histogram, name, help_, label_names, buckets=buckets)

    def expose(self) -> str:
        lines: list[str] = []
        for metric in self._metrics.values():
            lines.extend(metric.collect())
        return "\n".join(lines) + "\n"


global_registry = Registry()
