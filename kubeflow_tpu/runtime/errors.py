"""API error taxonomy mirroring Kubernetes StatusReasons."""

from __future__ import annotations


class ApiError(Exception):
    """Base error for apiserver interactions."""

    code = 500
    reason = "InternalError"

    def __init__(self, message: str = ""):
        super().__init__(message or self.reason)
        self.message = message or self.reason


class NotFound(ApiError):
    code = 404
    reason = "NotFound"


class AlreadyExists(ApiError):
    code = 409
    reason = "AlreadyExists"


class Conflict(ApiError):
    """resourceVersion conflict — caller should re-read and retry."""

    code = 409
    reason = "Conflict"


class Invalid(ApiError):
    code = 422
    reason = "Invalid"


class Forbidden(ApiError):
    code = 403
    reason = "Forbidden"


class Unauthorized(ApiError):
    code = 401
    reason = "Unauthorized"


class TooManyRequests(ApiError):
    """429 — apiserver priority & fairness rejected the request. The
    client honors Retry-After with bounded retries before raising."""

    code = 429
    reason = "TooManyRequests"


class ServerTimeout(ApiError):
    """No response within the client deadline (hung apiserver / dead
    conntrack entry). Retriable: the workqueue re-queues with backoff,
    which is exactly what a pinned-forever reconcile worker could not do."""

    code = 504
    reason = "ServerTimeout"


def error_for_code(code: int, message: str = "", reason: str | None = None) -> ApiError:
    if code == 409:
        # Both AlreadyExists and Conflict are 409s; the apiserver's Status
        # body carries the distinguishing ``reason`` field — prefer it when
        # the caller parsed one (free-text matching misclassifies a Conflict
        # whose message happens to contain "already exists", or a non-English
        # AlreadyExists body). Default to Conflict — the stale-resourceVersion
        # case — as the safer retry behavior.
        if reason == "AlreadyExists":
            return AlreadyExists(message)
        if reason == "Conflict":
            return Conflict(message)
        if "AlreadyExists" in message or "already exists" in message:
            return AlreadyExists(message)
        return Conflict(message)
    for cls in (NotFound, Invalid, Forbidden, Unauthorized, TooManyRequests,
                ServerTimeout):
        if cls.code == code:
            return cls(message)
    err = ApiError(message)
    err.code = code
    return err
