"""Controller runtime, built from scratch for this stack.

The reference leans on controller-runtime (Go); this package is its
asyncio-native equivalent: a typed scheme, list/watch informers with local
caches, rate-limited workqueues, reconciler workers, create-or-update apply
helpers with drift detection, an event recorder, and Prometheus-text metrics.
Controllers talk to any object implementing the ``KubeApi`` protocol — the
real apiserver over HTTPS (``httpclient.HttpKube``) or the in-memory fake
(``kubeflow_tpu.testing.fakekube.FakeKube``, our envtest).
"""

from kubeflow_tpu.runtime.errors import ApiError, Conflict, Forbidden, NotFound
from kubeflow_tpu.runtime.objects import (
    controller_owner,
    get_meta,
    new_object,
    owned_by,
    set_controller_owner,
)
from kubeflow_tpu.runtime.scheme import Scheme, GVK, DEFAULT_SCHEME

__all__ = [
    "ApiError",
    "Conflict",
    "Forbidden",
    "NotFound",
    "Scheme",
    "GVK",
    "DEFAULT_SCHEME",
    "controller_owner",
    "get_meta",
    "new_object",
    "owned_by",
    "set_controller_owner",
]
