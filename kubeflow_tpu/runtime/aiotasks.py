"""Task-teardown helper shared by every component that owns tasks.

``reap`` is the one audited place a cancelled task's outcome may be
dropped: on shutdown the owner cancels its tasks and awaits them so
cancellation actually lands before the process (or test) moves on —
and at that point the task's result is noise. A real failure was
already surfaced by the task itself while it ran (workqueue backoff,
informer relist counters, log lines); re-raising it out of ``stop()``
would turn every teardown into a crash lottery.

Grown out of ISSUE 12's ``exception-swallow`` pass: five copies of the
``try: await task / except (CancelledError, Exception): pass`` idiom
(manager, informer, leader election, podsim, chaos harness) became this
one documented swallow.
"""

from __future__ import annotations

import asyncio


async def reap(*tasks: asyncio.Task | None) -> None:
    """Await already-cancelled (or finished) tasks, discarding outcomes.

    Call AFTER ``task.cancel()``: this only reaps — it does not cancel.
    ``None`` entries are skipped so callers can pass optional task
    slots without guarding.
    """
    for task in tasks:
        if task is None:
            continue
        try:
            await task
        except (asyncio.CancelledError, Exception):  # kftpu: ignore[exception-swallow] teardown reaper — the task surfaced its own failures while alive; stop() must not crash on them
            pass
