"""Namespace-hash sharding for an active-active control plane.

One manager replica per *process slot*, N lease-backed **shards** over the
controller keyspace: every key hashes namespace → shard, and a replica
reconciles a key iff it currently holds that shard's Lease. This is the
direction knative's StatefulSet bucket leases and client-go sharded
informers take — membership IS lease ownership, so the failure story
reduces to the lease protocol already proven in
``runtime/leaderelection.py``:

* one ``coordination.k8s.io/v1`` Lease per shard
  (``kubeflow-tpu-shard-<i>``), held by at most one replica;
* each replica has a static *preferred* slice (``shard % replicas ==
  replica``) it claims eagerly, so a healthy fleet converges to an even
  spread without coordination;
* a dead replica's shards expire and are absorbed by survivors — a
  non-preferred shard is only claimed after it has been observed
  orphaned on two consecutive ticks, giving the preferred owner a full
  tick of priority and keeping startup races from scrambling the spread;
* a restarted preferred owner reclaims its slice **on demand**: it
  stamps a claim annotation (``SHARD_PREFERRED_CLAIM``) on the held
  Lease, and the holder releases at its next renew iff the claim is
  younger than ``lease_seconds``. A dead replica never stamps, so an
  absorbed shard whose preferred owner is gone is simply kept — no
  periodic release churn into a void (the failure mode of timer-based
  handback). ``handback_ticks`` remains as an optional belt-and-
  suspenders periodic release, off by default.

Hashing is ``zlib.crc32``, not ``hash()``: built-in str hashing is salted
per process (PYTHONHASHSEED) and would both break seed-reproducible
chaos runs and disagree ACROSS replicas — two replicas disagreeing on
``shard_of`` is a dual-processing bug, not a perf problem.

Shard 0 doubles as the **arbiter** shard: cluster-scoped keys (no
namespace) hash there, and whichever replica holds it runs the global
chip-ledger arbitration (scheduler/runtime.py ``attach_ring``).
"""

from __future__ import annotations

import asyncio
import logging
import time
import zlib

from kubeflow_tpu.api.keys import SHARD_PREFERRED_CLAIM
from kubeflow_tpu.runtime.aiotasks import reap
from kubeflow_tpu.runtime.errors import ApiError
from kubeflow_tpu.runtime.leaderelection import LeaderElector
from kubeflow_tpu.runtime.metrics import global_registry
from kubeflow_tpu.runtime.objects import deep_get, fmt_iso_micro, parse_iso

log = logging.getLogger(__name__)

LEASE_PREFIX = "kubeflow-tpu-shard"
ARBITER_SHARD = 0


def shard_of(namespace: str | None, shards: int) -> int:
    """Map a key's namespace to its shard. Cluster-scoped objects (no
    namespace) land on the arbiter shard deterministically."""
    if shards <= 1:
        return 0
    if not namespace:
        return ARBITER_SHARD
    return zlib.crc32(namespace.encode()) % shards


class ShardRing:
    """One replica's view of the shard lease ring.

    The ring never runs per-elector renew loops; a single maintenance
    loop ticks every ``renew_seconds`` and, per shard: renews what it
    holds, eagerly claims its preferred slice, and absorbs orphans after
    the two-tick confirmation. Ownership reads (``owns_key`` & friends)
    are synchronous set lookups — they sit on the informer-delta and
    dequeue hot paths.
    """

    def __init__(
        self,
        kube,
        *,
        shards: int = 4,
        replica: int = 0,
        replicas: int = 1,
        identity: str | None = None,
        namespace: str = "kubeflow-tpu",
        lease_prefix: str = LEASE_PREFIX,
        lease_seconds: float = 15.0,
        renew_seconds: float = 5.0,
        handback_ticks: int = 0,
        clock=None,
        registry=None,
    ):
        if shards < 1:
            raise ValueError("shards must be >= 1")
        if not (0 <= replica < max(1, replicas)):
            raise ValueError(f"replica {replica} out of range for "
                             f"{replicas} replicas")
        self.kube = kube
        self.shards = shards
        self.replica = replica
        self.replicas = max(1, replicas)
        self.identity = identity or f"replica-{replica}"
        self.namespace = namespace
        self.renew_seconds = renew_seconds
        self.lease_seconds = lease_seconds
        self.handback_ticks = handback_ticks
        self.clock = clock or time.time
        self._lease_prefix = lease_prefix
        registry = registry or global_registry
        self._electors = [
            LeaderElector(
                kube,
                lease_name=f"{lease_prefix}-{i}",
                namespace=namespace,
                identity=self.identity,
                lease_seconds=lease_seconds,
                renew_seconds=renew_seconds,
                clock=self.clock,
                registry=registry,
            )
            for i in range(shards)
        ]
        self._owned: set[int] = set()
        self._renew_failures: dict[int, int] = {}
        # shard → consecutive ticks observed orphaned (expired/unheld);
        # a non-preferred shard needs 2 before absorption.
        self._orphan_ticks: dict[int, int] = {}
        # shard → ticks left before a voluntary handback (absorbed
        # shards only; 0 entries mean no countdown running).
        self._handback: dict[int, int] = {}
        # Last observed holder per shard (observability only).
        self.holders: dict[int, str | None] = {}
        self.transitions = 0
        self._acquire_cbs: list = []
        self._lose_cbs: list = []
        self._task: asyncio.Task | None = None
        self._m_owned = registry.gauge(
            "shard_ring_owned_shards",
            "Shards whose lease this replica currently holds")
        self._m_transitions = registry.counter(
            "shard_ring_transitions_total",
            "Shard ownership changes observed by this replica",
            ["shard", "event"])  # acquired | lost | handback

    # ---- ownership reads (hot path, sync) ---------------------------------------

    @property
    def owned(self) -> frozenset:
        return frozenset(self._owned)

    def owns_shard(self, shard: int) -> bool:
        return shard in self._owned

    def owns_namespace(self, namespace: str | None) -> bool:
        return shard_of(namespace, self.shards) in self._owned

    def owns_key(self, key) -> bool:
        """key is a (namespace, name) tuple — the manager's Key shape."""
        return shard_of(key[0], self.shards) in self._owned

    @property
    def is_arbiter(self) -> bool:
        return ARBITER_SHARD in self._owned

    # ---- callbacks --------------------------------------------------------------

    def on_acquire(self, cb) -> None:
        """cb(shard: int), fired synchronously when a shard is gained."""
        self._acquire_cbs.append(cb)

    def on_lose(self, cb) -> None:
        """cb(shard: int), fired synchronously when a shard is lost —
        BEFORE any lease API write, so fencing precedes visibility."""
        self._lose_cbs.append(cb)

    def _fire(self, cbs: list, shard: int) -> None:
        for cb in cbs:
            try:
                cb(shard)
            except Exception:
                log.exception("shard ring callback failed for shard %d", shard)

    def _gain(self, shard: int) -> None:
        if shard in self._owned:
            return
        self._owned.add(shard)
        self.transitions += 1
        self.holders[shard] = self.identity
        self._electors[shard]._set_leader(True)
        self._m_owned.set(len(self._owned))
        self._m_transitions.labels(shard=str(shard), event="acquired").inc()
        self._renew_failures[shard] = 0
        self._orphan_ticks.pop(shard, None)
        if self.handback_ticks and not self._preferred(shard):
            self._handback[shard] = self.handback_ticks
        log.info("shard ring: %s acquired shard %d", self.identity, shard)
        self._fire(self._acquire_cbs, shard)

    def _drop(self, shard: int, event: str = "lost") -> None:
        if shard not in self._owned:
            return
        # Fence FIRST: the moment ownership is gone locally, workers stop
        # dequeuing this shard's keys — only then may the lease become
        # claimable by someone else.
        self._owned.discard(shard)
        self.transitions += 1
        self._handback.pop(shard, None)
        self._electors[shard]._set_leader(False)
        self._m_owned.set(len(self._owned))
        self._m_transitions.labels(shard=str(shard), event=event).inc()
        log.log(logging.INFO if event == "handback" else logging.ERROR,
                "shard ring: %s %s shard %d", self.identity, event, shard)
        self._fire(self._lose_cbs, shard)

    def _preferred(self, shard: int) -> bool:
        return shard % self.replicas == self.replica

    # ---- maintenance ------------------------------------------------------------

    async def tick(self) -> None:
        """One maintenance round: renew held shards, claim preferred and
        confirmed-orphan shards. Public so tests (and soak harnesses with
        scaled clocks) can drive the ring deterministically."""
        for shard in range(self.shards):
            el = self._electors[shard]
            if shard in self._owned:
                countdown = self._handback.get(shard)
                if countdown is not None:
                    if countdown <= 1:
                        await self._handback_shard(shard, el)
                        continue
                    # kftpu: ignore[await-race] the single maintenance task (start's tick + _loop) is the only writer of the per-shard counters; debug_info only reads, and a torn snapshot there is harmless
                    self._handback[shard] = countdown - 1
                if await el.try_acquire():
                    # kftpu: ignore[await-race] same single-maintenance-writer argument as _handback above
                    self._renew_failures[shard] = 0
                    # Demand-driven handback: an absorbed shard goes back
                    # the moment its preferred owner proves it is alive by
                    # stamping a fresh claim on the Lease. No claimant →
                    # keep the shard forever (the owner is dead; releasing
                    # would just churn the keyspace through an unowned
                    # window every few ticks for nobody).
                    if not self._preferred(shard):
                        claimant = await self._fresh_claim(shard)
                        if claimant is not None and claimant != self.identity:
                            await self._handback_shard(shard, el)
                    continue
                # Mirror the single-lease renew tolerance: transient API
                # failures are survivable while the lease is still fresh;
                # an observed FOREIGN holder is an immediate loss.
                self._renew_failures[shard] = \
                    self._renew_failures.get(shard, 0) + 1
                holder = await el.current_holder()
                lost_for_sure = holder is not None and holder != self.identity
                expired_budget = (self._renew_failures[shard]
                                  * self.renew_seconds >= self.lease_seconds)
                if lost_for_sure or expired_budget:
                    self.holders[shard] = holder
                    self._drop(shard)
                continue
            holder = await el.current_holder()
            self.holders[shard] = holder
            if holder is None:
                # kftpu: ignore[await-race] same single-maintenance-writer argument as _handback above
                self._orphan_ticks[shard] = \
                    self._orphan_ticks.get(shard, 0) + 1
            else:
                self._orphan_ticks[shard] = 0
            eager = self._preferred(shard)
            confirmed_orphan = self._orphan_ticks.get(shard, 0) >= 2
            if eager or confirmed_orphan:
                if await el.try_acquire():
                    self._gain(shard)
                elif eager and holder is not None:
                    # Preferred shard held fresh by someone else (we came
                    # back after a crash, or a startup race scrambled the
                    # spread): ask for it back. The holder releases at its
                    # next renew; acquisition follows on our next tick.
                    await self._stamp_claim(shard)

    async def _handback_shard(self, shard: int, el: LeaderElector) -> None:
        """Voluntarily release an absorbed shard so its (possibly
        restarted) preferred owner can reclaim it."""
        self._drop(shard, event="handback")
        await el.release()

    # ---- demand-driven handback (claim protocol) --------------------------------

    def _lease_name(self, shard: int) -> str:
        return f"{self._lease_prefix}-{shard}"

    def _parse_claim(self, lease: dict) -> str | None:
        """The claim annotation's identity, or None when absent/stale.
        Freshness is judged against ``lease_seconds`` with THIS replica's
        clock — same skew tolerance as the lease protocol itself; a
        claimant that stopped stamping (died) goes stale within one
        lease duration and is ignored."""
        raw = deep_get(lease, "metadata", "annotations",
                       SHARD_PREFERRED_CLAIM, default="") or ""
        ident, _, stamp = raw.rpartition(" ")
        ts = parse_iso(stamp)
        if not ident or ts is None or self.clock() - ts > self.lease_seconds:
            return None
        return ident

    async def _fresh_claim(self, shard: int) -> str | None:
        try:
            lease = await self.kube.get(
                "Lease", self._lease_name(shard), self.namespace)
        except ApiError:
            return None
        return self._parse_claim(lease)

    async def _stamp_claim(self, shard: int) -> None:
        """Record that this live replica wants its preferred shard back.
        Write-through CAS like the lease protocol: the update carries the
        read's resourceVersion, so a racing holder renew wins cleanly and
        we simply retry next tick."""
        try:
            lease = await self.kube.get(
                "Lease", self._lease_name(shard), self.namespace)
        except ApiError:
            return
        if self._parse_claim(lease) == self.identity:
            return  # our claim is still fresh; don't churn the holder's CAS
        ann = lease.setdefault("metadata", {}).setdefault("annotations", {})
        ann[SHARD_PREFERRED_CLAIM] = \
            f"{self.identity} {fmt_iso_micro(self.clock())}"
        try:
            # kftpu: ignore[await-race] the update IS the CAS: it carries the resourceVersion from the get above, so a racing holder renew wins with Conflict and we retry next tick — re-validation is server-side
            await self.kube.update("Lease", lease)
        except ApiError:
            pass  # lost the CAS to the holder's renew; retry next tick

    async def start(self) -> None:
        """Run one synchronous tick (so a cold replica owns its preferred
        shards before its manager starts), then maintain in background."""
        await self.tick()
        self._task = asyncio.create_task(self._loop(), name="shard-ring")

    async def _loop(self) -> None:
        while True:
            await asyncio.sleep(self.renew_seconds)
            try:
                await self.tick()
            except asyncio.CancelledError:
                raise
            except Exception:
                log.exception("shard ring maintenance tick failed")

    async def stop(self, *, release: bool = True) -> None:
        """Graceful departure: stop maintaining and (by default) release
        every held lease so survivors absorb without waiting for expiry.
        ``release=False`` models a crash — leases are left to expire."""
        if self._task:
            self._task.cancel()
            await reap(self._task)
            # kftpu: ignore[await-race] the cancel above stopped the only other writer (_loop never touches _task anyway); shutdown is caller-serialized
            self._task = None
        for shard in sorted(self._owned):
            self._drop(shard, event="lost")
            if release:
                await self._electors[shard].release()

    async def kill(self) -> None:
        """Simulated process crash for chaos harnesses: the maintenance
        loop dies and NOTHING else happens — no lease writes, no fencing
        callbacks, local ownership state frozen mid-flight. Survivors must
        recover purely from lease expiry, exactly as with a real SIGKILL."""
        if self._task:
            self._task.cancel()
            await reap(self._task)
            # kftpu: ignore[await-race] same cancel-first shutdown ordering as stop()
            self._task = None

    # ---- observability ----------------------------------------------------------

    def debug_info(self) -> dict:
        return {
            "identity": self.identity,
            "shards": self.shards,
            "replica": self.replica,
            "replicas": self.replicas,
            "owned": sorted(self._owned),
            "is_arbiter": self.is_arbiter,
            "transitions": self.transitions,
            "holders": {str(s): h for s, h in sorted(self.holders.items())},
        }
