"""Client-side priority & fairness for apiserver traffic.

The server-side APF machinery protects the apiserver from *all* clients;
this is the client protecting itself — and the cluster — from its own
burst shapes (ISSUE 4). Three lanes, each a bounded concurrency pool:

- **read** (get/list): the informer relists and cold-cache fallbacks.
- **write** (create/update/patch/delete of anything but Events): the
  traffic that makes reconciles converge.
- **event**: best-effort Event emission. Low priority by construction —
  an event-lane request defers while the write lane is SATURATED
  (queued-or-in-flight writes ≥ the write limit), so an event flood (a
  cluster-wide slice restart narrating itself) can never starve the CR
  writes that fix it. The deference is bounded (``event_patience``):
  reconciles await their own event emissions inline, so an event must
  never wedge the reconcile issuing the writes — after the patience
  window it proceeds through its own (tiny) lane, which by construction
  never consumes write capacity anyway.

Watches are exempt: they are long-lived streams, and parking one in a
semaphore slot would deadlock the informer machinery the lanes exist to
serve. Both API clients route through this class — ``HttpKube`` on the
wire, ``FakeKube`` in-process — so lane behavior is testable in tier-1.

Limits default from env (documented in docs/operations.md):
``KUBE_CLIENT_MAX_READS``, ``KUBE_CLIENT_MAX_WRITES``,
``KUBE_CLIENT_EVENT_LANE``.
"""

from __future__ import annotations

import asyncio
import contextlib
import os

from kubeflow_tpu.runtime.errors import NotFound

WRITE_VERBS = frozenset(
    {"create", "update", "update_status", "patch", "delete"})
READ_VERBS = frozenset({"get", "list"})

READS_ENV = "KUBE_CLIENT_MAX_READS"
WRITES_ENV = "KUBE_CLIENT_MAX_WRITES"
EVENTS_ENV = "KUBE_CLIENT_EVENT_LANE"
EVENT_PATIENCE_ENV = "KUBE_CLIENT_EVENT_PATIENCE"
QPS_ENV = "KUBE_CLIENT_MAX_QPS"

DEFAULT_MAX_READS = 16
DEFAULT_MAX_WRITES = 8
DEFAULT_EVENT_LANE = 1
DEFAULT_EVENT_PATIENCE_SEC = 1.0
# client-go's rest.Config defaults QPS=20/Burst=30, i.e. burst = 1.5×QPS.
# Off (None) by default here: lanes bound concurrency already, and the
# QPS bucket is the per-REPLICA budget knob for sharded deployments.
QPS_BURST_FACTOR = 1.5


def _env_int(name: str, default: int) -> int:
    try:
        return max(1, int(os.environ.get(name, "") or default))
    except ValueError:
        return default


def _env_float(name: str, default: float) -> float:
    try:
        return max(0.0, float(os.environ.get(name, "") or default))
    except ValueError:
        return default


class FlowControl:
    """Bounded per-lane concurrency with a low-priority event lane."""

    def __init__(
        self,
        max_reads: int | None = None,
        max_writes: int | None = None,
        event_lane: int | None = None,
        event_patience: float | None = None,
        max_qps: float | None = None,
    ):
        # Explicit 0 is clamped to 1, not silently replaced by the env
        # default — a lane can be narrowed to serial, never to "off".
        self.max_reads = (max(1, max_reads) if max_reads is not None
                          else _env_int(READS_ENV, DEFAULT_MAX_READS))
        self.max_writes = (max(1, max_writes) if max_writes is not None
                           else _env_int(WRITES_ENV, DEFAULT_MAX_WRITES))
        self.event_lane = (max(1, event_lane) if event_lane is not None
                           else _env_int(EVENTS_ENV, DEFAULT_EVENT_LANE))
        self.event_patience = (
            event_patience if event_patience is not None
            else _env_float(EVENT_PATIENCE_ENV, DEFAULT_EVENT_PATIENCE_SEC))
        # client-go-style request rate cap (QPS + burst bucket), applied
        # to read/write lanes before lane admission. None = unlimited
        # (the historical behavior); the env knob lets a deployment cap
        # every replica uniformly.
        if max_qps is None:
            env_qps = _env_float(QPS_ENV, 0.0)
            max_qps = env_qps if env_qps > 0 else None
        self.max_qps = max_qps
        self._qps_burst = (max(1.0, max_qps * QPS_BURST_FACTOR)
                           if max_qps else 0.0)
        self._qps_tokens = self._qps_burst
        self._qps_refill_at: float | None = None
        self._read_sem = asyncio.Semaphore(self.max_reads)
        self._write_sem = asyncio.Semaphore(self.max_writes)
        self._event_sem = asyncio.Semaphore(self.event_lane)
        # Writes queued OR in flight. The event lane defers while this
        # saturates the write limit (set() = lane has spare capacity).
        self._writes_busy = 0
        self._lane_open = asyncio.Event()
        self._lane_open.set()
        self.admitted = {"read": 0, "write": 0, "event": 0}

    @staticmethod
    def lane_of(verb: str, kind: str | None = None) -> str | None:
        if verb in WRITE_VERBS:
            return "event" if kind == "Event" else "write"
        if verb in READ_VERBS:
            return "read"
        return None  # watch / pod_logs: long-lived or out of scope

    async def _pace(self) -> None:
        """Token-bucket pacing: take one token (going negative reserves a
        future slot, which keeps waiters FIFO-fair) and sleep out the
        deficit. Watches and the event lane are exempt — streams are
        long-lived, and events already yield to writes by design."""
        if not self.max_qps:
            return
        loop = asyncio.get_running_loop()
        now = loop.time()
        if self._qps_refill_at is not None:
            self._qps_tokens = min(
                self._qps_burst,
                self._qps_tokens + (now - self._qps_refill_at) * self.max_qps)
        self._qps_refill_at = now
        self._qps_tokens -= 1.0
        if self._qps_tokens < 0:
            await asyncio.sleep(-self._qps_tokens / self.max_qps)

    async def acquire(self, verb: str, kind: str | None = None) -> str | None:
        lane = self.lane_of(verb, kind)
        if lane in ("read", "write"):
            await self._pace()
        if lane == "read":
            await self._read_sem.acquire()
        elif lane == "write":
            self._bump_writes(+1)
            try:
                await self._write_sem.acquire()
            except BaseException:
                self._bump_writes(-1)
                raise
        elif lane == "event":
            # Low priority, bounded: defer while the write lane is
            # saturated (re-check after every wakeup — a new write may
            # have re-closed the gate), but never past the patience
            # window — reconciles await their own emissions inline, and
            # the event lane never consumes write capacity anyway.
            deadline = asyncio.get_running_loop().time() + self.event_patience
            while self._writes_busy >= self.max_writes:
                remaining = deadline - asyncio.get_running_loop().time()
                if remaining <= 0:
                    break
                try:
                    await asyncio.wait_for(self._lane_open.wait(), remaining)
                except asyncio.TimeoutError:
                    break
            await self._event_sem.acquire()
        if lane is not None:
            self.admitted[lane] += 1
        return lane

    def release(self, verb: str, kind: str | None = None) -> None:
        lane = self.lane_of(verb, kind)
        if lane == "read":
            self._read_sem.release()
        elif lane == "write":
            self._write_sem.release()
            self._bump_writes(-1)
        elif lane == "event":
            self._event_sem.release()

    def _bump_writes(self, delta: int) -> None:
        self._writes_busy = max(0, self._writes_busy + delta)
        if self._writes_busy >= self.max_writes:
            self._lane_open.clear()
        else:
            self._lane_open.set()

    @contextlib.asynccontextmanager
    async def slot(self, verb: str, kind: str | None = None):
        lane = await self.acquire(verb, kind)
        try:
            yield lane
        finally:
            self.release(verb, kind)

    def debug_info(self) -> dict:
        return {
            "limits": {"read": self.max_reads, "write": self.max_writes,
                       "event": self.event_lane, "qps": self.max_qps},
            "writes_busy": self._writes_busy,
            "admitted": dict(self.admitted),
        }


class BudgetedClient:
    """A per-replica client facade: the SAME apiserver handle, its own
    FlowControl budget — the in-process equivalent of each manager
    replica carrying its own client-go rate limiter. Sharded deployments
    wrap every replica's kube in one of these so the aggregate request
    budget scales with replica count (that scaling IS the active-active
    throughput win; one event loop gains no CPU from more replicas).

    Rate-limited verbs pass through ``flow``; everything else (watch,
    pod_logs, test conveniences) delegates untouched. ``get_or_none``
    is reimplemented on the wrapped ``get`` so it pays for its read.
    """

    _PACED = ("get", "list", "list_with_rv", "create", "update",
              "patch", "delete")

    def __init__(self, kube, flow: FlowControl):
        self._kube = kube
        self.flow = flow
        for verb in self._PACED:
            if hasattr(kube, verb):
                setattr(self, verb, self._wrap(verb))

    def _wrap(self, verb: str):
        inner = getattr(self._kube, verb)
        lane_verb = verb if verb != "list_with_rv" else "list"

        async def call(*args, **kwargs):
            kind = args[0] if args else kwargs.get("kind")
            async with self.flow.slot(lane_verb, kind):
                return await inner(*args, **kwargs)

        return call

    async def get_or_none(self, kind, name, namespace=None):
        try:
            return await self.get(kind, name, namespace)
        except NotFound:
            return None

    def __getattr__(self, name):
        return getattr(self._kube, name)
