"""Client-side priority & fairness for apiserver traffic.

The server-side APF machinery protects the apiserver from *all* clients;
this is the client protecting itself — and the cluster — from its own
burst shapes (ISSUE 4). Three lanes, each a bounded concurrency pool:

- **read** (get/list): the informer relists and cold-cache fallbacks.
- **write** (create/update/patch/delete of anything but Events): the
  traffic that makes reconciles converge.
- **event**: best-effort Event emission. Low priority by construction —
  an event-lane request defers while the write lane is SATURATED
  (queued-or-in-flight writes ≥ the write limit), so an event flood (a
  cluster-wide slice restart narrating itself) can never starve the CR
  writes that fix it. The deference is bounded (``event_patience``):
  reconciles await their own event emissions inline, so an event must
  never wedge the reconcile issuing the writes — after the patience
  window it proceeds through its own (tiny) lane, which by construction
  never consumes write capacity anyway.

Watches are exempt: they are long-lived streams, and parking one in a
semaphore slot would deadlock the informer machinery the lanes exist to
serve. Both API clients route through this class — ``HttpKube`` on the
wire, ``FakeKube`` in-process — so lane behavior is testable in tier-1.

Limits default from env (documented in docs/operations.md):
``KUBE_CLIENT_MAX_READS``, ``KUBE_CLIENT_MAX_WRITES``,
``KUBE_CLIENT_EVENT_LANE``.
"""

from __future__ import annotations

import asyncio
import contextlib
import os

WRITE_VERBS = frozenset(
    {"create", "update", "update_status", "patch", "delete"})
READ_VERBS = frozenset({"get", "list"})

READS_ENV = "KUBE_CLIENT_MAX_READS"
WRITES_ENV = "KUBE_CLIENT_MAX_WRITES"
EVENTS_ENV = "KUBE_CLIENT_EVENT_LANE"
EVENT_PATIENCE_ENV = "KUBE_CLIENT_EVENT_PATIENCE"

DEFAULT_MAX_READS = 16
DEFAULT_MAX_WRITES = 8
DEFAULT_EVENT_LANE = 1
DEFAULT_EVENT_PATIENCE_SEC = 1.0


def _env_int(name: str, default: int) -> int:
    try:
        return max(1, int(os.environ.get(name, "") or default))
    except ValueError:
        return default


def _env_float(name: str, default: float) -> float:
    try:
        return max(0.0, float(os.environ.get(name, "") or default))
    except ValueError:
        return default


class FlowControl:
    """Bounded per-lane concurrency with a low-priority event lane."""

    def __init__(
        self,
        max_reads: int | None = None,
        max_writes: int | None = None,
        event_lane: int | None = None,
        event_patience: float | None = None,
    ):
        # Explicit 0 is clamped to 1, not silently replaced by the env
        # default — a lane can be narrowed to serial, never to "off".
        self.max_reads = (max(1, max_reads) if max_reads is not None
                          else _env_int(READS_ENV, DEFAULT_MAX_READS))
        self.max_writes = (max(1, max_writes) if max_writes is not None
                           else _env_int(WRITES_ENV, DEFAULT_MAX_WRITES))
        self.event_lane = (max(1, event_lane) if event_lane is not None
                           else _env_int(EVENTS_ENV, DEFAULT_EVENT_LANE))
        self.event_patience = (
            event_patience if event_patience is not None
            else _env_float(EVENT_PATIENCE_ENV, DEFAULT_EVENT_PATIENCE_SEC))
        self._read_sem = asyncio.Semaphore(self.max_reads)
        self._write_sem = asyncio.Semaphore(self.max_writes)
        self._event_sem = asyncio.Semaphore(self.event_lane)
        # Writes queued OR in flight. The event lane defers while this
        # saturates the write limit (set() = lane has spare capacity).
        self._writes_busy = 0
        self._lane_open = asyncio.Event()
        self._lane_open.set()
        self.admitted = {"read": 0, "write": 0, "event": 0}

    @staticmethod
    def lane_of(verb: str, kind: str | None = None) -> str | None:
        if verb in WRITE_VERBS:
            return "event" if kind == "Event" else "write"
        if verb in READ_VERBS:
            return "read"
        return None  # watch / pod_logs: long-lived or out of scope

    async def acquire(self, verb: str, kind: str | None = None) -> str | None:
        lane = self.lane_of(verb, kind)
        if lane == "read":
            await self._read_sem.acquire()
        elif lane == "write":
            self._bump_writes(+1)
            try:
                await self._write_sem.acquire()
            except BaseException:
                self._bump_writes(-1)
                raise
        elif lane == "event":
            # Low priority, bounded: defer while the write lane is
            # saturated (re-check after every wakeup — a new write may
            # have re-closed the gate), but never past the patience
            # window — reconciles await their own emissions inline, and
            # the event lane never consumes write capacity anyway.
            deadline = asyncio.get_running_loop().time() + self.event_patience
            while self._writes_busy >= self.max_writes:
                remaining = deadline - asyncio.get_running_loop().time()
                if remaining <= 0:
                    break
                try:
                    await asyncio.wait_for(self._lane_open.wait(), remaining)
                except asyncio.TimeoutError:
                    break
            await self._event_sem.acquire()
        if lane is not None:
            self.admitted[lane] += 1
        return lane

    def release(self, verb: str, kind: str | None = None) -> None:
        lane = self.lane_of(verb, kind)
        if lane == "read":
            self._read_sem.release()
        elif lane == "write":
            self._write_sem.release()
            self._bump_writes(-1)
        elif lane == "event":
            self._event_sem.release()

    def _bump_writes(self, delta: int) -> None:
        self._writes_busy = max(0, self._writes_busy + delta)
        if self._writes_busy >= self.max_writes:
            self._lane_open.clear()
        else:
            self._lane_open.set()

    @contextlib.asynccontextmanager
    async def slot(self, verb: str, kind: str | None = None):
        lane = await self.acquire(verb, kind)
        try:
            yield lane
        finally:
            self.release(verb, kind)

    def debug_info(self) -> dict:
        return {
            "limits": {"read": self.max_reads, "write": self.max_writes,
                       "event": self.event_lane},
            "writes_busy": self._writes_busy,
            "admitted": dict(self.admitted),
        }
