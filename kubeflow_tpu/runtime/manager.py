"""Controller manager: informers + workqueues + reconciler workers.

The asyncio equivalent of controller-runtime's Manager/Builder:

    mgr = Manager(kube)
    mgr.add_controller(
        Controller("notebook", "Notebook", reconciler.reconcile,
                   owns=["StatefulSet", "Service"],
                   watches=[Watch("Pod", map_fn=pod_to_notebook)]))
    await mgr.start()

``owns=`` maps child events to the controller owner (the reference's
``Owns(&appsv1.StatefulSet{})``); ``watches=`` takes an explicit mapping fn
(the reference's ``handler.EnqueueRequestsFromMapFunc``, e.g. pod events by
``notebook-name`` label, ``notebook_controller.go:739-787``).
"""

from __future__ import annotations

import asyncio
import logging
from dataclasses import dataclass, field
from typing import Awaitable, Callable

from kubeflow_tpu.runtime.informer import OWNER_INDEX, Informer, index_by_owner_uid
from kubeflow_tpu.runtime.metrics import Registry, global_registry
from kubeflow_tpu.runtime.objects import controller_of, name_of, namespace_of
from kubeflow_tpu.runtime.queue import RateLimitedQueue

log = logging.getLogger(__name__)

Key = tuple  # (namespace | None, name)
ReconcileFn = Callable[[Key], Awaitable["Result | None"]]
MapFn = Callable[[dict], list[Key]]


@dataclass(frozen=True)
class Result:
    requeue_after: float | None = None


@dataclass
class Watch:
    kind: str
    map_fn: MapFn
    label_selector: str | dict | None = None


@dataclass
class Controller:
    name: str
    kind: str
    reconcile: ReconcileFn
    owns: list[str] = field(default_factory=list)
    watches: list[Watch] = field(default_factory=list)
    workers: int = 2
    label_selector: str | dict | None = None
    # Event-coalescing window (seconds) for the controller's workqueue: a
    # burst of child events for one key triggers ONE reconcile at window
    # close instead of one per event. 0 disables (see RateLimitedQueue).
    coalesce_window: float = 0.0


class Manager:
    def __init__(self, kube, *, registry: Registry | None = None, namespace: str | None = None):
        self.kube = kube
        self.namespace = namespace
        self.registry = registry or global_registry
        self.controllers: list[Controller] = []
        self.informers: dict[tuple[str, str | None], Informer] = {}
        self._queues: dict[str, RateLimitedQueue] = {}
        self._tasks: list[asyncio.Task] = []
        from kubeflow_tpu.runtime.tracing import Tracer

        # The tracer owns the flight recorder: every reconcile's span tree
        # (queue wait, controller phases, API verbs) is retained after the
        # reconcile ends and served by /debug/traces.
        self.tracer = Tracer(self.registry)
        self._reconcile_total = self.registry.counter(
            "controller_reconcile_total", "Reconciles per controller", ["controller", "result"]
        )
        self._queue_depth = self.registry.gauge(
            "controller_queue_depth", "Workqueue depth", ["controller"]
        )
        self.reconcile_seconds = self.registry.histogram(
            "controller_reconcile_seconds",
            "Reconcile latency per controller",
            ["controller"],
        )

    def informer_for(
        self, kind: str, label_selector: str | dict | None = None
    ) -> Informer:
        key = (kind, str(label_selector) if label_selector else None)
        if key not in self.informers:
            self.informers[key] = Informer(
                self.kube, kind, namespace=self.namespace,
                label_selector=label_selector, registry=self.registry,
            )
        return self.informers[key]

    def add_controller(self, ctrl: Controller) -> None:
        self.controllers.append(ctrl)
        queue = RateLimitedQueue(coalesce_window=ctrl.coalesce_window)
        self._queues[ctrl.name] = queue

        primary = self.informer_for(ctrl.kind, ctrl.label_selector)
        primary.add_handler(lambda _e, obj: queue.add((namespace_of(obj), name_of(obj))))

        def owner_handler(_event: str, obj: dict) -> None:
            ref = controller_of(obj)
            if ref and ref.get("kind") == ctrl.kind:
                queue.add((namespace_of(obj), ref["name"]))

        for child_kind in ctrl.owns:
            child_inf = self.informer_for(child_kind)
            child_inf.add_handler(owner_handler)
            # client-go AddIndexers on every owned kind: reconcilers look
            # children up with by_index(OWNER_INDEX, owner_uid) instead of
            # scanning the cache (or LISTing the apiserver) per reconcile.
            child_inf.add_indexer(OWNER_INDEX, index_by_owner_uid)

        for watch in ctrl.watches:
            inf = self.informer_for(watch.kind, watch.label_selector)

            def mapped_handler(_event: str, obj: dict, _map=watch.map_fn) -> None:
                for key in _map(obj) or []:
                    queue.add(tuple(key))

            inf.add_handler(mapped_handler)

    def enqueue(self, controller_name: str, key) -> None:
        """Externally enqueue a reconcile key (config watchers, tests)."""
        self._queues[controller_name].add(tuple(key))

    def add_background(self, coro_fn) -> None:
        """Register an async task started with the manager (e.g. a mounted
        config-file watcher that re-enqueues objects on change)."""
        self._background_fns = getattr(self, "_background_fns", [])
        self._background_fns.append(coro_fn)

    async def start(self) -> None:
        for informer in self.informers.values():
            await informer.start()
        for fn in getattr(self, "_background_fns", []):
            self._tasks.append(asyncio.create_task(fn(), name="background"))
        for ctrl in self.controllers:
            for i in range(ctrl.workers):
                self._tasks.append(
                    asyncio.create_task(
                        self._worker(ctrl, self._queues[ctrl.name]),
                        name=f"{ctrl.name}-worker-{i}",
                    )
                )

    async def stop(self) -> None:
        for queue in self._queues.values():
            queue.shutdown()
        for task in self._tasks:
            task.cancel()
        for task in self._tasks:
            try:
                await task
            except (asyncio.CancelledError, Exception):
                pass
        for informer in self.informers.values():
            await informer.stop()

    async def wait_idle(self, timeout: float = 10.0, settle: float = 0.05) -> None:
        """Test helper: wait until all queues drain and stay drained."""
        def drained() -> bool:
            return all(
                q.ready_count() == 0 and not q._in_flight
                for q in self._queues.values()
            )

        deadline = asyncio.get_event_loop().time() + timeout
        while asyncio.get_event_loop().time() < deadline:
            if drained():
                await asyncio.sleep(settle)
                if drained():
                    return
            await asyncio.sleep(0.01)
        raise TimeoutError("manager queues did not drain")

    # ---- /debug introspection --------------------------------------------------

    def debug_traces(self, key=None, limit: int = 50) -> list[dict]:
        """Recent flight-recorder entries (most recent first), optionally
        for one reconcile key."""
        return self.tracer.recorder.entries(key=key, limit=limit)

    def debug_queues(self) -> dict:
        """Per-controller workqueue state: depth, in-flight, backoff keys,
        oldest queue wait."""
        return {name: q.debug_info() for name, q in self._queues.items()}

    def debug_informers(self) -> dict:
        """Per-informer cache state: sync, object counts, index hit/miss."""
        out = {}
        for (kind, selector), inf in self.informers.items():
            name = kind if selector is None else f"{kind}[{selector}]"
            out[name] = inf.debug_info()
        return out

    async def _worker(self, ctrl: Controller, queue: RateLimitedQueue) -> None:
        while True:
            key = await queue.get()
            if key is None:
                return
            queue_wait = queue.take_wait(key)
            self._queue_depth.labels(controller=ctrl.name).set(len(queue))
            try:
                with self.tracer.trace(
                    "reconcile", controller=ctrl.name, key=key
                ) as root, self.reconcile_seconds.time(controller=ctrl.name):
                    # The wait happened before any span context existed;
                    # inject it so the trace covers queue→done end to end.
                    root.add_synthetic("queue_wait", queue_wait)
                    result = await ctrl.reconcile(key)
            except Exception:
                log.exception("reconcile %s %s failed", ctrl.name, key)
                self._reconcile_total.labels(controller=ctrl.name, result="error").inc()
                # Record the failure BEFORE done(): if the key went dirty in
                # flight, done() re-queues it with this failure's backoff.
                queue.note_failure(key)
                queue.done(key)
                queue.add(key, queue.backoff_delay(key))
            else:
                queue.forget(key)
                self._reconcile_total.labels(controller=ctrl.name, result="success").inc()
                # done() BEFORE the delayed re-add: adding while the key is
                # still in flight would mark it dirty and done() would then
                # re-add it with no delay — a hot requeue loop.
                queue.done(key)
                if result and result.requeue_after:
                    queue.add(key, result.requeue_after)
            # Fairness: FakeKube awaits are often non-blocking, so guarantee
            # the event loop runs between reconciles even in a hot loop.
            await asyncio.sleep(0)
