"""Controller manager: informers + workqueues + reconciler workers.

The asyncio equivalent of controller-runtime's Manager/Builder:

    mgr = Manager(kube)
    mgr.add_controller(
        Controller("notebook", "Notebook", reconciler.reconcile,
                   owns=["StatefulSet", "Service"],
                   watches=[Watch("Pod", map_fn=pod_to_notebook)]))
    await mgr.start()

``owns=`` maps child events to the controller owner (the reference's
``Owns(&appsv1.StatefulSet{})``); ``watches=`` takes an explicit mapping fn
(the reference's ``handler.EnqueueRequestsFromMapFunc``, e.g. pod events by
``notebook-name`` label, ``notebook_controller.go:739-787``).
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import time
from dataclasses import dataclass, field
from typing import Awaitable, Callable

from kubeflow_tpu.runtime.aiotasks import reap
from kubeflow_tpu.runtime.errors import ApiError, Conflict
from kubeflow_tpu.runtime.events import EventRecorder
from kubeflow_tpu.runtime.informer import OWNER_INDEX, Informer, index_by_owner_uid
from kubeflow_tpu.runtime.metrics import Registry, global_registry
from kubeflow_tpu.runtime.objects import (
    controller_of,
    deep_get,
    get_meta,
    name_of,
    namespace_of,
    now_iso,
)
from kubeflow_tpu.runtime.queue import RateLimitedQueue
from kubeflow_tpu.runtime import slo as slo_mod
from kubeflow_tpu.runtime import timeline as timeline_mod
from kubeflow_tpu.runtime.tracing import span

log = logging.getLogger(__name__)

# Consecutive reconcile failures before a key is dead-lettered
# (poison-pill quarantine, runtime/queue.py). 0 disables.
DEFAULT_QUARANTINE_AFTER = 12
QUARANTINE_AFTER_ENV = "KFTPU_QUARANTINE_AFTER"


def _quarantine_after_from_env(environ=os.environ) -> int:
    raw = environ.get(QUARANTINE_AFTER_ENV)
    try:
        value = int(raw) if raw is not None else DEFAULT_QUARANTINE_AFTER
    except ValueError:
        return DEFAULT_QUARANTINE_AFTER
    return max(0, value)


def _change_token(obj: dict | None) -> str | None:
    """Quarantine release token: a signature of the USER-EDITABLE half of
    the object — everything but ``status``, with resourceVersion masked
    out of metadata. Deliberately not the raw resourceVersion: the
    manager's own Degraded status write bumps rv, and a quarantine that
    released on its own announcement would flap forever. Computed only
    for quarantined keys (rare), never on the hot delta path."""
    if obj is None:
        return None
    body = {k: v for k, v in obj.items() if k not in ("status", "metadata")}
    body["metadata"] = {k: v for k, v in get_meta(obj).items()
                        if k not in ("resourceVersion", "managedFields")}
    return json.dumps(body, sort_keys=True, default=str)

Key = tuple  # (namespace | None, name)
ReconcileFn = Callable[[Key], Awaitable["Result | None"]]
MapFn = Callable[[dict], list[Key]]


@dataclass(frozen=True)
class Result:
    requeue_after: float | None = None


def soonest(*results) -> "Result | None":
    """The Result that reconciles first (smallest positive
    requeue_after); None only when every input is None. Shared by the
    notebook and serving reconcilers — a drain/park grace deadline must
    not be deferred behind a longer periodic requeue (or vice versa)."""
    best = None
    for r in results:
        if r is None or not getattr(r, "requeue_after", 0):
            continue
        if best is None or r.requeue_after < best.requeue_after:
            best = r
    if best is None:
        return next((r for r in results if r is not None), None)
    return best


@dataclass
class Watch:
    kind: str
    map_fn: MapFn
    label_selector: str | dict | None = None


@dataclass
class Controller:
    name: str
    kind: str
    reconcile: ReconcileFn
    owns: list[str] = field(default_factory=list)
    watches: list[Watch] = field(default_factory=list)
    workers: int = 2
    label_selector: str | dict | None = None
    # Event-coalescing window (seconds) for the controller's workqueue: a
    # burst of child events for one key triggers ONE reconcile at window
    # close instead of one per event. 0 disables (see RateLimitedQueue).
    coalesce_window: float = 0.0


class Manager:
    def __init__(self, kube, *, registry: Registry | None = None,
                 namespace: str | None = None,
                 quarantine_after: int | None = None,
                 shard_ring=None):
        self.kube = kube
        self.namespace = namespace
        self.registry = registry or global_registry
        self.controllers: list[Controller] = []
        self.informers: dict[tuple[str, str | None], Informer] = {}
        self._queues: dict[str, RateLimitedQueue] = {}
        self._tasks: list[asyncio.Task] = []
        # Active-active sharding (runtime/sharding.py): when a ring is
        # attached, this replica only caches, enqueues and reconciles
        # keys of the shards it holds. Three fences, outermost first:
        # filtered informers (the field selector below keeps unowned
        # objects out of the cache entirely), handler-side key checks
        # (for events that arrive while ownership shifts), and the
        # dequeue-side re-check in _worker (the last line against
        # processing a key whose shard was lost while it sat queued).
        self.shard_ring = shard_ring
        if shard_ring is not None:
            shard_ring.on_acquire(self._on_shard_acquired)
            shard_ring.on_lose(self._on_shard_lost)
        self._fenced_total = self.registry.counter(
            "controller_shard_fenced_total",
            "Dequeued keys skipped because their shard is not owned",
            ["controller"])
        # Poison-pill quarantine budget (KFTPU_QUARANTINE_AFTER): a key
        # failing this many reconciles in a row is dead-lettered instead
        # of retrying at max backoff forever.
        self.quarantine_after = (
            quarantine_after if quarantine_after is not None
            else _quarantine_after_from_env())
        # ctrl name → its primary informer: the quarantine path reads the
        # object's change token (release signal) and current status
        # (Degraded condition insert) from the cache, not fresh GETs.
        self._primaries: dict[str, Informer] = {}
        self.events = EventRecorder(kube, "controller-manager",
                                    registry=self.registry)
        from kubeflow_tpu.runtime.tracing import Tracer

        # The tracer owns the flight recorder: every reconcile's span tree
        # (queue wait, controller phases, API verbs) is retained after the
        # reconcile ends and served by /debug/traces.
        self.tracer = Tracer(self.registry)
        # SLO engine (runtime/slo.py): the manager owns one and installs
        # it as the process-wide feed target, so scattered producers
        # (scheduler admission wait, drain finalize, serving completions)
        # observe without constructor threading. Serves /debug/slo.
        self.slo = slo_mod.install(slo_mod.SloEngine(self.registry))
        # Durable lifecycle timelines (runtime/timeline.py): journal of
        # per-object lifecycle transitions persisted as a capped CR
        # annotation — survives manager restarts, serves /debug/timeline.
        self.timeline = timeline_mod.TimelineRecorder(kube)
        self._reconcile_total = self.registry.counter(
            "controller_reconcile_total", "Reconciles per controller", ["controller", "result"]
        )
        self._queue_depth = self.registry.gauge(
            "controller_queue_depth", "Workqueue depth", ["controller"]
        )
        self.reconcile_seconds = self.registry.histogram(
            "controller_reconcile_seconds",
            "Reconcile latency per controller",
            ["controller"],
        )
        self._quarantined_gauge = self.registry.gauge(
            "workqueue_quarantined_keys",
            "Keys dead-lettered after exhausting their retry budget",
            ["controller"],
        )

    def _owns(self, key) -> bool:
        return self.shard_ring is None or self.shard_ring.owns_key(key)

    def _shard_filter(self, obj: dict) -> bool:
        """Informer field selector: cache only owned shards' objects.
        Reads LIVE ring state so the filter follows rebalances."""
        return self.shard_ring.owns_namespace(namespace_of(obj))

    def informer_for(
        self, kind: str, label_selector: str | dict | None = None
    ) -> Informer:
        key = (kind, str(label_selector) if label_selector else None)
        if key not in self.informers:
            self.informers[key] = Informer(
                self.kube, kind, namespace=self.namespace,
                label_selector=label_selector,
                field_selector=(self._shard_filter
                                if self.shard_ring is not None else None),
                registry=self.registry,
            )
        return self.informers[key]

    def add_controller(self, ctrl: Controller) -> None:
        self.controllers.append(ctrl)
        queue = RateLimitedQueue(coalesce_window=ctrl.coalesce_window,
                                 quarantine_after=self.quarantine_after)
        self._queues[ctrl.name] = queue

        primary = self.informer_for(ctrl.kind, ctrl.label_selector)
        self._primaries[ctrl.name] = primary

        def primary_handler(event: str, obj: dict) -> None:
            key = (namespace_of(obj), name_of(obj))
            if not self._owns(key):
                return
            if event == "DELETED":
                # Failure-counter hygiene: the backoff/quarantine state
                # dies with the object (an unbounded dict would otherwise
                # leak one entry per ever-failed key). The add still runs
                # so the reconcile observes the deletion and cleans up.
                queue.forget(key)
                queue.add(key)
                self._sync_quarantine_gauge(ctrl.name, queue)
                return
            if not queue.is_quarantined(key):
                queue.add(key)
                return
            # Quarantined key: the delta's change token (metadata+spec
            # signature, computed only here — never on the hot path) is
            # the release signal. A CHANGED object gets a fresh retry
            # budget; same-token re-deliveries (relists, status-only
            # writes) leave the poison pill parked.
            if queue.add(key, token=_change_token(obj)):
                log.info("quarantine released for %s %s: object changed",
                         ctrl.kind, key)
                self._sync_quarantine_gauge(ctrl.name, queue)

        primary.add_handler(primary_handler)

        def owner_handler(_event: str, obj: dict) -> None:
            ref = controller_of(obj)
            if ref and ref.get("kind") == ctrl.kind:
                key = (namespace_of(obj), ref["name"])
                if self._owns(key):
                    queue.add(key)

        for child_kind in ctrl.owns:
            child_inf = self.informer_for(child_kind)
            child_inf.add_handler(owner_handler)
            # client-go AddIndexers on every owned kind: reconcilers look
            # children up with by_index(OWNER_INDEX, owner_uid) instead of
            # scanning the cache (or LISTing the apiserver) per reconcile.
            child_inf.add_indexer(OWNER_INDEX, index_by_owner_uid)

        for watch in ctrl.watches:
            inf = self.informer_for(watch.kind, watch.label_selector)

            def mapped_handler(_event: str, obj: dict, _map=watch.map_fn) -> None:
                for key in _map(obj) or []:
                    if self._owns(tuple(key)):
                        queue.add(tuple(key))

            inf.add_handler(mapped_handler)

    def enqueue(self, controller_name: str, key) -> None:
        """Externally enqueue a reconcile key (config watchers, tests)."""
        self._queues[controller_name].add(tuple(key))

    def add_background(self, coro_fn) -> None:
        """Register an async task started with the manager (e.g. a mounted
        config-file watcher that re-enqueues objects on change)."""
        self._background_fns = getattr(self, "_background_fns", [])
        self._background_fns.append(coro_fn)

    async def start(self) -> None:
        for informer in self.informers.values():
            await informer.start()
        for fn in getattr(self, "_background_fns", []):
            self._tasks.append(asyncio.create_task(fn(), name="background"))
        for ctrl in self.controllers:
            for i in range(ctrl.workers):
                self._tasks.append(
                    asyncio.create_task(
                        self._worker(ctrl, self._queues[ctrl.name]),
                        name=f"{ctrl.name}-worker-{i}",
                    )
                )

    async def stop(self) -> None:
        for queue in self._queues.values():
            queue.shutdown()
        for task in self._tasks:
            task.cancel()
        await reap(*self._tasks)
        for informer in self.informers.values():
            await informer.stop()

    async def wait_idle(self, timeout: float = 10.0, settle: float = 0.05) -> None:
        """Test helper: wait until all queues drain and stay drained."""
        def drained() -> bool:
            return all(
                q.ready_count() == 0 and not q._in_flight
                for q in self._queues.values()
            )

        deadline = asyncio.get_event_loop().time() + timeout
        while asyncio.get_event_loop().time() < deadline:
            if drained():
                await asyncio.sleep(settle)
                if drained():
                    return
            await asyncio.sleep(0.01)
        raise TimeoutError("manager queues did not drain")

    # ---- shard rebalance ---------------------------------------------------------

    def _on_shard_acquired(self, shard: int) -> None:
        """Ring callback (sync): absorb the new shard's keyspace. The
        filtered watches already pass its events (the field selector
        reads live ring state); the refill surfaces every object with no
        event in flight, and the primary handlers enqueue them."""
        self._tasks.append(asyncio.create_task(
            self._absorb_shard(shard), name=f"absorb-shard-{shard}"))

    async def _absorb_shard(self, shard: int) -> None:
        for informer in list(self.informers.values()):
            try:
                added = await informer.refill()
                if added:
                    log.info("shard %d absorb: %s refill surfaced %d "
                             "object(s)", shard, informer.kind, added)
            except Exception:
                log.exception("shard %d absorb refill failed for %s",
                              shard, informer.kind)

    def _on_shard_lost(self, shard: int) -> None:
        """Ring callback (sync): evict the lost shard's keys from every
        workqueue AND informer cache before the new owner can start
        reconciling them. The cache eviction is load-bearing for
        re-acquisition, not just memory hygiene: ``refill()`` is an
        additive relist that only surfaces cache-MISSING objects, so a
        replica that loses and later regains the same shard would
        otherwise refill nothing — its stale cache still holds the
        keyspace whose queued keys the purge below just dropped."""
        from kubeflow_tpu.runtime.sharding import shard_of

        shards = self.shard_ring.shards

        def lost(key) -> bool:
            return shard_of(key[0], shards) == shard

        for name, queue in self._queues.items():
            purged = queue.purge(lost)
            if purged:
                log.info("shard %d lost: purged %d queued key(s) from %s",
                         shard, purged, name)
        for informer in self.informers.values():
            evicted = [key for key in informer.cache if lost(key)]
            for ns, obj_name in evicted:
                informer.evict(obj_name, ns)
            if evicted:
                log.info("shard %d lost: evicted %d cached %s object(s)",
                         shard, len(evicted), informer.kind)

    def debug_sharding(self) -> dict | None:
        """Ring + fence state for /debug — None when unsharded."""
        if self.shard_ring is None:
            return None
        return self.shard_ring.debug_info()

    # ---- poison-pill quarantine ------------------------------------------------

    def _sync_quarantine_gauge(self, name: str, queue: RateLimitedQueue) -> None:
        self._quarantined_gauge.labels(controller=name).set(
            len(queue.quarantined_keys()))

    async def _announce_quarantine(self, ctrl: Controller, key,
                                   queue: RateLimitedQueue,
                                   cached: dict | None) -> None:
        """Surface a quarantine on the object itself: a Degraded status
        condition (what the web apps and kubectl watchers read) and a
        Warning Event. Best-effort — the object may be exactly what's
        broken — and traced, so /debug/traces shows the dead-lettering."""
        ns, name = key
        failures = queue.poison_streak(key)
        # A ROOT trace, not a bare span: the reconcile root that led here
        # already closed (the exception left its `with`), and only root
        # traces reach the flight recorder — the dead-lettering must show
        # up under /debug/traces?key=<ns>/<name>.
        with self.tracer.trace("quarantine", controller=ctrl.name,
                               key=key), \
                span("quarantine", key=f"{ns}/{name}", failures=failures):
            obj = cached
            if obj is None:
                try:
                    obj = await self.kube.get_or_none(ctrl.kind, name, ns)
                except ApiError:
                    obj = None
            if obj is None:
                return
            message = (
                f"reconcile failed {failures} times in a row; reconciliation "
                "suspended until the spec changes or an operator requeues "
                "the key (POST /debug/queue/requeue)")
            condition = {
                "type": "Degraded",
                "status": "True",
                "lastProbeTime": now_iso(),
                "reason": "ReconcileQuarantined",
                "message": message,
            }
            conditions = [condition] + [
                c for c in deep_get(obj, "status", "conditions", default=[])
                if c.get("type") != "Degraded"
            ][:7]
            try:
                await self.kube.patch(
                    ctrl.kind, name, {"status": {"conditions": conditions}},
                    ns, subresource="status")
            except ApiError as exc:
                log.debug("Degraded condition write for %s %s failed "
                          "(the quarantine itself holds; the Event "
                          "below still announces it): %s", ctrl.kind,
                          key, exc)
            await self.events.event(
                obj, "Warning", "ReconcileQuarantined", message)

    def requeue_quarantined(self, controller_name: str, key) -> bool:
        """Manual escape hatch behind POST /debug/queue/requeue: un-park a
        dead-lettered key with a fresh retry budget."""
        queue = self._queues.get(controller_name)
        if queue is None:
            return False
        released = queue.release_quarantined(tuple(key))
        if released:
            log.info("quarantine released for %s %s: manual requeue",
                     controller_name, key)
            self._sync_quarantine_gauge(controller_name, queue)
        return released

    # ---- /debug introspection --------------------------------------------------

    def debug_traces(self, key=None, limit: int = 50) -> list[dict]:
        """Recent flight-recorder entries (most recent first), optionally
        for one reconcile key."""
        return self.tracer.recorder.entries(key=key, limit=limit)

    def debug_queues(self) -> dict:
        """Per-controller workqueue state: depth, in-flight, backoff keys,
        oldest queue wait."""
        return {name: q.debug_info() for name, q in self._queues.items()}

    def debug_timeline(self, key) -> list[dict]:
        """One object's lifecycle timeline (/debug/timeline/<ns>/<name>):
        the recorder's cache merged with the durable annotation read from
        the primary informer — a rebuilt manager serves the journal its
        predecessor persisted."""
        key = tuple(key)
        annotations = None
        informer = self._primaries.get("notebook")
        if informer is not None:
            obj = informer.get(key[1], key[0])
            if obj is not None:
                annotations = (get_meta(obj).get("annotations") or {})
        return timeline_mod.render(
            self.timeline.entries(key, annotations=annotations))

    def debug_informers(self) -> dict:
        """Per-informer cache state: sync, object counts, index hit/miss."""
        out = {}
        for (kind, selector), inf in self.informers.items():
            name = kind if selector is None else f"{kind}[{selector}]"
            out[name] = inf.debug_info()
        return out

    async def _worker(self, ctrl: Controller, queue: RateLimitedQueue) -> None:
        while True:
            key = await queue.get()
            if key is None:
                return
            if not self._owns(key):
                # Shard fence: ownership moved while the key sat queued.
                # Drop it — the new owner's absorb refill re-discovers it
                # — and drop its failure state with it (the streak belongs
                # to the keyspace's new owner now, starting fresh).
                queue.forget(key)
                queue.done(key)
                self._fenced_total.labels(controller=ctrl.name).inc()
                await asyncio.sleep(0)
                continue
            queue_wait = queue.take_wait(key)
            self._queue_depth.labels(controller=ctrl.name).set(len(queue))
            t0 = time.perf_counter()
            trace_id = None
            try:
                with self.tracer.trace(
                    "reconcile", controller=ctrl.name, key=key
                ) as root, self.reconcile_seconds.time(controller=ctrl.name):
                    # The wait happened before any span context existed;
                    # inject it so the trace covers queue→done end to end.
                    root.add_synthetic("queue_wait", queue_wait)
                    trace_id = root.trace_id
                    result = await ctrl.reconcile(key)
            except Exception as exc:
                log.exception("reconcile %s %s failed", ctrl.name, key)
                self._reconcile_total.labels(controller=ctrl.name, result="error").inc()
                # Record the failure BEFORE done(): if the key went dirty in
                # flight, done() re-queues it with this failure's backoff.
                # Conflicts are optimistic-concurrency noise (a stale read
                # racing another writer), not poison — they back off but
                # never advance the quarantine streak: a 409 storm
                # self-heals the moment it lifts, and quarantining healthy
                # keys through an apiserver incident would strand them
                # until a spec edit.
                queue.note_failure(key,
                                   poisonous=not isinstance(exc, Conflict))
                went_dirty = queue.done(key)
                if queue.should_quarantine(key) and not went_dirty:
                    # A dirty key means the object changed WHILE this
                    # (stale) attempt was failing — quarantining now would
                    # record the edited object's token and park the user's
                    # fix unseen. Let the dirty re-add run; a truly
                    # poisoned key fails that attempt too and quarantines
                    # on the next non-dirty cycle.
                    # Poison pill: the key exhausted its consecutive-
                    # failure budget — park it in the dead-letter set
                    # instead of retrying at max backoff forever, and say
                    # so on the object (Degraded condition + Warning
                    # Event). A spec change (new informer delta rv) or
                    # POST /debug/queue/requeue releases it.
                    cached = self._primaries[ctrl.name].get(key[1], key[0])
                    queue.quarantine(key, token=_change_token(cached))
                    self._sync_quarantine_gauge(ctrl.name, queue)
                    await self._announce_quarantine(ctrl, key, queue, cached)
                else:
                    queue.add(key, queue.backoff_delay(key))
            else:
                queue.forget(key)
                self._reconcile_total.labels(controller=ctrl.name, result="success").inc()
                # done() BEFORE the delayed re-add: adding while the key is
                # still in flight would mark it dirty and done() would then
                # re-add it with no delay — a hot requeue loop.
                queue.done(key)
                if result and result.requeue_after:
                    queue.add(key, result.requeue_after)
            # Reconcile-latency SLI: the histogram above is the raw
            # signal; this is the same number scored against the
            # objective (success and failure alike — a failing reconcile
            # still spent the operator's latency budget).
            self.slo.observe("reconcile_latency",
                             time.perf_counter() - t0, key=key,
                             trace_id=trace_id)
            # Fairness: FakeKube awaits are often non-blocking, so guarantee
            # the event loop runs between reconciles even in a hot loop.
            await asyncio.sleep(0)
