"""List/watch informer with a local cache and secondary indexes.

Mirrors client-go's shared informer: an initial list primes the cache, a watch
streams deltas, and registered handlers receive (event, obj). On watch failure
the informer relists (resync-on-error), which is all the reference stack needs
(controller-runtime does the same under the hood).

Indexes follow client-go's ``AddIndexers`` semantics: an index function maps
an object to zero or more hashable values, the informer maintains the inverted
value → keys mapping incrementally on every watch delta (and rebuilds it on
relist), and ``by_index(name, value)`` answers in O(matches) instead of the
O(cache) linear scan every per-event lookup used to pay.
"""

from __future__ import annotations

import asyncio
import logging
import random
import time
import zlib
from typing import Awaitable, Callable, Hashable

from kubeflow_tpu.runtime.aiotasks import reap
from kubeflow_tpu.runtime.objects import (
    controller_of,
    get_meta,
    key_of,
    name_of,
    namespace_of,
)

log = logging.getLogger(__name__)

Handler = Callable[[str, dict], None]
IndexFn = Callable[[dict], list[Hashable]]

# ---- built-in index functions (the client-go "namespace" indexer and the
# two shapes every controller here needs: owner UID and a label's value) ----

OWNER_INDEX = "owner"
NAMESPACE_INDEX = "namespace"


def index_by_owner_uid(obj: dict) -> list[Hashable]:
    """Index children under their controller owner's UID (unique
    cluster-wide, so the value needs no namespace qualifier)."""
    ref = controller_of(obj)
    return [ref["uid"]] if ref and ref.get("uid") else []


def index_by_namespace(obj: dict) -> list[Hashable]:
    return [namespace_of(obj)]


def index_by_label(label: str) -> IndexFn:
    """Index by a label's value, namespace-qualified: values are
    ``(namespace, label_value)`` because label values (unlike UIDs) only
    identify an object within its namespace."""

    def fn(obj: dict) -> list[Hashable]:
        value = (get_meta(obj).get("labels") or {}).get(label)
        return [(namespace_of(obj), value)] if value is not None else []

    return fn


class Informer:
    def __init__(
        self,
        kube,
        kind: str,
        namespace: str | None = None,
        label_selector: str | dict | None = None,
        field_selector=None,
        resync_backoff: float = 1.0,
        resync_backoff_max: float = 30.0,
        registry=None,
    ):
        self.kube = kube
        self.kind = kind
        self.namespace = namespace
        self.label_selector = label_selector
        # Sharded (filtered) informer: a predicate threaded into every
        # list AND watch — the client only ever sees its slice of the
        # keyspace. A predicate reading live state (ShardRing ownership)
        # makes the filter follow rebalances without informer restarts;
        # refill() closes the gap for objects with no event in flight.
        self.field_selector = field_selector
        # Relist storm control: ``resync_backoff`` is the BASE delay (a
        # cleanly-closed watch relists after it); consecutive list/watch
        # FAILURES escalate exponentially toward ``resync_backoff_max``
        # with jitter, and any successful list resets the streak — a
        # flapping apiserver sees a decorrelated trickle of LISTs, not a
        # fixed-cadence hammer from every informer at once.
        self.resync_backoff = resync_backoff
        self.resync_backoff_max = resync_backoff_max
        self._consecutive_failures = 0
        self._last_sync: float | None = None   # monotonic of last good list
        self._current_backoff = resync_backoff
        # Deterministic per-informer jitter stream — crc32, not hash():
        # built-in str hashing is salted per process (PYTHONHASHSEED),
        # which would make a chaos-soak seed irreproducible across runs.
        self._jitter_rng = random.Random(zlib.crc32(
            f"{kind}/{namespace}/{label_selector}".encode()))
        self.cache: dict[tuple[str | None, str], dict] = {}
        self._handlers: list[Handler] = []
        self._task: asyncio.Task | None = None
        self._synced = asyncio.Event()
        # name → index fn; name → value → set of cache keys; key → the
        # values it currently occupies per index (so a MODIFIED delta can
        # leave its old buckets without re-deriving them from a stale obj).
        self._index_fns: dict[str, IndexFn] = {}
        self._indexes: dict[str, dict[Hashable, set]] = {}
        self._indexed_values: dict[str, dict[tuple, list[Hashable]]] = {}
        # Per-index [hits, misses] — the registry metric aggregates the
        # same numbers fleet-wide; these local counters feed the
        # per-informer /debug/informers view without a registry scrape.
        self._index_stats: dict[str, list[int]] = {}
        self._relists = 0
        self._refills = 0
        self._lookups = (
            registry.counter(
                "informer_index_lookups_total",
                "Secondary-index lookups per informer",
                ["kind", "index", "result"],
            )
            if registry is not None
            else None
        )
        self._relists_total = (
            registry.counter(
                "informer_relists_total",
                "List attempts per informer (first sync + every relist)",
                ["kind"],
            )
            if registry is not None
            else None
        )
        self._sync_age = (
            registry.gauge(
                "informer_last_sync_age_seconds",
                "Seconds since the informer's last successful list "
                "(refreshed on sync and on every /debug/informers read)",
                ["kind"],
            )
            if registry is not None
            else None
        )

    # ---- indexes ---------------------------------------------------------------

    def add_indexer(self, name: str, fn: IndexFn) -> None:
        """Register a secondary index (idempotent per name, client-go
        AddIndexers). Safe after start: existing cache entries are indexed
        on the spot."""
        if name in self._index_fns:
            return
        self._index_fns[name] = fn
        self._indexes[name] = {}
        self._indexed_values[name] = {}
        for key, obj in self.cache.items():
            self._index_one(name, key, obj)

    def has_indexer(self, name: str) -> bool:
        return name in self._index_fns

    def by_index(self, name: str, value: Hashable) -> list[dict]:
        """Objects whose index fn emitted ``value`` — O(matches)."""
        keys = self._indexes[name].get(value)  # KeyError for unknown index
        stats = self._index_stats.setdefault(name, [0, 0])
        stats[0 if keys else 1] += 1
        if self._lookups is not None:
            self._lookups.labels(
                kind=self.kind, index=name, result="hit" if keys else "miss"
            ).inc()
        return [self.cache[k] for k in keys or () if k in self.cache]

    def _index_one(self, name: str, key: tuple, obj: dict) -> None:
        try:
            values = list(self._index_fns[name](obj))
        except Exception:
            log.exception("index %s failed for %s %s", name, self.kind, key)
            values = []
        self._indexed_values[name][key] = values
        for value in values:
            self._indexes[name].setdefault(value, set()).add(key)

    def _unindex_one(self, name: str, key: tuple) -> None:
        for value in self._indexed_values[name].pop(key, ()):
            bucket = self._indexes[name].get(value)
            if bucket is not None:
                bucket.discard(key)
                if not bucket:
                    del self._indexes[name][value]

    def _apply_delta(self, event: str, key: tuple, obj: dict) -> None:
        """Single cache+index writer for watch deltas and relist diffs —
        indexes can never drift from the cache because every mutation
        funnels through here."""
        for name in self._index_fns:
            self._unindex_one(name, key)
        if event == "DELETED":
            self.cache.pop(key, None)
        else:
            self.cache[key] = obj
            for name in self._index_fns:
                self._index_one(name, key, obj)

    # ---- handlers / lifecycle --------------------------------------------------

    def add_handler(self, fn: Handler) -> None:
        self._handlers.append(fn)

    def get(self, name: str, namespace: str | None = None) -> dict | None:
        return self.cache.get((namespace, name))

    def evict(self, name: str, namespace: str | None = None) -> None:
        """Drop one entry from the cache AND every index (controllers that
        must not trust a possibly-stale read — e.g. after deleting the
        object — use this instead of poking ``cache`` directly, which
        would strand index entries). The watch repopulates it if the
        object still exists."""
        key = (namespace, name)
        if key in self.cache:
            self._apply_delta("DELETED", key, self.cache[key])

    def items(self) -> list[dict]:
        return list(self.cache.values())

    def _selector_kwargs(self) -> dict:
        # Built conditionally so clients without filtered-watch support
        # (HttpKube today) keep their unchanged call signature.
        return ({"field_selector": self.field_selector}
                if self.field_selector is not None else {})

    def _admit(self, obj: dict) -> bool:
        """Live re-check of a callable field selector at CACHE-APPLY time.
        List snapshots and queued watch events cross awaits; with a
        shard-filter selector the ownership they were filtered under can
        be stale by the time they land — applying a pre-loss snapshot
        would re-cache a foreign object, and refill() (cache-miss based)
        would then never re-surface it on a later regain."""
        fs = self.field_selector
        return not callable(fs) or fs(obj)

    async def refill(self) -> int:
        """Additive relist: list under the CURRENT field selector and
        dispatch ADDED for keys missing from the cache. Never deletes —
        a list snapshot racing the live watch must not retract objects
        the watch already delivered. This is the shard-absorption path:
        after a replica acquires a shard, refill() surfaces every object
        of the new keyspace that has no organic event in flight, and the
        primary handlers enqueue them."""
        objs, _rv = await self.kube.list_with_rv(
            self.kind, self.namespace, self.label_selector,
            **self._selector_kwargs())
        self._refills += 1
        added = 0
        for obj in objs:
            if not self._admit(obj):
                continue  # shard lost while the list was in flight
            key = key_of(obj)
            if key in self.cache:
                continue
            self._apply_delta("ADDED", key, obj)
            self._dispatch("ADDED", obj)
            added += 1
        return added

    def debug_info(self) -> dict:
        """JSON-shaped snapshot for the /debug/informers endpoint."""
        sync_age = (
            round(time.monotonic() - self._last_sync, 3)
            if self._last_sync is not None else None
        )
        if self._sync_age is not None and sync_age is not None:
            # /debug reads double as the gauge refresh (a plain gauge
            # can't age itself between scrapes).
            self._sync_age.labels(kind=self.kind).set(sync_age)
        return {
            "kind": self.kind,
            "namespace": self.namespace,
            "label_selector": (
                str(self.label_selector) if self.label_selector else None
            ),
            "synced": self._synced.is_set(),
            "filtered": self.field_selector is not None,
            "objects": len(self.cache),
            "relists": self._relists,
            "refills": self._refills,
            # Storm-control state: a flapping watch shows up as a failure
            # streak + growing backoff + an aging last sync, instead of a
            # fixed-cadence LIST hammer.
            "consecutive_failures": self._consecutive_failures,
            "current_backoff_sec": round(self._current_backoff, 3),
            "last_sync_age_sec": sync_age,
            "indexes": {
                name: {
                    "values": len(self._indexes.get(name, {})),
                    "hits": self._index_stats.get(name, [0, 0])[0],
                    "misses": self._index_stats.get(name, [0, 0])[1],
                }
                for name in self._index_fns
            },
        }

    async def start(self) -> None:
        self._task = asyncio.create_task(self._run(), name=f"informer-{self.kind}")
        await self._synced.wait()

    async def stop(self) -> None:
        if self._task:
            self._task.cancel()
            await reap(self._task)

    def _dispatch(self, event: str, obj: dict) -> None:
        for fn in self._handlers:
            try:
                fn(event, obj)
            except Exception:
                log.exception("informer handler failed for %s %s", self.kind, key_of(obj))

    async def _run(self) -> None:
        while True:
            try:
                # kftpu: ignore[await-race] the single _run task is this counter's only writer; debug_info only reads it
                self._relists += 1
                if self._relists_total is not None:
                    self._relists_total.labels(kind=self.kind).inc()
                refills_at_list = self._refills
                objs, rv = await self.kube.list_with_rv(
                    self.kind, self.namespace, self.label_selector,
                    **self._selector_kwargs()
                )
                # A successful list resets the failure streak — backoff
                # escalation is for CONSECUTIVE failures only.
                # kftpu: ignore[await-race] the single _run task is this attr's only writer; debug_info only reads it
                self._consecutive_failures = 0
                self._current_backoff = self.resync_backoff
                self._last_sync = time.monotonic()
                if self._sync_age is not None:
                    self._sync_age.labels(kind=self.kind).set(0.0)
                fresh = {key_of(o): o for o in objs if self._admit(o)}
                # The deletion sweep trusts the snapshot's completeness;
                # a refill() that interleaved with the list (shard
                # absorbed mid-await) added keys the stale snapshot never
                # saw — sweeping now would evict them with no event ever
                # coming back. Skip one round; the next relist re-syncs.
                if self._refills == refills_at_list:
                    for key, obj in list(self.cache.items()):
                        if key not in fresh:
                            self._apply_delta("DELETED", key, obj)
                            self._dispatch("DELETED", obj)
                for key, obj in fresh.items():
                    existed = key in self.cache
                    self._apply_delta("MODIFIED" if existed else "ADDED", key, obj)
                    self._dispatch("MODIFIED" if existed else "ADDED", obj)
                self._synced.set()
                # resource_version threads the list's snapshot into the watch
                # so deletes between list and watch are never missed; a 410
                # Gone (or any error) falls through to a relist.
                async for event, obj in self.kube.watch(
                    self.kind,
                    self.namespace,
                    self.label_selector,
                    send_initial=False,
                    resource_version=rv,
                    **self._selector_kwargs(),
                ):
                    if event != "DELETED" and not self._admit(obj):
                        continue  # ownership moved while the event queued
                    self._apply_delta(event, (namespace_of(obj), name_of(obj)), obj)
                    self._dispatch(event, obj)
                # Watch closed cleanly → relist after the base backoff,
                # jittered DOWN like the failure path: an apiserver restart
                # closes every informer's watch in the same instant, and a
                # clean close must not relist in lockstep either.
                delay = self.resync_backoff * \
                    (1.0 - 0.25 * self._jitter_rng.random())
            except asyncio.CancelledError:
                raise
            except Exception:
                self._consecutive_failures += 1
                delay = min(
                    self.resync_backoff * (2 ** (self._consecutive_failures - 1)),
                    self.resync_backoff_max,
                )
                # Jitter decorrelates the relist herd: every informer of a
                # restarting apiserver would otherwise LIST in lockstep.
                # Jittered DOWNWARD so the configured ceiling is a real
                # ceiling (additive jitter would overshoot it by 25%).
                delay *= 1.0 - 0.25 * self._jitter_rng.random()
                self._current_backoff = delay
                log.exception(
                    "informer %s list/watch failed (%d in a row); relist "
                    "in %.2fs", self.kind, self._consecutive_failures, delay)
            await asyncio.sleep(delay)
