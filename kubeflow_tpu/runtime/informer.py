"""List/watch informer with a local cache.

Mirrors client-go's shared informer: an initial list primes the cache, a watch
streams deltas, and registered handlers receive (event, obj). On watch failure
the informer relists (resync-on-error), which is all the reference stack needs
(controller-runtime does the same under the hood).
"""

from __future__ import annotations

import asyncio
import logging
from typing import Awaitable, Callable

from kubeflow_tpu.runtime.objects import key_of, name_of, namespace_of

log = logging.getLogger(__name__)

Handler = Callable[[str, dict], None]


class Informer:
    def __init__(
        self,
        kube,
        kind: str,
        namespace: str | None = None,
        label_selector: str | dict | None = None,
        resync_backoff: float = 1.0,
    ):
        self.kube = kube
        self.kind = kind
        self.namespace = namespace
        self.label_selector = label_selector
        self.resync_backoff = resync_backoff
        self.cache: dict[tuple[str | None, str], dict] = {}
        self._handlers: list[Handler] = []
        self._task: asyncio.Task | None = None
        self._synced = asyncio.Event()

    def add_handler(self, fn: Handler) -> None:
        self._handlers.append(fn)

    def get(self, name: str, namespace: str | None = None) -> dict | None:
        return self.cache.get((namespace, name))

    def items(self) -> list[dict]:
        return list(self.cache.values())

    async def start(self) -> None:
        self._task = asyncio.create_task(self._run(), name=f"informer-{self.kind}")
        await self._synced.wait()

    async def stop(self) -> None:
        if self._task:
            self._task.cancel()
            try:
                await self._task
            except (asyncio.CancelledError, Exception):
                pass

    def _dispatch(self, event: str, obj: dict) -> None:
        for fn in self._handlers:
            try:
                fn(event, obj)
            except Exception:
                log.exception("informer handler failed for %s %s", self.kind, key_of(obj))

    async def _run(self) -> None:
        while True:
            try:
                objs, rv = await self.kube.list_with_rv(
                    self.kind, self.namespace, self.label_selector
                )
                fresh = {key_of(o): o for o in objs}
                for key, obj in list(self.cache.items()):
                    if key not in fresh:
                        del self.cache[key]
                        self._dispatch("DELETED", obj)
                for key, obj in fresh.items():
                    existed = key in self.cache
                    self.cache[key] = obj
                    self._dispatch("MODIFIED" if existed else "ADDED", obj)
                self._synced.set()
                # resource_version threads the list's snapshot into the watch
                # so deletes between list and watch are never missed; a 410
                # Gone (or any error) falls through to a relist.
                async for event, obj in self.kube.watch(
                    self.kind,
                    self.namespace,
                    self.label_selector,
                    send_initial=False,
                    resource_version=rv,
                ):
                    key = (namespace_of(obj), name_of(obj))
                    if event == "DELETED":
                        self.cache.pop(key, None)
                    else:
                        self.cache[key] = obj
                    self._dispatch(event, obj)
                # watch closed cleanly → relist
            except asyncio.CancelledError:
                raise
            except Exception:
                log.exception("informer %s list/watch failed; relisting", self.kind)
            await asyncio.sleep(self.resync_backoff)
