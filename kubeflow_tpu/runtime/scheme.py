"""Kind registry: apiVersion/kind ⇄ REST path mapping.

Equivalent of the Go scheme + RESTMapper. Kinds used by the stack are
registered up front; CRDs register alongside built-ins (our CRDs live in the
``kubeflow.org`` group like the reference's, see e.g.
``notebook-controller/api/v1/notebook_types.go``).
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass(frozen=True)
class GVK:
    group: str
    version: str
    kind: str
    plural: str
    namespaced: bool = True

    @property
    def api_version(self) -> str:
        return f"{self.group}/{self.version}" if self.group else self.version

    @property
    def key(self) -> str:
        """Stable storage/lookup key, version-independent (like a GR)."""
        return f"{self.plural}.{self.group}" if self.group else self.plural

    def rest_base(self, namespace: str | None) -> str:
        root = f"/apis/{self.group}/{self.version}" if self.group else f"/api/{self.version}"
        if self.namespaced and namespace:
            return f"{root}/namespaces/{namespace}/{self.plural}"
        return f"{root}/{self.plural}"


class Scheme:
    def __init__(self) -> None:
        self._by_kind: dict[str, GVK] = {}
        self._by_key: dict[str, GVK] = {}

    def register(self, gvk: GVK) -> GVK:
        # Last registration wins per kind name; CRD versions share storage.
        self._by_kind[gvk.kind] = gvk
        self._by_key[gvk.key] = gvk
        return gvk

    def by_kind(self, kind: str) -> GVK:
        try:
            return self._by_kind[kind]
        except KeyError:
            raise KeyError(f"kind {kind!r} not registered in scheme") from None

    def gvk_of(self, obj: dict) -> GVK:
        return self.by_kind(obj["kind"])

    def kinds(self) -> list[GVK]:
        return list(self._by_kind.values())


DEFAULT_SCHEME = Scheme()

_CORE = [
    GVK("", "v1", "Pod", "pods"),
    GVK("", "v1", "Service", "services"),
    GVK("", "v1", "Namespace", "namespaces", namespaced=False),
    GVK("", "v1", "ServiceAccount", "serviceaccounts"),
    GVK("", "v1", "ConfigMap", "configmaps"),
    GVK("", "v1", "Secret", "secrets"),
    GVK("", "v1", "Event", "events"),
    GVK("", "v1", "PersistentVolumeClaim", "persistentvolumeclaims"),
    GVK("", "v1", "ResourceQuota", "resourcequotas"),
    GVK("", "v1", "Node", "nodes", namespaced=False),
    GVK("", "v1", "PodTemplate", "podtemplates"),
    GVK("autoscaling.x-k8s.io", "v1beta1", "ProvisioningRequest",
        "provisioningrequests"),
    GVK("apps", "v1", "StatefulSet", "statefulsets"),
    GVK("apps", "v1", "Deployment", "deployments"),
    GVK("rbac.authorization.k8s.io", "v1", "Role", "roles"),
    GVK("rbac.authorization.k8s.io", "v1", "RoleBinding", "rolebindings"),
    GVK("rbac.authorization.k8s.io", "v1", "ClusterRole", "clusterroles", namespaced=False),
    GVK("networking.k8s.io", "v1", "NetworkPolicy", "networkpolicies"),
    GVK("storage.k8s.io", "v1", "StorageClass", "storageclasses", namespaced=False),
    GVK("coordination.k8s.io", "v1", "Lease", "leases"),
    GVK("authorization.k8s.io", "v1", "SubjectAccessReview", "subjectaccessreviews", namespaced=False),
    # Istio (used when the mesh is enabled, mirroring the reference's USE_ISTIO)
    GVK("networking.istio.io", "v1beta1", "VirtualService", "virtualservices"),
    GVK("security.istio.io", "v1beta1", "AuthorizationPolicy", "authorizationpolicies"),
    # Our CRDs (kubeflow.org group for drop-in familiarity)
    GVK("kubeflow.org", "v1", "Notebook", "notebooks"),
    GVK("kubeflow.org", "v1", "InferenceService", "inferenceservices"),
    GVK("kubeflow.org", "v1", "Profile", "profiles", namespaced=False),
    GVK("kubeflow.org", "v1alpha1", "PodDefault", "poddefaults"),
    GVK("tensorboard.kubeflow.org", "v1alpha1", "Tensorboard", "tensorboards"),
    GVK("kubeflow.org", "v1alpha1", "PVCViewer", "pvcviewers"),
]

for _gvk in _CORE:
    DEFAULT_SCHEME.register(_gvk)
