"""Create-or-update helpers with drift detection.

Port-in-spirit of the reference's ``components/common/reconcilehelper/util.go``
(:18-219): each helper fetches the live object, creates it if absent, and
otherwise copies only the fields the controller owns — preserving
cluster-managed fields (Service clusterIP, statuses) so reconciles converge
instead of fighting the apiserver.
"""

from __future__ import annotations

import asyncio
import hashlib
import json
import logging
import os
from collections import OrderedDict
from dataclasses import dataclass

from kubeflow_tpu.runtime.errors import AlreadyExists, Conflict, NotFound
from kubeflow_tpu.runtime.metrics import global_registry
from kubeflow_tpu.runtime.tracing import span
from kubeflow_tpu.runtime.objects import (
    deep_get,
    deepcopy,
    get_meta,
    name_of,
    namespace_of,
    set_controller_owner,
)

log = logging.getLogger(__name__)

# Write-elision telemetry (bench reports these): "hash" means the
# last-applied cache short-circuited before even diffing; "diff" means the
# copier compared and found no drift. Either way zero API writes happened.
M_ELIDED = global_registry.counter(
    "apply_writes_elided_total",
    "Child reconciles that issued no API write",
    ["kind", "via"],
)


def state_hash(obj) -> str:
    """Stable content hash of a JSON-shaped object (dict key order and
    whitespace don't matter; list order does — k8s list order is
    semantic, matching subset_equal below)."""
    return hashlib.sha1(
        json.dumps(obj, sort_keys=True, separators=(",", ":"),
                   default=str).encode()
    ).hexdigest()


class ApplyCache:
    """Per-key last-applied memory: (kind, ns, name) → (desired-state
    hash, live resourceVersion at last convergence). A reconcile whose
    desired state hashes the same while the live object's rv is unchanged
    is provably a no-op — skip the diff entirely. Any external change
    bumps the rv and falls through to the copier, so drift repair is
    untouched; a desired-state change misses on the hash.

    LRU-bounded: deletion paths (owner cascade, GC) don't flow through
    here, so without a bound the cache would grow with *historical*
    object count under create/delete churn. Eviction only costs a diff
    on the next reconcile of that key — never correctness."""

    def __init__(self, max_entries: int = 4096):
        self.max_entries = max_entries
        self._entries: "OrderedDict[tuple, tuple[str, str | None]]" = \
            OrderedDict()

    @staticmethod
    def key_of(desired: dict) -> tuple:
        return (desired.get("kind"), namespace_of(desired), name_of(desired))

    def unchanged(self, key: tuple, desired_hash: str, live_rv) -> bool:
        entry = self._entries.get(key)
        if entry is None or entry != (desired_hash, live_rv):
            return False
        self._entries.move_to_end(key)
        return True

    def record(self, key: tuple, desired_hash: str, live_rv) -> None:
        self._entries[key] = (desired_hash, live_rv)
        self._entries.move_to_end(key)
        while len(self._entries) > self.max_entries:
            self._entries.popitem(last=False)

    def forget(self, key: tuple) -> None:
        self._entries.pop(key, None)


def informer_reader(informers: dict):
    """A ``reconcile_child`` reader over a kind → informer mapping (the
    shape every reconciler wires in its setup fn). The dict is read live,
    so setup may populate it after constructing the reader."""

    def reader(kind: str, name: str, namespace: str | None) -> dict | None:
        inf = informers.get(kind)
        return inf.get(name, namespace) if inf is not None else None

    return reader


def subset_equal(want, have) -> bool:
    """True when every field the controller sets is already present in the
    live object. The live object legitimately has MORE fields (apiserver
    defaulting: Service ipFamilies/sessionAffinity, pod restartPolicy/
    dnsPolicy, ...); comparing whole subtrees with ``==`` would see permanent
    drift and update in a hot loop against a real cluster. Trade-off: a field
    the controller *removes* from its desired state is not reverted — owned
    objects are regenerated wholesale on spec changes, so this doesn't bite.
    Lists compare element-wise (k8s list order is semantic)."""
    if isinstance(want, dict) and isinstance(have, dict):
        return all(k in have and subset_equal(v, have[k]) for k, v in want.items())
    if isinstance(want, list) and isinstance(have, list):
        return len(want) == len(have) and all(
            subset_equal(w, h) for w, h in zip(want, have)
        )
    return want == have


def copy_statefulset_fields(desired: dict, live: dict) -> bool:
    """Reference: CopyStatefulSetFields (util.go:57-86) — labels, annotations,
    replicas, template; returns True when an update is required."""
    changed = _copy_meta(desired, live)
    for path in (("spec", "replicas"), ("spec", "template")):
        changed |= _copy_path(desired, live, path)
    return changed


copy_deployment_fields = copy_statefulset_fields  # identical owned-field set


def copy_service_fields(desired: dict, live: dict) -> bool:
    """Reference: CopyServiceFields (util.go:118-145) — preserves clusterIP.

    The live clusterIP is folded into the desired spec *before* comparison so
    repeated reconciles converge instead of updating forever.
    """
    changed = _copy_meta(desired, live)
    want = deepcopy(deep_get(desired, "spec") or {})
    cluster_ip = deep_get(live, "spec", "clusterIP")
    if cluster_ip is not None and "clusterIP" not in want:
        want["clusterIP"] = cluster_ip
    if not subset_equal(want, deep_get(live, "spec") or {}):
        live["spec"] = want
        changed = True
    return changed


def copy_virtual_service(desired: dict, live: dict) -> bool:
    changed = _copy_meta(desired, live)
    changed |= _copy_path(desired, live, ("spec",))
    return changed


def copy_spec(desired: dict, live: dict) -> bool:
    changed = _copy_meta(desired, live)
    changed |= _copy_path(desired, live, ("spec",))
    return changed


def _copy_meta(desired: dict, live: dict) -> bool:
    """Fold desired labels/annotations into the live ones (other actors may
    legitimately add their own; only ours must be present and equal)."""
    changed = False
    for field in ("labels", "annotations"):
        want = get_meta(desired).get(field)
        have = get_meta(live).get(field) or {}
        if want is not None and not subset_equal(want, have):
            get_meta(live)[field] = {**have, **deepcopy(want)}
            changed = True
    return changed


def _copy_path(desired: dict, live: dict, path: tuple[str, ...]) -> bool:
    want = deep_get(desired, *path)
    have = deep_get(live, *path)
    if want is None or subset_equal(want, have):
        return False
    cur = live
    for part in path[:-1]:
        cur = cur.setdefault(part, {})
    cur[path[-1]] = deepcopy(want)
    return True


def copy_rolebinding_fields(desired: dict, live: dict) -> bool:
    """RoleBindings have no spec: the owned payload is subjects (+ roleRef).
    Note roleRef is immutable on a real apiserver — our bindings derive the
    role from the binding *name*, so a roleRef change implies a new name
    (delete + create), never an in-place update."""
    changed = _copy_meta(desired, live)
    for field in ("subjects", "roleRef"):
        want = desired.get(field)
        if want is not None and not subset_equal(want, live.get(field)):
            live[field] = deepcopy(want)
            changed = True
    return changed


COPIERS = {
    "StatefulSet": copy_statefulset_fields,
    "Deployment": copy_deployment_fields,
    "Service": copy_service_fields,
    "VirtualService": copy_virtual_service,
    "RoleBinding": copy_rolebinding_fields,
}


async def reconcile_child(
    kube, desired: dict, *, copier=None, cache: ApplyCache | None = None,
    reader=None,
) -> tuple[dict, bool]:
    """Ensure ``desired`` exists and owned fields match.

    Returns ``(live_object, created)`` — callers that count creations (e.g.
    the notebook_create_total metric) use the flag instead of a second
    read-before-write. The per-kind copier defaults from COPIERS; unknown
    kinds copy the whole spec. Conflict → raise (the workqueue retries with
    backoff, matching the reference's requeue-on-conflict behavior).

    ``reader(kind, name, namespace) -> dict | None`` reads the live object
    from a watch cache (informer) instead of a per-reconcile apiserver GET
    — a None return (cold cache) falls back to the GET, so correctness
    never depends on cache warmth. A stale cached rv at worst produces a
    Conflict the workqueue retries, same as any writer race.

    ``cache`` (ApplyCache) elides the whole diff when the desired-state
    hash AND the live rv match the last convergence — the steady-state
    reconcile touches neither the apiserver nor the copier.
    """
    kind = desired["kind"]
    copier = copier or COPIERS.get(kind, copy_spec)
    name, namespace = name_of(desired), namespace_of(desired)
    ckey = ApplyCache.key_of(desired) if cache is not None else None
    dh = state_hash(desired) if cache is not None else None

    with span("apply_child", kind=kind, name=name) as sp:
        live = reader(kind, name, namespace) if reader is not None else None
        if live is not None:
            if cache is not None and cache.unchanged(
                ckey, dh, get_meta(live).get("resourceVersion")
            ):
                M_ELIDED.labels(kind=kind, via="hash").inc()
                sp.set_attribute("outcome", "elided_hash")
                return deepcopy(live), False
            # The copier folds fields INTO live; never mutate the informer's
            # stored object.
            live = deepcopy(live)
        if live is None:
            try:
                live = await kube.get(kind, name, namespace)
            except NotFound:
                try:
                    created = await kube.create(kind, desired)
                    if cache is not None:
                        cache.record(
                            ckey, dh, get_meta(created).get("resourceVersion"))
                    sp.set_attribute("outcome", "created")
                    return created, True
                except AlreadyExists:
                    live = await kube.get(kind, name, namespace)
        if copier(desired, live):
            log.debug("updating %s %s/%s (drift)", kind, namespace, name)
            updated = await kube.update(kind, live)
            if cache is not None:
                cache.record(ckey, dh, get_meta(updated).get("resourceVersion"))
            sp.set_attribute("outcome", "updated")
            return updated, False
        M_ELIDED.labels(kind=kind, via="diff").inc()
        sp.set_attribute("outcome", "elided_diff")
        if cache is not None:
            cache.record(ckey, dh, get_meta(live).get("resourceVersion"))
        return live, False


# ---- DAG-parallel child apply (latency hiding) -------------------------------

# Kill switch / bench baseline: forces apply_set stages and overlap() to
# run sequentially, restoring the pre-ISSUE-4 serial round-trip shape.
SERIAL_ENV = "KFTPU_SERIAL_APPLY"


def _serial() -> bool:
    return os.environ.get(SERIAL_ENV, "") not in ("", "0", "false")


class Stage:
    """One dependency stage of an :func:`apply_set` DAG: a NAME (lands on
    the ``apply_stage`` span; ci/check_tracing.py pins that converted
    controllers declare literal stage names) plus the children that may
    run concurrently. A child is a desired-object dict (applied through
    :func:`reconcile_child`) or a coroutine / zero-arg async callable for
    custom work that must still respect the stage ordering. ``None``
    children are dropped, so option-gated children read naturally at the
    call site."""

    __slots__ = ("name", "children")

    def __init__(self, name: str, children):
        self.name = name
        self.children = [c for c in children if c is not None]


@dataclass
class ChildOutcome:
    """Per-child result of :func:`apply_set` — recorded even when a
    stage-mate failed (first-error semantics raise only after the whole
    stage settles)."""

    child: object
    result: object = None   # reconcile_child's live object / callable return
    created: bool = False
    error: Exception | None = None


async def _run_child(kube, row: ChildOutcome, cache, reader, owner) -> None:
    child = row.child
    try:
        if isinstance(child, dict):
            if owner is not None:
                set_controller_owner(child, owner)
            row.result, row.created = await reconcile_child(
                kube, child, cache=cache, reader=reader)
        elif asyncio.iscoroutine(child):
            row.result = await child
        else:
            row.result = await child()
    except Exception as e:  # CancelledError propagates (shutdown)
        row.error = e


def _discard(children) -> None:
    """Close coroutine children that will never run (stages skipped after
    an earlier-stage error, or everything pending when a cancellation
    tears through mid-run), so they don't warn about never being
    awaited. Closing a finished coroutine is a no-op; a (theoretically)
    still-running one refuses — skip it rather than mask the real
    exception."""
    for c in children:
        if asyncio.iscoroutine(c):
            try:
                c.close()
            except RuntimeError:
                pass


async def apply_set(
    kube, stages, *, cache: ApplyCache | None = None, reader=None, owner=None,
) -> list[list[ChildOutcome]]:
    """Apply children as a dependency DAG of :class:`Stage` s.

    Children within a stage overlap via ``asyncio.gather`` — each keeps
    its own ``apply_child`` span and write elision — so a stage's wall
    time is its slowest child's RTT chain, not the sum. Stage N+1 starts
    only after every stage-N child settled (the barrier IS the dependency
    edge: e.g. capacity → slice StatefulSets → Services).

    First-error semantics: every stage-mate runs to completion and its
    outcome is recorded, then the first error re-raises (the workqueue
    retries with backoff). Later stages do not run; their coroutine
    children are closed.

    ``owner`` stamps the controller ownerReference on dict children;
    ``cache``/``reader`` thread through to :func:`reconcile_child`.
    ``KFTPU_SERIAL_APPLY=1`` forces sequential execution — the operator
    escape hatch, and the measured serial baseline of
    ``bench.py simulated_rtt``.
    """
    stages = list(stages)
    outcomes: list[list[ChildOutcome]] = []
    error: Exception | None = None
    for i, stage in enumerate(stages):
        if error is not None:
            _discard(stage.children)
            continue
        rows = [ChildOutcome(c) for c in stage.children]
        try:
            with span("apply_stage", stage=stage.name,
                      children=len(rows)) as sp:
                if _serial() or len(rows) <= 1:
                    for row in rows:
                        await _run_child(kube, row, cache, reader, owner)
                else:
                    await asyncio.gather(
                        *(_run_child(kube, row, cache, reader, owner)
                          for row in rows))
                failed = [r for r in rows if r.error is not None]
                if failed:
                    sp.fail(repr(failed[0].error))
                    error = failed[0].error
        except BaseException:
            # Cancellation (or a non-Exception) tore through mid-stage:
            # close this stage's never-started children and every later
            # stage's, then let it propagate.
            _discard(stage.children)
            for later in stages[i + 1:]:
                _discard(later.children)
            raise
        outcomes.append(rows)
    if error is not None:
        raise error
    return outcomes


async def overlap(*aws):
    """Run independent reconcile steps concurrently (sequentially under
    ``KFTPU_SERIAL_APPLY=1``) and return their results in argument order.
    ``None`` arguments stay ``None`` in the result, so option-gated steps
    keep positional results aligned. Same first-error semantics as an
    apply_set stage: every step settles, then the first error re-raises.
    """
    async def run_one(a):
        return None if a is None else await a

    # ≤1 real awaitable or the kill switch: nothing to overlap — skip
    # the per-coroutine Task spawns (the 0-RTT hot path keeps its cost).
    if _serial() or sum(a is not None for a in aws) <= 1:
        results, first = [], None
        for i, a in enumerate(aws):
            try:
                results.append(await run_one(a))
            except Exception as e:
                results.append(None)
                if first is None:
                    first = e
            except BaseException:
                _discard(aws[i + 1:])  # cancelled mid-run
                raise
        if first is not None:
            raise first
        return results
    try:
        results = await asyncio.gather(
            *(run_one(a) for a in aws), return_exceptions=True)
    except BaseException:
        # gather only raises here when itself cancelled; its run_one
        # tasks were cancelled too, but one cancelled before its first
        # step never awaited its inner coroutine — close stragglers.
        _discard(aws)
        raise
    for r in results:
        if isinstance(r, BaseException):
            raise r
    return results
