"""Create-or-update helpers with drift detection.

Port-in-spirit of the reference's ``components/common/reconcilehelper/util.go``
(:18-219): each helper fetches the live object, creates it if absent, and
otherwise copies only the fields the controller owns — preserving
cluster-managed fields (Service clusterIP, statuses) so reconciles converge
instead of fighting the apiserver.
"""

from __future__ import annotations

import logging

from kubeflow_tpu.runtime.errors import AlreadyExists, Conflict, NotFound
from kubeflow_tpu.runtime.objects import (
    deep_get,
    deepcopy,
    get_meta,
    name_of,
    namespace_of,
)

log = logging.getLogger(__name__)


def copy_statefulset_fields(desired: dict, live: dict) -> bool:
    """Reference: CopyStatefulSetFields (util.go:57-86) — labels, annotations,
    replicas, template; returns True when an update is required."""
    changed = _copy_meta(desired, live)
    for path in (("spec", "replicas"), ("spec", "template")):
        changed |= _copy_path(desired, live, path)
    return changed


def copy_deployment_fields(desired: dict, live: dict) -> bool:
    changed = _copy_meta(desired, live)
    for path in (("spec", "replicas"), ("spec", "template")):
        changed |= _copy_path(desired, live, path)
    return changed


def copy_service_fields(desired: dict, live: dict) -> bool:
    """Reference: CopyServiceFields (util.go:118-145) — preserves clusterIP.

    The live clusterIP is folded into the desired spec *before* comparison so
    repeated reconciles converge instead of updating forever.
    """
    changed = _copy_meta(desired, live)
    want = deepcopy(deep_get(desired, "spec") or {})
    cluster_ip = deep_get(live, "spec", "clusterIP")
    if cluster_ip is not None and "clusterIP" not in want:
        want["clusterIP"] = cluster_ip
    if deep_get(live, "spec") != want:
        live["spec"] = want
        changed = True
    return changed


def copy_virtual_service(desired: dict, live: dict) -> bool:
    changed = _copy_meta(desired, live)
    changed |= _copy_path(desired, live, ("spec",))
    return changed


def copy_spec(desired: dict, live: dict) -> bool:
    changed = _copy_meta(desired, live)
    changed |= _copy_path(desired, live, ("spec",))
    return changed


def _copy_meta(desired: dict, live: dict) -> bool:
    changed = False
    for field in ("labels", "annotations"):
        want = get_meta(desired).get(field)
        if want is not None and get_meta(live).get(field) != want:
            get_meta(live)[field] = deepcopy(want)
            changed = True
    return changed


def _copy_path(desired: dict, live: dict, path: tuple[str, ...]) -> bool:
    want = deep_get(desired, *path)
    have = deep_get(live, *path)
    if want is None or want == have:
        return False
    cur = live
    for part in path[:-1]:
        cur = cur.setdefault(part, {})
    cur[path[-1]] = deepcopy(want)
    return True


COPIERS = {
    "StatefulSet": copy_statefulset_fields,
    "Deployment": copy_deployment_fields,
    "Service": copy_service_fields,
    "VirtualService": copy_virtual_service,
}


async def reconcile_child(kube, desired: dict, *, copier=None) -> dict:
    """Ensure ``desired`` exists and owned fields match; returns the live object.

    The per-kind copier defaults from COPIERS; unknown kinds copy the whole
    spec. Conflict → raise (the workqueue retries with backoff, matching the
    reference's requeue-on-conflict behavior).
    """
    kind = desired["kind"]
    copier = copier or COPIERS.get(kind, copy_spec)
    name, namespace = name_of(desired), namespace_of(desired)
    try:
        live = await kube.get(kind, name, namespace)
    except NotFound:
        try:
            return await kube.create(kind, desired)
        except AlreadyExists:
            live = await kube.get(kind, name, namespace)
    if copier(desired, live):
        log.debug("updating %s %s/%s (drift)", kind, namespace, name)
        return await kube.update(kind, live)
    return live
