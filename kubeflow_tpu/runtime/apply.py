"""Create-or-update helpers with drift detection.

Port-in-spirit of the reference's ``components/common/reconcilehelper/util.go``
(:18-219): each helper fetches the live object, creates it if absent, and
otherwise copies only the fields the controller owns — preserving
cluster-managed fields (Service clusterIP, statuses) so reconciles converge
instead of fighting the apiserver.
"""

from __future__ import annotations

import hashlib
import json
import logging
from collections import OrderedDict

from kubeflow_tpu.runtime.errors import AlreadyExists, Conflict, NotFound
from kubeflow_tpu.runtime.metrics import global_registry
from kubeflow_tpu.runtime.tracing import span
from kubeflow_tpu.runtime.objects import (
    deep_get,
    deepcopy,
    get_meta,
    name_of,
    namespace_of,
)

log = logging.getLogger(__name__)

# Write-elision telemetry (bench reports these): "hash" means the
# last-applied cache short-circuited before even diffing; "diff" means the
# copier compared and found no drift. Either way zero API writes happened.
M_ELIDED = global_registry.counter(
    "apply_writes_elided_total",
    "Child reconciles that issued no API write",
    ["kind", "via"],
)


def state_hash(obj) -> str:
    """Stable content hash of a JSON-shaped object (dict key order and
    whitespace don't matter; list order does — k8s list order is
    semantic, matching subset_equal below)."""
    return hashlib.sha1(
        json.dumps(obj, sort_keys=True, separators=(",", ":"),
                   default=str).encode()
    ).hexdigest()


class ApplyCache:
    """Per-key last-applied memory: (kind, ns, name) → (desired-state
    hash, live resourceVersion at last convergence). A reconcile whose
    desired state hashes the same while the live object's rv is unchanged
    is provably a no-op — skip the diff entirely. Any external change
    bumps the rv and falls through to the copier, so drift repair is
    untouched; a desired-state change misses on the hash.

    LRU-bounded: deletion paths (owner cascade, GC) don't flow through
    here, so without a bound the cache would grow with *historical*
    object count under create/delete churn. Eviction only costs a diff
    on the next reconcile of that key — never correctness."""

    def __init__(self, max_entries: int = 4096):
        self.max_entries = max_entries
        self._entries: "OrderedDict[tuple, tuple[str, str | None]]" = \
            OrderedDict()

    @staticmethod
    def key_of(desired: dict) -> tuple:
        return (desired.get("kind"), namespace_of(desired), name_of(desired))

    def unchanged(self, key: tuple, desired_hash: str, live_rv) -> bool:
        entry = self._entries.get(key)
        if entry is None or entry != (desired_hash, live_rv):
            return False
        self._entries.move_to_end(key)
        return True

    def record(self, key: tuple, desired_hash: str, live_rv) -> None:
        self._entries[key] = (desired_hash, live_rv)
        self._entries.move_to_end(key)
        while len(self._entries) > self.max_entries:
            self._entries.popitem(last=False)

    def forget(self, key: tuple) -> None:
        self._entries.pop(key, None)


def informer_reader(informers: dict):
    """A ``reconcile_child`` reader over a kind → informer mapping (the
    shape every reconciler wires in its setup fn). The dict is read live,
    so setup may populate it after constructing the reader."""

    def reader(kind: str, name: str, namespace: str | None) -> dict | None:
        inf = informers.get(kind)
        return inf.get(name, namespace) if inf is not None else None

    return reader


def subset_equal(want, have) -> bool:
    """True when every field the controller sets is already present in the
    live object. The live object legitimately has MORE fields (apiserver
    defaulting: Service ipFamilies/sessionAffinity, pod restartPolicy/
    dnsPolicy, ...); comparing whole subtrees with ``==`` would see permanent
    drift and update in a hot loop against a real cluster. Trade-off: a field
    the controller *removes* from its desired state is not reverted — owned
    objects are regenerated wholesale on spec changes, so this doesn't bite.
    Lists compare element-wise (k8s list order is semantic)."""
    if isinstance(want, dict) and isinstance(have, dict):
        return all(k in have and subset_equal(v, have[k]) for k, v in want.items())
    if isinstance(want, list) and isinstance(have, list):
        return len(want) == len(have) and all(
            subset_equal(w, h) for w, h in zip(want, have)
        )
    return want == have


def copy_statefulset_fields(desired: dict, live: dict) -> bool:
    """Reference: CopyStatefulSetFields (util.go:57-86) — labels, annotations,
    replicas, template; returns True when an update is required."""
    changed = _copy_meta(desired, live)
    for path in (("spec", "replicas"), ("spec", "template")):
        changed |= _copy_path(desired, live, path)
    return changed


copy_deployment_fields = copy_statefulset_fields  # identical owned-field set


def copy_service_fields(desired: dict, live: dict) -> bool:
    """Reference: CopyServiceFields (util.go:118-145) — preserves clusterIP.

    The live clusterIP is folded into the desired spec *before* comparison so
    repeated reconciles converge instead of updating forever.
    """
    changed = _copy_meta(desired, live)
    want = deepcopy(deep_get(desired, "spec") or {})
    cluster_ip = deep_get(live, "spec", "clusterIP")
    if cluster_ip is not None and "clusterIP" not in want:
        want["clusterIP"] = cluster_ip
    if not subset_equal(want, deep_get(live, "spec") or {}):
        live["spec"] = want
        changed = True
    return changed


def copy_virtual_service(desired: dict, live: dict) -> bool:
    changed = _copy_meta(desired, live)
    changed |= _copy_path(desired, live, ("spec",))
    return changed


def copy_spec(desired: dict, live: dict) -> bool:
    changed = _copy_meta(desired, live)
    changed |= _copy_path(desired, live, ("spec",))
    return changed


def _copy_meta(desired: dict, live: dict) -> bool:
    """Fold desired labels/annotations into the live ones (other actors may
    legitimately add their own; only ours must be present and equal)."""
    changed = False
    for field in ("labels", "annotations"):
        want = get_meta(desired).get(field)
        have = get_meta(live).get(field) or {}
        if want is not None and not subset_equal(want, have):
            get_meta(live)[field] = {**have, **deepcopy(want)}
            changed = True
    return changed


def _copy_path(desired: dict, live: dict, path: tuple[str, ...]) -> bool:
    want = deep_get(desired, *path)
    have = deep_get(live, *path)
    if want is None or subset_equal(want, have):
        return False
    cur = live
    for part in path[:-1]:
        cur = cur.setdefault(part, {})
    cur[path[-1]] = deepcopy(want)
    return True


def copy_rolebinding_fields(desired: dict, live: dict) -> bool:
    """RoleBindings have no spec: the owned payload is subjects (+ roleRef).
    Note roleRef is immutable on a real apiserver — our bindings derive the
    role from the binding *name*, so a roleRef change implies a new name
    (delete + create), never an in-place update."""
    changed = _copy_meta(desired, live)
    for field in ("subjects", "roleRef"):
        want = desired.get(field)
        if want is not None and not subset_equal(want, live.get(field)):
            live[field] = deepcopy(want)
            changed = True
    return changed


COPIERS = {
    "StatefulSet": copy_statefulset_fields,
    "Deployment": copy_deployment_fields,
    "Service": copy_service_fields,
    "VirtualService": copy_virtual_service,
    "RoleBinding": copy_rolebinding_fields,
}


async def reconcile_child(
    kube, desired: dict, *, copier=None, cache: ApplyCache | None = None,
    reader=None,
) -> tuple[dict, bool]:
    """Ensure ``desired`` exists and owned fields match.

    Returns ``(live_object, created)`` — callers that count creations (e.g.
    the notebook_create_total metric) use the flag instead of a second
    read-before-write. The per-kind copier defaults from COPIERS; unknown
    kinds copy the whole spec. Conflict → raise (the workqueue retries with
    backoff, matching the reference's requeue-on-conflict behavior).

    ``reader(kind, name, namespace) -> dict | None`` reads the live object
    from a watch cache (informer) instead of a per-reconcile apiserver GET
    — a None return (cold cache) falls back to the GET, so correctness
    never depends on cache warmth. A stale cached rv at worst produces a
    Conflict the workqueue retries, same as any writer race.

    ``cache`` (ApplyCache) elides the whole diff when the desired-state
    hash AND the live rv match the last convergence — the steady-state
    reconcile touches neither the apiserver nor the copier.
    """
    kind = desired["kind"]
    copier = copier or COPIERS.get(kind, copy_spec)
    name, namespace = name_of(desired), namespace_of(desired)
    ckey = ApplyCache.key_of(desired) if cache is not None else None
    dh = state_hash(desired) if cache is not None else None

    with span("apply_child", kind=kind, name=name) as sp:
        live = reader(kind, name, namespace) if reader is not None else None
        if live is not None:
            if cache is not None and cache.unchanged(
                ckey, dh, get_meta(live).get("resourceVersion")
            ):
                M_ELIDED.labels(kind=kind, via="hash").inc()
                sp.set_attribute("outcome", "elided_hash")
                return deepcopy(live), False
            # The copier folds fields INTO live; never mutate the informer's
            # stored object.
            live = deepcopy(live)
        if live is None:
            try:
                live = await kube.get(kind, name, namespace)
            except NotFound:
                try:
                    created = await kube.create(kind, desired)
                    if cache is not None:
                        cache.record(
                            ckey, dh, get_meta(created).get("resourceVersion"))
                    sp.set_attribute("outcome", "created")
                    return created, True
                except AlreadyExists:
                    live = await kube.get(kind, name, namespace)
        if copier(desired, live):
            log.debug("updating %s %s/%s (drift)", kind, namespace, name)
            updated = await kube.update(kind, live)
            if cache is not None:
                cache.record(ckey, dh, get_meta(updated).get("resourceVersion"))
            sp.set_attribute("outcome", "updated")
            return updated, False
        M_ELIDED.labels(kind=kind, via="diff").inc()
        sp.set_attribute("outcome", "elided_diff")
        if cache is not None:
            cache.record(ckey, dh, get_meta(live).get("resourceVersion"))
        return live, False
