"""TPU topology library — the single source of truth for accelerator decisions.

Where the reference scatters GPU knowledge across a spawner YAML
(``crud-web-apps/jupyter/backend/apps/common/yaml/spawner_ui_config.yaml:120-141``,
vendor limitsKeys like ``nvidia.com/gpu``) and env-var plumbing, this package
centralises every TPU-specific mapping: accelerator generation + topology →
(#hosts, chips/host, GKE node selectors, ``TPU_WORKER_*`` env, resource requests).
"""

from kubeflow_tpu.tpu.topology import (
    ACCELERATORS,
    TpuAccelerator,
    TpuSlice,
    TopologyError,
    parse_topology,
)

__all__ = [
    "ACCELERATORS",
    "TpuAccelerator",
    "TpuSlice",
    "TopologyError",
    "parse_topology",
]
