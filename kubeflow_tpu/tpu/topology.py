"""Pure TPU slice topology math.

This is the TPU-native replacement for the reference's accelerator plumbing
(GPU vendor limitsKeys in ``spawner_ui_config.yaml:120-141`` and the gpu form
setter in ``crud-web-apps/jupyter/backend/apps/common/form.py``): a single pure
library that maps ``(accelerator, topology)`` to everything the control plane
needs — host count (StatefulSet replicas), chips per host (``google.com/tpu``
requests), GKE node selectors, ``TPU_WORKER_*`` environment, and stable worker
hostnames for ``jax.distributed.initialize``.

Everything here is pure and unit-testable; no Kubernetes imports.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field

# GKE well-known labels/resources for TPU scheduling.
GKE_TPU_ACCELERATOR_LABEL = "cloud.google.com/gke-tpu-accelerator"
GKE_TPU_TOPOLOGY_LABEL = "cloud.google.com/gke-tpu-topology"
TPU_RESOURCE = "google.com/tpu"

# Port our controllers wire for jax.distributed coordinator (DCN bootstrap).
JAX_COORDINATOR_PORT = 8476

# Megascale (multislice) coordinator port — the DCN-side rendezvous libtpu
# uses to join N slices into one training job.
MEGASCALE_PORT = 8080


class TopologyError(ValueError):
    """Invalid accelerator/topology combination."""


@dataclass(frozen=True)
class TpuAccelerator:
    """Static facts about one TPU generation.

    Peak numbers are approximate public figures used only for bandwidth /
    utilisation *estimates* in diagnostics (never for scheduling decisions).
    """

    name: str                      # short name used in our CRD: "v4", "v5e", "v5p", "v6e"
    gke_accelerator: str           # value for cloud.google.com/gke-tpu-accelerator
    host_bounds: tuple[int, ...]   # chip grid of one host, e.g. (2, 4) or (2, 2, 1)
    cores_per_chip: int            # TensorCores per chip (accelerator_type counts cores)
    hbm_gib_per_chip: int
    peak_bf16_tflops_per_chip: float
    hbm_gbps_per_chip: float       # HBM bandwidth, GB/s
    ici_gbps_per_link: float       # one-way ICI bandwidth per link, GB/s (approx)
    topologies: tuple[str, ...]    # GKE-documented topology strings
    accelerator_type_prefix: str = ""  # e.g. "v5litepod" -> accelerator_type "v5litepod-16"
    dcn_gbps_per_host: float = 12.5  # host NIC bandwidth, GB/s (approx public
                                     # figure; the cross-slice DCN floor)

    @property
    def ndim(self) -> int:
        return len(self.host_bounds)

    @property
    def chips_per_full_host(self) -> int:
        return math.prod(self.host_bounds)

    def accelerator_type(self, num_chips: int) -> str:
        """GCE-style accelerator type string, which counts *cores*: v4-8 = 4 chips."""
        prefix = self.accelerator_type_prefix or self.name
        return f"{prefix}-{num_chips * self.cores_per_chip}"


ACCELERATORS: dict[str, TpuAccelerator] = {
    acc.name: acc
    for acc in (
        TpuAccelerator(
            name="v4",
            gke_accelerator="tpu-v4-podslice",
            host_bounds=(2, 2, 1),
            cores_per_chip=2,
            hbm_gib_per_chip=32,
            peak_bf16_tflops_per_chip=275.0,
            hbm_gbps_per_chip=1228.0,
            ici_gbps_per_link=50.0,
            topologies=(
                "2x2x1", "2x2x2", "2x2x4", "2x4x4", "4x4x4", "4x4x8",
                "4x8x8", "8x8x8", "8x8x12", "8x8x16", "8x16x16",
            ),
        ),
        TpuAccelerator(
            name="v5e",
            gke_accelerator="tpu-v5-lite-podslice",
            host_bounds=(2, 4),
            cores_per_chip=1,
            hbm_gib_per_chip=16,
            peak_bf16_tflops_per_chip=197.0,
            hbm_gbps_per_chip=819.0,
            ici_gbps_per_link=50.0,
            topologies=("1x1", "2x2", "2x4", "4x4", "4x8", "8x8", "8x16", "16x16"),
            accelerator_type_prefix="v5litepod",
        ),
        TpuAccelerator(
            name="v5p",
            gke_accelerator="tpu-v5p-slice",
            host_bounds=(2, 2, 1),
            cores_per_chip=2,
            hbm_gib_per_chip=95,
            peak_bf16_tflops_per_chip=459.0,
            hbm_gbps_per_chip=2765.0,
            ici_gbps_per_link=100.0,
            dcn_gbps_per_host=25.0,
            topologies=(
                "2x2x1", "2x2x2", "2x4x4", "4x4x4", "4x4x8", "4x8x8",
                "8x8x8", "8x8x16", "8x16x16", "16x16x16", "16x16x24",
            ),
        ),
        TpuAccelerator(
            name="v6e",
            gke_accelerator="tpu-v6e-slice",
            host_bounds=(2, 4),
            cores_per_chip=1,
            hbm_gib_per_chip=32,
            peak_bf16_tflops_per_chip=918.0,
            hbm_gbps_per_chip=1640.0,
            ici_gbps_per_link=100.0,
            dcn_gbps_per_host=25.0,
            topologies=("1x1", "2x2", "2x4", "4x4", "4x8", "8x8", "8x16", "16x16"),
        ),
    )
}


def parse_topology(topology: str) -> tuple[int, ...]:
    """Parse "4x4" / "2x2x2" into an int tuple.

    Strict by design: every axis must be a bare decimal integer — no
    whitespace, signs, or floats. ``int()`` alone would accept "4 x 4"
    (it strips whitespace), and the raw string flows into GKE node-
    selector label values and the fleet scheduler's shape matching,
    where "4 x 4" and "4x4" must not name two different shapes."""
    parts = topology.lower().split("x")
    try:
        if any(not part.isdigit() for part in parts):
            raise ValueError
        dims = tuple(int(part) for part in parts)
    except ValueError:
        raise TopologyError(f"malformed topology {topology!r}") from None
    if not dims or any(d < 1 for d in dims):
        raise TopologyError(f"malformed topology {topology!r}")
    return dims


@dataclass(frozen=True)
class TpuSlice:
    """A resolved (accelerator, topology) pair with all derived scheduling facts.

    The controller uses this to size the StatefulSet (``num_hosts``), the
    webhook uses it to inject worker env, and the web apps use it to render
    accelerator pickers — one shared source of truth.
    """

    accelerator: TpuAccelerator
    topology: tuple[int, ...]
    topology_str: str = field(default="", compare=False)

    @classmethod
    def parse(cls, accelerator: str, topology: str, *, strict: bool = False) -> "TpuSlice":
        """Resolve an accelerator name + topology string.

        With ``strict=True`` only GKE-documented topologies are accepted;
        otherwise any grid that tiles into full hosts (or fits in one host)
        validates, which keeps the library future-proof for new slice shapes.
        """
        acc = ACCELERATORS.get(accelerator.lower())
        if acc is None:
            raise TopologyError(
                f"unknown accelerator {accelerator!r}; known: {sorted(ACCELERATORS)}"
            )
        dims = parse_topology(topology)
        if len(dims) != acc.ndim:
            raise TopologyError(
                f"{acc.name} topologies are {acc.ndim}-D, got {topology!r}"
            )
        if strict and topology.lower() not in acc.topologies:
            raise TopologyError(
                f"{topology!r} is not a documented {acc.name} topology; "
                f"known: {acc.topologies}"
            )
        slice_ = cls(accelerator=acc, topology=dims, topology_str=topology.lower())
        slice_._validate()
        return slice_

    def _validate(self) -> None:
        chips = self.num_chips
        if chips <= self.accelerator.chips_per_full_host:
            # Sub-host (or exactly one host) slice: must fit the host grid.
            if any(
                d > b for d, b in zip(sorted(self.topology), sorted(self.accelerator.host_bounds))
            ):
                raise TopologyError(
                    f"topology {self.topology_str} does not fit one "
                    f"{self.accelerator.name} host {self.accelerator.host_bounds}"
                )
        else:
            # Multi-host slice: every axis must tile into full hosts.
            for d, b in zip(self.topology, self.accelerator.host_bounds):
                if d % b != 0:
                    raise TopologyError(
                        f"multi-host topology {self.topology_str} must be a multiple of "
                        f"the host grid {self.accelerator.host_bounds} on every axis"
                    )

    # ---- derived scheduling facts -------------------------------------------------

    @property
    def num_chips(self) -> int:
        return math.prod(self.topology)

    @property
    def num_hosts(self) -> int:
        return max(1, self.num_chips // self.accelerator.chips_per_full_host)

    @property
    def chips_per_host(self) -> int:
        return min(self.num_chips, self.accelerator.chips_per_full_host)

    @property
    def multi_host(self) -> bool:
        return self.num_hosts > 1

    @property
    def accelerator_type(self) -> str:
        return self.accelerator.accelerator_type(self.num_chips)

    def host_grid(self) -> tuple[int, ...]:
        """How hosts tile the chip grid, per axis (all 1s for single-host)."""
        if not self.multi_host:
            return tuple(1 for _ in self.topology)
        return tuple(d // b for d, b in zip(self.topology, self.accelerator.host_bounds))

    def chips_per_host_bounds(self) -> tuple[int, ...]:
        """Per-axis chip grid of one host's share of the slice."""
        if not self.multi_host:
            return self.topology
        return self.accelerator.host_bounds

    # ---- Kubernetes-facing outputs ------------------------------------------------

    def node_selectors(self) -> dict[str, str]:
        return {
            GKE_TPU_ACCELERATOR_LABEL: self.accelerator.gke_accelerator,
            GKE_TPU_TOPOLOGY_LABEL: self.topology_str,
        }

    def resource_requests(self) -> dict[str, str]:
        """Per-pod resources: each worker pod takes its host's whole chip share."""
        return {TPU_RESOURCE: str(self.chips_per_host)}

    def worker_hostnames(
        self, name: str, headless_service: str, namespace: str,
        cluster_domain: str = "cluster.local",
    ) -> list[str]:
        """Stable per-worker DNS names via the headless Service.

        StatefulSet pods ``<name>-<i>`` get
        ``<name>-<i>.<headless-svc>.<ns>.svc.<domain>`` — this is the
        TPU_WORKER_HOSTNAMES / jax.distributed bootstrap contract.
        """
        return [
            f"{name}-{i}.{headless_service}.{namespace}.svc.{cluster_domain}"
            for i in range(self.num_hosts)
        ]

    def worker_env(self, worker_id: int, hostnames: list[str]) -> dict[str, str]:
        """libtpu + JAX environment for worker ``worker_id`` of the slice.

        TPU-native replacement for the CUDA env the reference's images inherit
        from their base layers: everything libtpu needs to wire ICI from
        topology, plus the DCN coordinator for jax.distributed.
        """
        if not 0 <= worker_id < self.num_hosts:
            raise TopologyError(
                f"worker_id {worker_id} out of range for {self.num_hosts}-host slice"
            )
        env = {
            "TPU_WORKER_ID": str(worker_id),
            "TPU_WORKER_HOSTNAMES": ",".join(hostnames),
            "TPU_CHIPS_PER_HOST_BOUNDS": ",".join(str(d) for d in self.chips_per_host_bounds()),
            "TPU_HOST_BOUNDS": ",".join(str(d) for d in self.host_grid()),
            "TPU_ACCELERATOR_TYPE": self.accelerator_type,
            "TPU_SKIP_MDS_QUERY": "true",  # pods have no GCE metadata server
            "TPU_TOPOLOGY": self.topology_str,
        }
        if hostnames:
            env["JAX_COORDINATOR_ADDRESS"] = f"{hostnames[0]}:{JAX_COORDINATOR_PORT}"
            env["JAX_NUM_PROCESSES"] = str(self.num_hosts)
            env["JAX_PROCESS_ID"] = str(worker_id)
        return env

    # ---- diagnostics estimates ----------------------------------------------------

    def peak_bf16_tflops(self) -> float:
        return self.num_chips * self.accelerator.peak_bf16_tflops_per_chip

    def with_slices(self, num_slices: int) -> "MultiSlice":
        return MultiSlice(slice=self, num_slices=num_slices)

    def allreduce_algo_bandwidth_gbps(self) -> float:
        """Approximate achievable all-reduce algorithm bandwidth over ICI.

        Ring all-reduce moves ``2*(k-1)/k`` bytes per byte reduced; on a torus
        each chip drives one link per ring direction. Used by the ICI probe to
        score "fraction of peak" (north-star metric, BASELINE.md).
        """
        k = self.num_chips
        if k <= 1:
            return float("inf")
        link = self.accelerator.ici_gbps_per_link
        # Bidirectional ring over the largest torus dimension as a floor estimate.
        return link * 2 * k / (2 * (k - 1))


@dataclass(frozen=True)
class MultiSlice:
    """``num_slices`` identical TPU slices joined over DCN (Multislice).

    ICI exists only *within* a slice; across slices traffic rides the
    data-center network, joined by libtpu's megascale layer. The control
    plane consequences, all derived here:

    - one StatefulSet per slice (``slice_sts_name``) — ICI placement is
      per-slice, so each slice schedules as its own gang;
    - per-slice ``TPU_WORKER_*`` env (libtpu wires ICI per slice), plus
      ``MEGASCALE_*`` env that is static per slice (slice id, slice
      count, the DCN coordinator = slice 0's worker 0);
    - one *global* jax.distributed process space: ``JAX_NUM_PROCESSES``
      spans every host of every slice.

    The reference has no analogue (single-pod notebooks); this is the
    TPU-native frontier past parity (SURVEY.md §2.4/§7, VERDICT r2 #7).
    """

    slice: TpuSlice
    num_slices: int

    @classmethod
    def parse(
        cls, accelerator: str, topology: str, num_slices: int = 1,
        *, strict: bool = False,
    ) -> "MultiSlice":
        if not isinstance(num_slices, int) or isinstance(num_slices, bool) \
                or num_slices < 1:
            raise TopologyError(f"numSlices must be a positive int, got {num_slices!r}")
        if num_slices > 64:
            raise TopologyError(f"numSlices {num_slices} exceeds the supported 64")
        return cls(
            slice=TpuSlice.parse(accelerator, topology, strict=strict),
            num_slices=num_slices,
        )

    @property
    def multi(self) -> bool:
        return self.num_slices > 1

    @property
    def num_chips(self) -> int:
        return self.slice.num_chips * self.num_slices

    @property
    def total_hosts(self) -> int:
        return self.slice.num_hosts * self.num_slices

    def slice_sts_name(self, base: str, slice_id: int) -> str:
        """StatefulSet (and pod-name prefix) for one slice. Single-slice
        notebooks keep the bare name — zero churn for the common case.

        Defensively clamped: pod hostnames (``<sts>-<ordinal>``) must stay
        valid DNS labels (≤63 chars). Admission caps Notebook names well
        below this, but direct library callers get a truncate-and-hash
        instead of an apiserver rejection at create time."""
        if not self.multi:
            return base
        name = f"{base}-s{slice_id}"
        limit = 56  # + "-<ordinal>" keeps the pod hostname ≤ 63
        if len(name) <= limit:
            return name
        import hashlib

        digest = hashlib.sha256(base.encode()).hexdigest()[:8]
        suffix = f"-{digest}-s{slice_id}"
        return base[: limit - len(suffix)].rstrip("-.") + suffix

    def worker_hostnames(
        self, name: str, headless_service: str, namespace: str,
        cluster_domain: str = "cluster.local",
    ) -> list[list[str]]:
        """Per-slice stable DNS names (pods of every slice's StatefulSet
        share one headless Service)."""
        return [
            self.slice.worker_hostnames(
                self.slice_sts_name(name, j), headless_service, namespace,
                cluster_domain,
            )
            for j in range(self.num_slices)
        ]

    def megascale_env(self, slice_id: int, hostnames: list[list[str]]) -> dict[str, str]:
        """Slice-static megascale env (bakeable into slice ``slice_id``'s
        StatefulSet template — unlike TPU_WORKER_ID it doesn't vary by
        ordinal)."""
        if not 0 <= slice_id < self.num_slices:
            raise TopologyError(
                f"slice_id {slice_id} out of range for {self.num_slices} slices"
            )
        if not self.multi:
            return {}
        coordinator = hostnames[0][0]
        return {
            "MEGASCALE_COORDINATOR_ADDRESS": f"{coordinator}:{MEGASCALE_PORT}",
            "MEGASCALE_NUM_SLICES": str(self.num_slices),
            "MEGASCALE_SLICE_ID": str(slice_id),
        }

    def worker_env(
        self, slice_id: int, worker_id: int, hostnames: list[list[str]]
    ) -> dict[str, str]:
        """Full env for worker ``worker_id`` of slice ``slice_id``:
        intra-slice TPU_* (ICI) + megascale (DCN) + the global
        jax.distributed process space."""
        env = self.slice.worker_env(worker_id, hostnames[slice_id])
        env.update(self.megascale_env(slice_id, hostnames))
        if self.multi:
            env["JAX_COORDINATOR_ADDRESS"] = (
                f"{hostnames[0][0]}:{JAX_COORDINATOR_PORT}"
            )
            env["JAX_NUM_PROCESSES"] = str(self.total_hosts)
            env["JAX_PROCESS_ID"] = str(
                slice_id * self.slice.num_hosts + worker_id
            )
            # DCN probe peers: worker 0 of every slice (probe/dcn.py runs
            # one rank per slice to validate the cross-slice network).
            env["KFTPU_SLICE_PEERS"] = ",".join(h[0] for h in hostnames)
        return env

    def peak_bf16_tflops(self) -> float:
        return self.num_slices * self.slice.peak_bf16_tflops()

    def dcn_ring_bandwidth_gbps(self) -> float:
        """Approximate achievable per-direction DCN ring bandwidth for the
        cross-slice probe (one rank per slice — worker 0's host NIC is the
        bottleneck). Used by probe/dcn.py to score "fraction of peak" for
        the megascale path, the DCN analogue of
        ``TpuSlice.allreduce_algo_bandwidth_gbps`` (BASELINE.md config 4).
        Single-slice: no cross-slice traffic exists → inf."""
        if self.num_slices <= 1:
            return float("inf")
        return self.slice.accelerator.dcn_gbps_per_host
