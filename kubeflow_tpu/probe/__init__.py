"""Slice burn-in probes.

Two probes validate a freshly spawned slice (the framework's e2e health
check and the BASELINE.md north-star metric):

- :mod:`kubeflow_tpu.probe.ici` — JAX all-reduce bandwidth over ICI,
  scored as a fraction of the topology's theoretical peak
  (``TpuSlice.allreduce_algo_bandwidth_gbps``).
- :mod:`kubeflow_tpu.probe.dcn` — TCP ring bandwidth over the DCN/pod
  network between workers (native C++ engine in ``native/``), validating
  the headless-Service path ``jax.distributed.initialize`` bootstraps over.

Run in-notebook or as a Job: ``python -m kubeflow_tpu.probe``.
"""

from kubeflow_tpu.probe.ici import IciReport, run_ici_probe

__all__ = ["run_ici_probe", "IciReport"]
