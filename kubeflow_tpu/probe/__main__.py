"""CLI: ``python -m kubeflow_tpu.probe`` — run the slice burn-in.

Prints one JSON document: ICI all-reduce report (+ DCN ring report when
running inside a multi-worker slice).
"""

from __future__ import annotations

import argparse
import json
import os

# Defaults for off-cluster runs; on-cluster the controller injects both
# (docs/operations.md "Probe / burn-in env").
ACCELERATOR_ENV = "KFTPU_ACCELERATOR"


def main() -> None:
    parser = argparse.ArgumentParser(description="TPU slice burn-in probe")
    parser.add_argument("--mbytes", type=float, default=64.0)
    parser.add_argument("--iters", type=int, default=10)
    parser.add_argument("--accelerator", default=os.environ.get(ACCELERATOR_ENV))
    parser.add_argument("--topology", default=os.environ.get("TPU_TOPOLOGY"))
    parser.add_argument("--skip-dcn", action="store_true")
    args = parser.parse_args()

    from kubeflow_tpu.probe.ici import run_ici_probe

    report: dict = {
        "ici": run_ici_probe(
            mbytes=args.mbytes,
            iters=args.iters,
            accelerator=args.accelerator,
            topology=args.topology,
        ).to_dict()
    }

    if not args.skip_dcn:
        from kubeflow_tpu.probe.dcn import (
            run_rank,
            slice_env_config,
            worker_env_config,
        )

        config = worker_env_config()
        if config is not None:
            rank, world, peers = config
            try:
                report["dcn"] = run_rank(rank, world, peers, mbytes=args.mbytes)
            except Exception as e:  # burn-in keeps going; DCN result is advisory
                report["dcn"] = {"error": str(e)}

        # Cross-slice ring (multislice): one rank per slice, worker 0 only —
        # validates the links megascale training rides. Separate port base
        # so it never collides with the intra-slice ring above.
        slice_config = slice_env_config()
        if slice_config is not None:
            rank, world, peers = slice_config
            try:
                raw = run_rank(
                    rank, world, peers, mbytes=args.mbytes, base_port=19500)
                report["dcn_cross_slice"] = raw
            except Exception as e:
                report["dcn_cross_slice"] = {"error": str(e)}
            else:
                # Score this rank's ring rate against the topology
                # estimate (same contract as report["ici"]). A scoring
                # failure must not discard the measurement above.
                if args.accelerator and args.topology:
                    try:
                        from kubeflow_tpu.probe.dcn import score_reports
                        from kubeflow_tpu.tpu.topology import MultiSlice

                        ms = MultiSlice.parse(args.accelerator,
                                              args.topology,
                                              num_slices=world)
                        report["dcn_cross_slice_scored"] = score_reports(
                            [raw], multi=ms).to_dict()
                    except Exception as e:
                        report["dcn_cross_slice_scored"] = {"error": str(e)}

    print(json.dumps(report))


if __name__ == "__main__":
    main()
