"""ICI all-reduce bandwidth probe.

Measures achieved all-reduce algorithm bandwidth across all local devices
with a jitted ``psum`` under ``shard_map``, and scores it against the
topology library's theoretical estimate. On TPU this exercises the ICI
rings libtpu wired from ``TPU_*`` topology env; on CPU (tests, dev) the
same code path runs against the virtual mesh — the *score* is only
meaningful on real hardware, the *plumbing* is validated everywhere.

Ring all-reduce moves ``2*(k-1)/k`` bytes per byte reduced; algorithm
bandwidth = ``2*(k-1)/k * bytes / time`` per chip (the convention in the
public scaling literature, PAPERS.md).
"""

from __future__ import annotations

import math
import time
from dataclasses import asdict, dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

try:
    from jax import shard_map  # jax >= 0.6
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map


@dataclass(frozen=True)
class IciReport:
    devices: int
    bytes_per_device: int
    iters: int
    mean_seconds: float
    algo_bandwidth_gbps: float     # per chip
    peak_estimate_gbps: float | None
    fraction_of_peak: float | None
    backend: str

    def to_dict(self) -> dict:
        out = asdict(self)
        # Single-device probes have no inter-chip traffic: bandwidth is
        # unbounded. JSON has no Infinity, so serialize non-finite as None.
        for key, value in out.items():
            if isinstance(value, float) and not math.isfinite(value):
                out[key] = None
        return out


def run_ici_probe(
    *,
    mbytes: float = 64.0,
    iters: int = 10,
    warmup: int = 3,
    devices: list | None = None,
    accelerator: str | None = None,
    topology: str | None = None,
) -> IciReport:
    """All-reduce ``mbytes`` of bf16 across all devices, ``iters`` times."""
    devices = devices or jax.devices()
    k = len(devices)
    mesh = jax.sharding.Mesh(np.asarray(devices), ("x",))
    n_elems = int(mbytes * 1e6 / 2)  # bf16
    n_elems -= n_elems % max(k, 1)

    # psum over the axis; each shard keeps its slice of the (replicated)
    # result so output stays sharded and no gather is timed.
    @jax.jit
    @partial(
        shard_map,
        mesh=mesh,
        in_specs=jax.sharding.PartitionSpec("x"),
        out_specs=jax.sharding.PartitionSpec("x"),
    )
    def allreduce_slice(x):
        return jax.lax.psum(x, "x")

    x = jnp.ones((n_elems,), jnp.bfloat16)
    x = jax.device_put(
        x,
        jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec("x")),
    )
    for _ in range(warmup):
        out = allreduce_slice(x)
    jax.block_until_ready(out)

    t0 = time.perf_counter()
    for _ in range(iters):
        out = allreduce_slice(x)
    jax.block_until_ready(out)
    mean = (time.perf_counter() - t0) / iters

    bytes_per_device = n_elems // max(k, 1) * 2
    if k > 1:
        algo_gbps = (2 * (k - 1) / k) * bytes_per_device / mean / 1e9
    else:
        algo_gbps = float("inf")

    peak = fraction = None
    if accelerator and topology:
        from kubeflow_tpu.tpu.topology import TpuSlice

        tpu = TpuSlice.parse(accelerator, topology)
        peak = tpu.allreduce_algo_bandwidth_gbps()
        if peak and peak != float("inf"):
            fraction = algo_gbps / peak

    return IciReport(
        devices=k,
        bytes_per_device=bytes_per_device,
        iters=iters,
        mean_seconds=mean,
        algo_bandwidth_gbps=round(algo_gbps, 3),
        peak_estimate_gbps=round(peak, 3) if peak not in (None, float("inf")) else peak,
        fraction_of_peak=round(fraction, 4) if fraction is not None else None,
        backend=jax.default_backend(),
    )
