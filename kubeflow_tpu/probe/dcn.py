"""Python driver for the native DCN ring probe (``native/dcn_probe.cpp``).

Locates (or builds) the ``dcn_probe`` binary and runs one rank per worker.
In-notebook the rank/peers come from the ``TPU_WORKER_*`` env the controller
injected; in tests all ranks run as local subprocesses over loopback.
"""

from __future__ import annotations

import json
import math
import os
import shutil
import subprocess
from dataclasses import asdict, dataclass
from pathlib import Path

NATIVE_DIR = Path(__file__).resolve().parent.parent.parent / "native"

# Cross-slice ring peers, injected by the controller as worker env
# (tpu/topology.py worker_env; docs/operations.md "Probe / burn-in env").
SLICE_PEERS_ENV = "KFTPU_SLICE_PEERS"


@dataclass(frozen=True)
class DcnReport:
    """Scored cross-slice (or cross-worker) ring result — the DCN analogue
    of ``probe.ici.IciReport``. ``min_gbps`` is the slowest rank: a ring is
    only as fast as its weakest link, so that is the number scored."""

    world: int
    mbytes: float
    iters: int
    min_gbps: float
    mean_gbps: float
    peak_estimate_gbps: float | None
    fraction_of_peak: float | None

    def to_dict(self) -> dict:
        out = asdict(self)
        for key, value in out.items():
            if isinstance(value, float) and not math.isfinite(value):
                out[key] = None  # JSON has no Infinity
        return out


def score_reports(reports: list[dict], multi=None) -> DcnReport:
    """Fold per-rank probe JSON into one scored report.

    ``multi``: a ``tpu.topology.MultiSlice`` — when given (and multi-slice),
    the measured ring rate is scored against its
    ``dcn_ring_bandwidth_gbps()`` estimate, mirroring how the ICI probe
    scores against ``allreduce_algo_bandwidth_gbps``."""
    if not reports:
        raise DcnProbeError("no rank reports to score")
    rates = [r["gbps"] for r in reports if r.get("gbps") is not None]
    if not rates:  # world=1 sentinel report: no inter-host traffic
        min_gbps = mean_gbps = float("inf")
    else:
        min_gbps = min(rates)
        mean_gbps = sum(rates) / len(rates)
    peak = fraction = None
    if multi is not None:
        peak = multi.dcn_ring_bandwidth_gbps()
        if peak and math.isfinite(peak) and math.isfinite(min_gbps):
            fraction = min_gbps / peak
    return DcnReport(
        world=max(int(r.get("world", 1)) for r in reports),
        mbytes=float(reports[0].get("mbytes", 0.0)),
        iters=int(reports[0].get("iters", 0)),
        min_gbps=round(min_gbps, 3) if math.isfinite(min_gbps) else min_gbps,
        mean_gbps=round(mean_gbps, 3) if math.isfinite(mean_gbps) else mean_gbps,
        peak_estimate_gbps=(round(peak, 3)
                            if peak is not None and math.isfinite(peak)
                            else peak),
        fraction_of_peak=(round(fraction, 4)
                          if fraction is not None else None),
    )


class DcnProbeError(RuntimeError):
    pass


def find_or_build_binary() -> Path:
    """PATH → native/dcn_probe → build from source with g++."""
    on_path = shutil.which("dcn_probe")
    if on_path:
        return Path(on_path)
    binary = NATIVE_DIR / "dcn_probe"
    source = NATIVE_DIR / "dcn_probe.cpp"
    if not source.exists():
        if binary.exists():
            return binary  # binary-only install (trimmed image layer)
        raise DcnProbeError(f"dcn_probe source not found at {source}")
    if binary.exists() and binary.stat().st_mtime >= source.stat().st_mtime:
        return binary
    gxx = shutil.which("g++") or shutil.which("c++")
    if gxx is None:
        raise DcnProbeError("no C++ compiler available to build dcn_probe")
    subprocess.run(
        [gxx, "-O2", "-std=c++17", "-pthread", "-o", str(binary), str(source)],
        check=True,
        capture_output=True,
    )
    return binary


def run_rank(
    rank: int,
    world: int,
    peers: list[str],
    *,
    base_port: int = 19000,
    mbytes: float = 64.0,
    iters: int = 8,
    timeout: float = 120.0,
) -> dict:
    """Run this worker's rank; blocks until the ring completes."""
    binary = find_or_build_binary()
    proc = subprocess.run(
        [
            str(binary),
            "--rank", str(rank),
            "--world", str(world),
            "--peers", ",".join(peers),
            "--base-port", str(base_port),
            "--mbytes", str(mbytes),
            "--iters", str(iters),
        ],
        capture_output=True,
        text=True,
        timeout=timeout,
    )
    if proc.returncode != 0:
        raise DcnProbeError(f"rank {rank} failed: {proc.stderr.strip()}")
    return json.loads(proc.stdout.strip().splitlines()[-1])


def run_local_ring(
    world: int = 2, *, mbytes: float = 32.0, iters: int = 4,
    base_port: int = 19000,
) -> list[dict]:
    """All ranks as local subprocesses (tests / single-host sanity)."""
    binary = find_or_build_binary()
    peers = ["127.0.0.1"] * world
    procs = [
        subprocess.Popen(
            [
                str(binary),
                "--rank", str(rank),
                "--world", str(world),
                "--peers", ",".join(peers),
                "--base-port", str(base_port),
                "--mbytes", str(mbytes),
                "--iters", str(iters),
            ],
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
        )
        for rank in range(world)
    ]
    reports = []
    errors = []
    for rank, proc in enumerate(procs):
        out, err = proc.communicate(timeout=120)
        if proc.returncode != 0:
            errors.append(f"rank {rank}: {err.strip()}")
        else:
            reports.append(json.loads(out.strip().splitlines()[-1]))
    if errors:
        raise DcnProbeError("; ".join(errors))
    return reports


def worker_env_config() -> tuple[int, int, list[str]] | None:
    """(rank, world, peers) from the TPU_WORKER_* env, or None off-slice."""
    hostnames = os.environ.get("TPU_WORKER_HOSTNAMES", "")
    worker_id = os.environ.get("TPU_WORKER_ID", "")
    if not hostnames or not worker_id.isdigit():
        return None
    peers = hostnames.split(",")
    return int(worker_id), len(peers), peers


def slice_env_config() -> tuple[int, int, list[str]] | None:
    """(rank, world, peers) for the CROSS-SLICE ring: one rank per slice
    (worker 0 of each), peers from the KFTPU_SLICE_PEERS env the controller
    bakes into multislice StatefulSets (tpu/topology.py
    MultiSlice.worker_env). This is the path that validates the DCN links
    megascale training rides — run ``python -m kubeflow_tpu.probe`` from
    worker 0 of any slice and the cross-slice ring runs automatically
    (reported as ``dcn_cross_slice``).

    Returns None off-multislice or on a non-zero worker (only worker 0 of
    each slice participates; the others would collide on ports).
    """
    peers = os.environ.get(SLICE_PEERS_ENV, "")
    slice_id = os.environ.get("MEGASCALE_SLICE_ID", "")
    worker_id = os.environ.get("TPU_WORKER_ID", "0")
    if not peers or not slice_id.isdigit() or worker_id != "0":
        return None
    peer_list = peers.split(",")
    return int(slice_id), len(peer_list), peer_list
