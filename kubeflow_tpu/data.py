"""Input pipeline: deterministic sharded loading with device prefetch.

The missing third of the in-notebook training story (models/trainer.py is
the loop, utils/checkpoint.py the persistence; this feeds them). TPU
steps are short — a v5e chip finishes a 200ms train step while a naive
Python loader is still indexing — so the loader's job is to keep host
work off the step's critical path:

- **Deterministic sharding**: one global seeded permutation per epoch;
  process ``p`` of ``P`` takes every ``P``-th batch. Every process
  computes the same permutation locally (no coordination traffic), the
  shards are disjoint by construction, and a given ``(seed, step)``
  always names the same examples — which is what makes checkpoint/resume
  exact (trainer.fit fast-forwards by step count).
- **Static shapes**: the trailing partial batch is dropped, so every
  batch XLA sees has the same shape — no recompiles mid-epoch.
- **Prefetch**: a daemon thread stays ``depth`` batches ahead, so host
  indexing/augmentation overlaps the device step (the TPU equivalent of
  the CUDA-stream prefetch every GPU loader ships).
- **Multi-host assembly**: ``global_batches`` wraps the per-process
  stream with ``jax.make_array_from_process_local_data`` so each process
  feeds only its shard yet the train step sees one global jax.Array laid
  out on the mesh — the input-side complement of the controller's
  ``JAX_PROCESS_ID`` wiring.

Reference parity note: the reference has no data path at all (it is a
control plane; SURVEY.md §2.4); this module is part of the TPU data plane
its notebooks need. The design follows the public grain/tf.data split of
source vs sampler vs prefetch, rebuilt jax-first with stdlib threading.
"""

from __future__ import annotations

import queue
import sys
import threading
import warnings
import weakref
from dataclasses import dataclass
from typing import Any, Callable, Iterator

import numpy as np

__all__ = [
    "ArraySource",
    "ShardedLoader",
    "global_batches",
    "prefetch",
]


class ArraySource:
    """Index-addressable source over aligned arrays (numpy or memmap —
    a memmapped .npy on the workspace PVC streams without loading).

    ``source(idx)`` returns a tuple of ``arr[idx]`` per array."""

    def __init__(self, *arrays: np.ndarray):
        if not arrays:
            raise ValueError("need at least one array")
        n = len(arrays[0])
        if any(len(a) != n for a in arrays):
            raise ValueError("arrays must be index-aligned")
        self.arrays = arrays

    def __len__(self) -> int:
        return len(self.arrays[0])

    def __call__(self, idx: np.ndarray) -> tuple:
        return tuple(a[idx] for a in self.arrays)


@dataclass(frozen=True)
class _Position:
    epoch: int
    batch_in_epoch: int


class ShardedLoader:
    """Deterministic, per-process-sharded, infinitely-repeating batches.

    ``source``: ``len()`` + ``(indices ndarray) -> batch`` (ArraySource or
    any callable with those two). ``process_id``/``num_processes`` default
    to this worker's place in the slice (sdk.SliceInfo), so the same
    notebook code shards correctly from a v5e-4 to a multislice job.

    Iteration order is a pure function of ``(seed, epoch)`` — resuming by
    skipping ``step`` batches (trainer.fit's contract) reproduces the
    exact stream. ``state_dict()``/``load_state_dict()`` snapshot the
    position for loaders driven outside fit().
    """

    def __init__(self, source, batch_size: int, *, seed: int = 0,
                 shuffle: bool = True, process_id: int | None = None,
                 num_processes: int | None = None, transform: Callable | None = None):
        if process_id is None or num_processes is None:
            from kubeflow_tpu.sdk import SliceInfo

            info = SliceInfo.from_env()
            process_id = info.process_id if process_id is None else process_id
            num_processes = (info.num_processes if num_processes is None
                             else num_processes)
        if not (0 <= process_id < num_processes):
            raise ValueError(
                f"process_id {process_id} not in [0, {num_processes})")
        self.source = source
        self.batch_size = batch_size
        self.seed = seed
        self.shuffle = shuffle
        self.process_id = process_id
        self.num_processes = num_processes
        self.transform = transform
        # Whole batches per epoch, then whole batches per process: both
        # remainders dropped so shapes are static and shards symmetric
        # (every process runs the same number of steps — a ragged shard
        # would desync the collective in the train step).
        self.batches_per_epoch = len(source) // batch_size
        self.batches_per_process = self.batches_per_epoch // num_processes
        if self.batches_per_process == 0:
            raise ValueError(
                f"{len(source)} examples < one batch per process "
                f"({batch_size} × {num_processes})")
        self._pos = _Position(0, 0)
        self._order_cache: tuple[int, np.ndarray] | None = None
        # Bumped by every explicit repositioning (skip/load_state_dict).
        # A prefetcher's deferred rewind is only valid against the cursor
        # state it observed; a user skip() in between must win.
        self._cursor_moves = 0
        # Serializes cursor claims across concurrent generators; counts
        # every batch ever pulled (monotonic — never rewound), so a
        # prefetcher can tell whether pulls other than its own happened.
        self._iter_lock = threading.Lock()
        self._total_pulls = 0
        # The prefetcher currently wrapping this loader (weakref). A new
        # prefetch() over the same loader closes the old one FIRST — the
        # re-run-cell rebind `pf = prefetch(ld)` evaluates the RHS before
        # the old pf's __del__, so relying on GC alone would start the new
        # producer on the un-rewound cursor and then yank it back.
        self._active_prefetch: weakref.ref | None = None

    # -- deterministic order -----------------------------------------------------

    def _epoch_order(self, epoch: int) -> np.ndarray:
        if self._order_cache is not None and self._order_cache[0] == epoch:
            return self._order_cache[1]
        n = self.batches_per_epoch * self.batch_size
        if not self.shuffle:
            order = np.arange(n)
        else:
            rng = np.random.default_rng((self.seed, epoch))
            order = rng.permutation(len(self.source))[:n]
        self._order_cache = (epoch, order)
        return order

    def _batch_indices(self, epoch: int, batch_in_epoch: int) -> np.ndarray:
        order = self._epoch_order(epoch)
        start = batch_in_epoch * self.batch_size
        return order[start:start + self.batch_size]

    # -- iteration ----------------------------------------------------------------

    def __iter__(self) -> Iterator:
        while True:
            # Claim the position and advance the cursor atomically, BEFORE
            # the heavy work: concurrent generators (a prefetch producer
            # plus anything else iterating the same loader) must each get
            # a distinct batch — unlocked read-modify-write of _pos loses
            # updates, silently re-yielding or skipping batches. Indexing
            # and transform stay outside the lock so pulls overlap.
            with self._iter_lock:
                claimed = self._pos
                epoch, b = claimed.epoch, claimed.batch_in_epoch
                # Process p takes batches p, p+P, p+2P, … of the global order.
                global_batch = self.process_id + b * self.num_processes
                idx = self._batch_indices(epoch, global_batch)
                if b + 1 >= self.batches_per_process:
                    self._pos = _Position(epoch + 1, 0)
                else:
                    self._pos = _Position(epoch, b + 1)
                self._total_pulls += 1
                my_serial = self._total_pulls
                moves_at_claim = self._cursor_moves
            try:
                batch = self.source(idx)
                if self.transform is not None:
                    batch = self.transform(batch)
            except BaseException:
                # Hand the claim back when nothing else touched the
                # cursor since: a direct reader that catches a transient
                # source/transform error and re-iterates must retry this
                # batch, not silently skip it. With interleaved pulls or
                # an explicit reposition the claim stands (rolling back
                # out of order would corrupt the other reader's stream).
                with self._iter_lock:
                    if (self._total_pulls == my_serial
                            and self._cursor_moves == moves_at_claim):
                        self._pos = claimed
                        self._total_pulls -= 1
                raise
            yield batch

    # -- resume -------------------------------------------------------------------

    def skip(self, n_batches: int, *, detach_wait: float = 60.0) -> None:
        """O(1) fast-forward: position this loader exactly where a fresh
        loader would be after yielding ``n_batches``. The resume path
        that composes with ``prefetch`` — count the steps the *consumer*
        ran (the trainer's step counter) and skip that many; the wrapped
        loader's own cursor runs ahead by the prefetch depth and must not
        be snapshotted.

        ``detach_wait`` bounds the synchronous stall while a live
        prefetch producer wedged in a slow source/transform is waited
        out (default 60s — a checkpoint restore that must not block can
        pass a small value and accept the RuntimeWarning instead)."""
        self._detach_prefetcher(wait=detach_wait)
        epoch, b = divmod(int(n_batches), self.batches_per_process)
        with self._iter_lock:
            # Same lock as the iterator's cursor claim: a foreign
            # reader's read-modify-write must not overwrite this.
            self._pos = _Position(epoch, b)
            self._cursor_moves += 1

    def _detach_prefetcher(self, wait: float = 60.0) -> None:
        """Stop any prefetcher currently producing from this loader. An
        explicit reposition under a live producer is otherwise a race —
        the producer could pull one more batch *after* the new position
        lands, silently shifting the stream by one. Waits out a producer
        wedged in a slow transform (up to ``wait`` seconds past close()'s
        own short join) and retries the deferred rewind it skipped; only
        a producer still running after that gets a RuntimeWarning."""
        prev = (self._active_prefetch()
                if self._active_prefetch is not None else None)
        if prev is None:
            return
        prev.close()
        t = prev._thread
        if t is not None and t.is_alive():
            t.join(timeout=wait)
            if t.is_alive():
                warnings.warn(
                    "a prefetch() over this ShardedLoader is still "
                    "producing after {:.0f}s; the stream may shift — "
                    "close() it explicitly first".format(wait),
                    RuntimeWarning, stacklevel=3)
                return
        # Unconditional: an earlier close() may have skipped the rewind
        # while the producer was still wedged, even if that thread has
        # exited on its own by now. _try_rewind self-guards (once, only
        # with the producer stopped and the cursor untouched).
        prev._try_rewind()
        self._active_prefetch = None

    def _linear(self) -> int:
        """Cursor as a monotonic batch count (epochs never rewind)."""
        return (self._pos.epoch * self.batches_per_process
                + self._pos.batch_in_epoch)

    def rewind(self, n_batches: int, *, detach_wait: float = 60.0) -> None:
        """Move the cursor back ``n_batches`` (floored at the start).
        Used by ``prefetch``'s close path to hand back read-ahead batches
        the consumer never saw, so re-wrapping the same loader resumes
        where the *consumer* stopped — not ``depth+1`` batches later."""
        self.skip(max(0, self._linear() - int(n_batches)),
                  detach_wait=detach_wait)

    def state_dict(self) -> dict:
        """Cursor snapshot — valid only for a directly-iterated loader
        (under ``prefetch`` the cursor includes the producer's read-ahead;
        use ``skip`` with the consumed-step count instead)."""
        pos = self._pos  # single atomic read — no torn epoch/batch pair
        return {"epoch": pos.epoch, "batch_in_epoch": pos.batch_in_epoch}

    def load_state_dict(self, state: dict, *,
                        detach_wait: float = 60.0) -> None:
        self._detach_prefetcher(wait=detach_wait)
        with self._iter_lock:
            self._pos = _Position(int(state["epoch"]),
                                  int(state["batch_in_epoch"]))
            self._cursor_moves += 1


def prefetch(batches: Iterator, *, depth: int = 2,
             to_device: Callable | None = None) -> Iterator:
    """Run the upstream iterator ``depth`` elements ahead on a daemon
    thread, optionally pushing each element to device (``to_device``,
    e.g. a ``jax.device_put`` with the batch sharding) so the transfer
    overlaps the current step. An upstream exception re-raises at the
    consumer's ``next()``. Closing (or garbage-collecting) the returned
    iterator stops the producer — an abandoned pipeline (re-run notebook
    cell) releases its thread and buffered batches instead of pinning
    them for process lifetime.

    Note: the producer reads ahead, so the *upstream* iterator's position
    runs up to ``depth + 1`` elements past what the consumer has seen.
    When ``batches`` is a ``ShardedLoader`` directly, closing (or GC'ing)
    the prefetcher **rewinds** its cursor by the read-ahead the consumer
    never received — so the re-run-a-notebook-cell pattern (re-wrap the
    same loader in a fresh ``prefetch``) resumes exactly where training
    stopped instead of silently dropping ``depth+1`` batches. For any
    other iterator, snapshot resume state from consumed-step counts
    (``ShardedLoader.skip``), not from the wrapped loader's cursor."""
    if depth < 1:
        raise ValueError("depth must be >= 1")
    q: queue.Queue = queue.Queue(maxsize=depth)
    _END = object()
    stop = threading.Event()

    # Rewind support: count every batch pulled from a ShardedLoader (each
    # pull advances its cursor by exactly one) so close() can hand back
    # the produced-but-unconsumed difference.
    rewindable = batches if isinstance(batches, ShardedLoader) else None
    produced = [0]
    if rewindable is not None:
        # Hand off from any previous prefetcher over this loader: detach
        # (close + rewind) it BEFORE our producer starts pulling, so the
        # new stream continues exactly where the old consumer stopped
        # even when the old prefetcher is only dropped by the rebind
        # itself (`pf = prefetch(ld)` evaluates the RHS first).
        rewindable._detach_prefetcher()
        src = iter(batches)

        def counting():
            # Counts SUCCESSFUL pulls only: a failed pull rolls its own
            # cursor claim back inside ShardedLoader.__iter__ (when no
            # other reader interleaved), so it must not count toward the
            # close-time rewind either — the pair keeps
            # `_total_pulls == _start_pulls + produced` exactly.
            while not stop.is_set():
                item = next(src)
                produced[0] += 1
                yield item

        batches = counting()

    def put(item) -> bool:
        """Bounded put that gives up when the consumer is gone."""
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def producer():
        try:
            for item in batches:
                if to_device is not None:
                    item = to_device(item)
                if not put(item):
                    return
        except BaseException as e:  # noqa: BLE001 — relayed to consumer
            put((_END, e))
            return
        put((_END, None))

    thread = threading.Thread(target=producer, daemon=True,
                              name="kftpu-data-prefetch")
    pf = _Prefetcher(q, stop, _END, thread=thread,
                     rewindable=rewindable, produced=produced)
    if rewindable is not None:
        # Snapshots must precede thread.start(): the producer pulls (and
        # moves the cursor) the moment it runs.
        pf._cursor_moves_seen = rewindable._cursor_moves
        pf._start_pulls = rewindable._total_pulls
        rewindable._active_prefetch = weakref.ref(pf)
    thread.start()
    return pf


class _Prefetcher:
    """Consumer half of prefetch(). A real object (not a generator) so
    abandoning the pipeline before the first ``next()`` still releases
    the producer — a never-started generator's ``finally`` never runs,
    but ``__del__``/``close()`` here always do."""

    def __init__(self, q, stop, end, *, thread=None, rewindable=None,
                 produced=None):
        self._q = q
        self._stop = stop
        self._end = end
        self._thread = thread
        self._rewindable = rewindable
        self._produced = produced or [0]
        self._consumed = 0
        self._cursor_moves_seen = 0
        self._start_pulls = 0
        self._rewound = False
        self._closed = False
        self._done = False

    def __iter__(self):
        return self

    def __next__(self):
        if self._done:
            raise StopIteration
        item = self._q.get()
        if (isinstance(item, tuple) and len(item) == 2
                and item[0] is self._end):
            self._done = True
            self._stop.set()
            if item[1] is not None:
                raise item[1]
            raise StopIteration
        self._consumed += 1
        return item

    def close(self):
        if self._closed:
            return
        self._closed = True
        self._done = True
        self._stop.set()
        if self._rewindable is None:
            return
        if sys is None or sys.is_finalizing():
            # Interpreter teardown (final GC runs __del__): threading
            # internals are already gone — joining would raise inside
            # teardown, and a rewind is pointless with the process dying.
            return
        # Hand the read-ahead back: the producer stops within one put
        # timeout of the stop flag; once it has, produced-consumed is
        # exactly the batches the loader's cursor ran past the consumer.
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            if self._thread.is_alive():
                # _try_rewind will refuse below; say so — a user who next
                # iterates the loader DIRECTLY (no re-wrap, so no detach
                # retry) would otherwise silently lose the read-ahead.
                warnings.warn(
                    "prefetch producer still running after close(); the "
                    "loader cursor stays ahead by the read-ahead until a "
                    "re-wrap in prefetch() retries the hand-back",
                    RuntimeWarning, stacklevel=2)
        self._try_rewind()

    def _try_rewind(self):
        """Rewind the loader by the read-ahead, once, and only while it
        is safe: the producer must be stopped (a live one could still
        pull) and the cursor untouched since this prefetcher started
        (skip/load_state_dict — a checkpoint resume — wins over a
        relative rewind). Retried by _detach_prefetcher after it waits
        out a producer close() gave up on."""
        if self._rewound:
            return
        if self._thread is not None and self._thread.is_alive():
            return
        if self._rewindable._cursor_moves != self._cursor_moves_seen:
            return
        if (self._rewindable._total_pulls
                != self._start_pulls + self._produced[0]):
            # Pulls beyond our own happened: something else has been
            # iterating the loader (e.g. it was re-wrapped as
            # prefetch(iter(ld)) — an iterator, so the handoff couldn't
            # see it). Rewinding under a foreign reader would re-deliver
            # batches it already produced.
            return
        self._rewound = True
        over = self._produced[0] - self._consumed
        if over > 0:
            self._rewindable.rewind(over)

    def __del__(self):
        try:
            self.close()
        except Exception:  # kftpu: ignore[exception-swallow] destructor during interpreter teardown — logging/metrics may already be torn down and raising is fatal
            pass


def global_batches(batches: Iterator, mesh, spec) -> Iterator:
    """Assemble each process's local batch into one global ``jax.Array``
    laid out as ``spec`` on ``mesh`` (``jax.make_array_from_process_local_
    data``). Single-process: a plain ``device_put`` with the same
    sharding, so notebook code is identical at every scale."""
    import jax
    from jax.sharding import NamedSharding

    sharding = NamedSharding(mesh, spec)

    def to_global(x):
        if jax.process_count() == 1:
            return jax.device_put(x, sharding)
        return jax.make_array_from_process_local_data(sharding, np.asarray(x))

    for batch in batches:
        yield jax.tree.map(to_global, batch)
