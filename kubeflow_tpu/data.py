"""Input pipeline: deterministic sharded loading with device prefetch.

The missing third of the in-notebook training story (models/trainer.py is
the loop, utils/checkpoint.py the persistence; this feeds them). TPU
steps are short — a v5e chip finishes a 200ms train step while a naive
Python loader is still indexing — so the loader's job is to keep host
work off the step's critical path:

- **Deterministic sharding**: one global seeded permutation per epoch;
  process ``p`` of ``P`` takes every ``P``-th batch. Every process
  computes the same permutation locally (no coordination traffic), the
  shards are disjoint by construction, and a given ``(seed, step)``
  always names the same examples — which is what makes checkpoint/resume
  exact (trainer.fit fast-forwards by step count).
- **Static shapes**: the trailing partial batch is dropped, so every
  batch XLA sees has the same shape — no recompiles mid-epoch.
- **Prefetch**: a daemon thread stays ``depth`` batches ahead, so host
  indexing/augmentation overlaps the device step (the TPU equivalent of
  the CUDA-stream prefetch every GPU loader ships).
- **Multi-host assembly**: ``global_batches`` wraps the per-process
  stream with ``jax.make_array_from_process_local_data`` so each process
  feeds only its shard yet the train step sees one global jax.Array laid
  out on the mesh — the input-side complement of the controller's
  ``JAX_PROCESS_ID`` wiring.

Reference parity note: the reference has no data path at all (it is a
control plane; SURVEY.md §2.4); this module is part of the TPU data plane
its notebooks need. The design follows the public grain/tf.data split of
source vs sampler vs prefetch, rebuilt jax-first with stdlib threading.
"""

from __future__ import annotations

import queue
import threading
from dataclasses import dataclass
from typing import Any, Callable, Iterator

import numpy as np

__all__ = [
    "ArraySource",
    "ShardedLoader",
    "global_batches",
    "prefetch",
]


class ArraySource:
    """Index-addressable source over aligned arrays (numpy or memmap —
    a memmapped .npy on the workspace PVC streams without loading).

    ``source(idx)`` returns a tuple of ``arr[idx]`` per array."""

    def __init__(self, *arrays: np.ndarray):
        if not arrays:
            raise ValueError("need at least one array")
        n = len(arrays[0])
        if any(len(a) != n for a in arrays):
            raise ValueError("arrays must be index-aligned")
        self.arrays = arrays

    def __len__(self) -> int:
        return len(self.arrays[0])

    def __call__(self, idx: np.ndarray) -> tuple:
        return tuple(a[idx] for a in self.arrays)


@dataclass(frozen=True)
class _Position:
    epoch: int
    batch_in_epoch: int


class ShardedLoader:
    """Deterministic, per-process-sharded, infinitely-repeating batches.

    ``source``: ``len()`` + ``(indices ndarray) -> batch`` (ArraySource or
    any callable with those two). ``process_id``/``num_processes`` default
    to this worker's place in the slice (sdk.SliceInfo), so the same
    notebook code shards correctly from a v5e-4 to a multislice job.

    Iteration order is a pure function of ``(seed, epoch)`` — resuming by
    skipping ``step`` batches (trainer.fit's contract) reproduces the
    exact stream. ``state_dict()``/``load_state_dict()`` snapshot the
    position for loaders driven outside fit().
    """

    def __init__(self, source, batch_size: int, *, seed: int = 0,
                 shuffle: bool = True, process_id: int | None = None,
                 num_processes: int | None = None, transform: Callable | None = None):
        if process_id is None or num_processes is None:
            from kubeflow_tpu.sdk import SliceInfo

            info = SliceInfo.from_env()
            process_id = info.process_id if process_id is None else process_id
            num_processes = (info.num_processes if num_processes is None
                             else num_processes)
        if not (0 <= process_id < num_processes):
            raise ValueError(
                f"process_id {process_id} not in [0, {num_processes})")
        self.source = source
        self.batch_size = batch_size
        self.seed = seed
        self.shuffle = shuffle
        self.process_id = process_id
        self.num_processes = num_processes
        self.transform = transform
        # Whole batches per epoch, then whole batches per process: both
        # remainders dropped so shapes are static and shards symmetric
        # (every process runs the same number of steps — a ragged shard
        # would desync the collective in the train step).
        self.batches_per_epoch = len(source) // batch_size
        self.batches_per_process = self.batches_per_epoch // num_processes
        if self.batches_per_process == 0:
            raise ValueError(
                f"{len(source)} examples < one batch per process "
                f"({batch_size} × {num_processes})")
        self._pos = _Position(0, 0)
        self._order_cache: tuple[int, np.ndarray] | None = None

    # -- deterministic order -----------------------------------------------------

    def _epoch_order(self, epoch: int) -> np.ndarray:
        if self._order_cache is not None and self._order_cache[0] == epoch:
            return self._order_cache[1]
        n = self.batches_per_epoch * self.batch_size
        if not self.shuffle:
            order = np.arange(n)
        else:
            rng = np.random.default_rng((self.seed, epoch))
            order = rng.permutation(len(self.source))[:n]
        self._order_cache = (epoch, order)
        return order

    def _batch_indices(self, epoch: int, batch_in_epoch: int) -> np.ndarray:
        order = self._epoch_order(epoch)
        start = batch_in_epoch * self.batch_size
        return order[start:start + self.batch_size]

    # -- iteration ----------------------------------------------------------------

    def __iter__(self) -> Iterator:
        while True:
            epoch, b = self._pos.epoch, self._pos.batch_in_epoch
            # Process p takes batches p, p+P, p+2P, … of the global order.
            global_batch = self.process_id + b * self.num_processes
            batch = self.source(self._batch_indices(epoch, global_batch))
            if self.transform is not None:
                batch = self.transform(batch)
            if b + 1 >= self.batches_per_process:
                self._pos = _Position(epoch + 1, 0)
            else:
                self._pos = _Position(epoch, b + 1)
            yield batch

    # -- resume -------------------------------------------------------------------

    def skip(self, n_batches: int) -> None:
        """O(1) fast-forward: position this loader exactly where a fresh
        loader would be after yielding ``n_batches``. The resume path
        that composes with ``prefetch`` — count the steps the *consumer*
        ran (the trainer's step counter) and skip that many; the wrapped
        loader's own cursor runs ahead by the prefetch depth and must not
        be snapshotted."""
        epoch, b = divmod(int(n_batches), self.batches_per_process)
        self._pos = _Position(epoch, b)

    def state_dict(self) -> dict:
        """Cursor snapshot — valid only for a directly-iterated loader
        (under ``prefetch`` the cursor includes the producer's read-ahead;
        use ``skip`` with the consumed-step count instead)."""
        return {"epoch": self._pos.epoch,
                "batch_in_epoch": self._pos.batch_in_epoch}

    def load_state_dict(self, state: dict) -> None:
        self._pos = _Position(int(state["epoch"]),
                              int(state["batch_in_epoch"]))


def prefetch(batches: Iterator, *, depth: int = 2,
             to_device: Callable | None = None) -> Iterator:
    """Run the upstream iterator ``depth`` elements ahead on a daemon
    thread, optionally pushing each element to device (``to_device``,
    e.g. a ``jax.device_put`` with the batch sharding) so the transfer
    overlaps the current step. An upstream exception re-raises at the
    consumer's ``next()``. Closing (or garbage-collecting) the returned
    iterator stops the producer — an abandoned pipeline (re-run notebook
    cell) releases its thread and buffered batches instead of pinning
    them for process lifetime.

    Note: the producer reads ahead, so the *upstream* iterator's position
    runs up to ``depth + 1`` elements past what the consumer has seen —
    snapshot resume state from consumed-step counts
    (``ShardedLoader.skip``), not from the wrapped loader's cursor."""
    if depth < 1:
        raise ValueError("depth must be >= 1")
    q: queue.Queue = queue.Queue(maxsize=depth)
    _END = object()
    stop = threading.Event()

    def put(item) -> bool:
        """Bounded put that gives up when the consumer is gone."""
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def producer():
        try:
            for item in batches:
                if to_device is not None:
                    item = to_device(item)
                if not put(item):
                    return
        except BaseException as e:  # noqa: BLE001 — relayed to consumer
            put((_END, e))
            return
        put((_END, None))

    threading.Thread(target=producer, daemon=True,
                     name="kftpu-data-prefetch").start()
    return _Prefetcher(q, stop, _END)


class _Prefetcher:
    """Consumer half of prefetch(). A real object (not a generator) so
    abandoning the pipeline before the first ``next()`` still releases
    the producer — a never-started generator's ``finally`` never runs,
    but ``__del__``/``close()`` here always do."""

    def __init__(self, q, stop, end):
        self._q = q
        self._stop = stop
        self._end = end
        self._done = False

    def __iter__(self):
        return self

    def __next__(self):
        if self._done:
            raise StopIteration
        item = self._q.get()
        if (isinstance(item, tuple) and len(item) == 2
                and item[0] is self._end):
            self._done = True
            self._stop.set()
            if item[1] is not None:
                raise item[1]
            raise StopIteration
        return item

    def close(self):
        self._done = True
        self._stop.set()

    __del__ = close


def global_batches(batches: Iterator, mesh, spec) -> Iterator:
    """Assemble each process's local batch into one global ``jax.Array``
    laid out as ``spec`` on ``mesh`` (``jax.make_array_from_process_local_
    data``). Single-process: a plain ``device_put`` with the same
    sharding, so notebook code is identical at every scale."""
    import jax
    from jax.sharding import NamedSharding

    sharding = NamedSharding(mesh, spec)

    def to_global(x):
        if jax.process_count() == 1:
            return jax.device_put(x, sharding)
        return jax.make_array_from_process_local_data(sharding, np.asarray(x))

    for batch in batches:
        yield jax.tree.map(to_global, batch)
