"""Profile CRD semantics (multi-tenancy).

Reference: ``profile-controller/api/v1/profile_types.go:36-69`` — a
cluster-scoped Profile owns one namespace; spec carries the owner subject,
an optional ResourceQuotaSpec, and a list of cloud plugins.

TPU-native addition: ``spec.tpuQuota`` — a simple chip-count ceiling that the
controller materialises as ``requests.google.com/tpu`` in the namespace's
ResourceQuota (SURVEY.md §2.4: quota on TPU chips replaces GPU quota).
"""

from __future__ import annotations

from kubeflow_tpu.api import keys
from kubeflow_tpu.runtime.errors import Invalid
from kubeflow_tpu.runtime.objects import deep_get, name_of
from kubeflow_tpu.tpu.topology import TPU_RESOURCE

KIND = "Profile"
API_VERSION = keys.API_V1

# Version lineage, mirroring the reference which serves Profile at v1
# (storage) and v1beta1 with structurally identical schemas
# (profile-controller/api/{v1,v1beta1}/profile_types.go differ only in
# package name and kubebuilder markers).
STORAGE_API_VERSION = API_VERSION
SERVED_API_VERSIONS = (
    keys.API_V1,
    keys.API_V1BETA1,
)


def convert(profile: dict, to_api_version: str) -> dict:
    """Convert a Profile between served versions (identity rewrite — see
    kubeflow_tpu.api.convert for why)."""
    from kubeflow_tpu.api.convert import identity_convert

    return identity_convert(profile, to_api_version,
                            served=SERVED_API_VERSIONS,
                            storage=STORAGE_API_VERSION, kind=KIND)

# Condition types (profile_types.go:47-51)
SUCCEED = "Successful"
FAILED = "Failed"
UNKNOWN = "Unknown"

OWNER_ANNOTATION = "owner"
QUOTA_NAME = "kf-resource-quota"  # profile_controller.go:253-280
TPU_QUOTA_KEY = f"requests.{TPU_RESOURCE}"


def new(
    name: str,
    owner: str,
    *,
    owner_kind: str = "User",
    tpu_quota: int | None = None,
    resource_quota: dict | None = None,
    plugins: list[dict] | None = None,
) -> dict:
    spec: dict = {"owner": {"kind": owner_kind, "name": owner}}
    if tpu_quota is not None:
        spec["tpuQuota"] = tpu_quota
    if resource_quota:
        spec["resourceQuotaSpec"] = resource_quota
    if plugins:
        spec["plugins"] = plugins
    return {
        "apiVersion": API_VERSION,
        "kind": KIND,
        "metadata": {"name": name},
        "spec": spec,
    }


def owner_of(profile: dict) -> dict:
    return deep_get(profile, "spec", "owner", default={}) or {}


def quota_spec_of(profile: dict) -> dict | None:
    """Effective ResourceQuotaSpec: explicit spec merged with tpuQuota."""
    quota = deep_get(profile, "spec", "resourceQuotaSpec")
    tpu_quota = deep_get(profile, "spec", "tpuQuota")
    if tpu_quota is None:
        return quota
    quota = dict(quota or {})
    hard = dict(quota.get("hard") or {})
    hard[TPU_QUOTA_KEY] = str(tpu_quota)
    quota["hard"] = hard
    return quota


def validate(profile: dict) -> None:
    name = name_of(profile)
    if not name:
        raise Invalid("Profile: metadata.name is required")
    owner = owner_of(profile)
    if not owner.get("name"):
        raise Invalid(f"Profile {name}: spec.owner.name is required")
    tpu_quota = deep_get(profile, "spec", "tpuQuota")
    if tpu_quota is not None and (not isinstance(tpu_quota, int) or tpu_quota < 0):
        raise Invalid(f"Profile {name}: spec.tpuQuota must be a non-negative integer")
